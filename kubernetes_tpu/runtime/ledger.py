"""Decision ledger: durable per-cycle decision record + offline replay.

PR 5's flight recorder answers *when* a cycle was slow; this module
answers *why a pod landed where it did* and *which predicate rejected
every node* — and makes both replayable.  Three pieces:

  * `DecisionLedger`: an opt-in, bounded, append-only record of every
    scheduling cycle's INPUTS (the host snapshot as a delta against the
    previously recorded one, the encoded pod batch / ports / nominated /
    in-batch-affinity tensors, the extender/framework extra mask+score,
    the selectHost rotation base) and OUTCOMES (winners, engine kind,
    tier, fault class/attempts, degraded flag, trace id).  Recording is
    off the hot path: `record_cycle` is an O(1) ring append plus a
    non-blocking enqueue to a persistent writer thread (the fetch/
    bind-tail worker pattern) that serializes and appends length-prefixed
    npz blocks to one file.  Bounded twice — a full writer queue DROPS
    the record (never blocks a cycle) and `max_cycles` caps the file.

  * an in-memory decisions ring served at `GET /debug/decisions` (health
    server + apiserver), each entry cross-linked to /debug/traces by the
    cycle's trace id.

  * `replay(path)`: reconstructs each recorded cycle's snapshot by
    folding the deltas (codec/transfer.apply_snapshot_delta), re-executes
    it through a freshly built engine, and compares winners bit-for-bit.
    Replaying through the RECORDED engine is deterministic (the
    bit-identity gate CI pins, fault-injected recordings included);
    cross-engine replay is a comparison tool — the engines match
    one-at-a-time semantics, but argmax-tie rotation can pick different
    winners on tie-heavy workloads.  This is the substrate ROADMAP item
    4's weight-tuning loop re-scores against: same records, different
    weight vector, evaluate the counterfactual placements.

File format: `u64le length + npz` blocks; block 0 is the header (engine
config as JSON under `__meta__`), every later block one cycle.
"""

from __future__ import annotations

import dataclasses
import io
import json
import struct
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.codec.schema import (
    ClusterTensors,
    FilterConfig,
    ScoreConfig,
    reason_message,
    reason_name,
)
from kubernetes_tpu.codec.transfer import apply_snapshot_delta, snapshot_delta
from kubernetes_tpu.utils import klog
from kubernetes_tpu.utils import metrics as m

_LEN = struct.Struct("<Q")

# hard ceiling for one /debug/* response body; the handlers halve their
# entry limit until the rendered JSON fits (a long-lived ring must never
# produce an unbounded response)
MAX_DEBUG_BODY_BYTES = 4 << 20


# ------------------------------------------------------------ explain

def explain_unschedulable(counts) -> Tuple[str, str]:
    """Attribution reason counts (i32[NUM_REASONS]) -> (dominant plugin
    name, kubectl-describe-parity message):

        0/5000 nodes are available: 4987 Insufficient resources,
        13 node(s) had taints that the pod didn't tolerate.
    """
    counts = np.asarray(counts)
    total = int(counts.sum())
    order = np.argsort(-counts, kind="stable")
    parts = [
        f"{int(counts[k])} {reason_message(int(k))}"
        for k in order if counts[k] > 0
    ]
    dominant = reason_name(int(order[0])) if parts else ""
    msg = f"0/{total} nodes are available"
    if parts:
        msg += ": " + ", ".join(parts)
    return dominant, msg + "."


# ------------------------------------------------- pytree (de)serialization

def _component_fields(obj) -> List[str]:
    if dataclasses.is_dataclass(obj):
        return [f.name for f in dataclasses.fields(obj)]
    return list(obj._fields)  # NamedTuple


def _pack_component(out: Dict[str, np.ndarray], prefix: str, obj) -> None:
    for fname in _component_fields(obj):
        out[f"{prefix}.{fname}"] = np.asarray(getattr(obj, fname))


def _unpack_component(z, prefix: str, cls):
    if dataclasses.is_dataclass(cls):
        names = [f.name for f in dataclasses.fields(cls)]
    else:
        names = list(cls._fields)
    return cls(**{n: z[f"{prefix}.{n}"] for n in names})


def _tuplify(x):
    if isinstance(x, list):
        return tuple(_tuplify(v) for v in x)
    return x


def engine_meta(cfg: FilterConfig, weights, unsched_taint_key: int,
                zone_key_id: int, score_cfg: Optional[ScoreConfig],
                percentage_of_nodes_to_score: int, engine: str) -> dict:
    """JSON-serializable engine identity for the ledger header — enough
    to rebuild a bit-identical engine in a fresh process (interner ids in
    the recorded tensors already agree with these key ids)."""
    return {
        "version": 1,
        "engine": engine,
        "filter_config": dataclasses.asdict(cfg),
        "weights": (
            [float(w) for w in np.asarray(weights, np.float32)]
            if weights is not None else None
        ),
        "unsched_taint_key": int(unsched_taint_key),
        "zone_key_id": int(zone_key_id),
        "score_cfg": (
            dataclasses.asdict(score_cfg) if score_cfg is not None else None
        ),
        "percentage_of_nodes_to_score": int(percentage_of_nodes_to_score),
    }


def build_replay_fn(header: dict, engine: Optional[str] = None):
    """Rebuild the recorded engine (or the other one — placements are
    pinned bit-identical across engines) from a ledger header."""
    fc = {k: _tuplify(v) for k, v in header["filter_config"].items()}
    if fc.get("enabled") is not None:
        fc["enabled"] = tuple(fc["enabled"])
    cfg = FilterConfig(**fc)
    sc = header.get("score_cfg")
    score_cfg = (
        ScoreConfig(**{k: _tuplify(v) for k, v in sc.items()})
        if sc is not None else None
    )
    kind = engine or header.get("engine", "speculative")
    if kind == "speculative":
        from kubernetes_tpu.models.speculative import (
            make_speculative_scheduler as maker,
        )
    else:
        from kubernetes_tpu.models.batched import (
            make_sequential_scheduler as maker,
        )
    return maker(
        cfg=cfg,
        weights=header.get("weights"),
        unsched_taint_key=header["unsched_taint_key"],
        zone_key_id=header["zone_key_id"],
        score_cfg=score_cfg,
        percentage_of_nodes_to_score=header.get(
            "percentage_of_nodes_to_score", 100
        ),
    )


# ------------------------------------------------------------- the ledger

class DecisionLedger:
    """Bounded append-only cycle record + in-memory decisions ring.

    `path=None` keeps the ring (the /debug/decisions source) without
    touching disk.  Scope: plain scheduling cycles (both tiers, both
    engines, degraded included) — gang launches and preemption what-ifs
    have their own device paths and are not recorded.  Thread-safety:
    record_cycle is called from the scheduling thread only; the writer
    thread owns the file and the delta-base snapshot; readers (HTTP
    handlers) take the ring lock."""

    def __init__(
        self,
        path: Optional[str] = None,
        max_cycles: int = 4096,
        ring_capacity: int = 256,
        queue_capacity: int = 64,
        meta: Optional[dict] = None,
    ):
        self.path = path
        self.max_cycles = int(max_cycles)
        self.meta = dict(meta or {})
        self._ring: "deque[dict]" = deque(maxlen=max(1, int(ring_capacity)))
        self._lock = threading.Lock()
        self.cycles_total = 0     # records accepted (ring + file intent)
        self.bytes_total = 0      # bytes appended to the file
        self.dropped_total = 0    # queue-full or max_cycles drops
        self._written = 0
        self._busy = False
        self._q: Optional["deque"] = None
        self._cv: Optional[threading.Condition] = None
        self._queue_capacity = max(1, int(queue_capacity))
        self._prev_snap: Optional[ClusterTensors] = None
        self._header_written = False
        self._closed = False
        if path:
            # fresh file per ledger session: the delta chain starts at a
            # full snapshot, so stale blocks from an older run would not
            # reconstruct
            open(path, "wb").close()
            self._cv = threading.Condition()
            self._q = deque()
            t = threading.Thread(
                target=self._writer_loop, name="ktpu-ledger", daemon=True
            )
            t.start()
            self._thread = t

    def ensure_meta(self, meta: dict) -> None:
        """Fill the header meta lazily (the Scheduler calls this with its
        engine identity); first writer-thread record freezes it."""
        if not self._header_written and not self.meta:
            self.meta = dict(meta)

    # ------------------------------------------------------------ record

    def record_cycle(self, inputs: dict, outcome: dict,
                     decisions: List[dict]) -> bool:
        """O(1) hot-path submit: ring append + non-blocking enqueue.
        `inputs` holds the cycle's tensors (cluster/batch/ports/nominated/
        aff_state/extra_mask/extra_score/last_index0), `outcome` the JSON
        facts (cycle/tier/engine/winners/pods/...), `decisions` the
        per-pod ring entries.  Returns False when the record was dropped
        (queue full or max_cycles reached)."""
        with self._lock:
            entry = {
                "cycle": outcome.get("cycle"),
                "trace_id": outcome.get("trace_id", ""),
                "tier": outcome.get("tier", ""),
                "engine": outcome.get("engine", ""),
                "degraded": bool(outcome.get("degraded", False)),
                "time": time.time(),
                "pods": decisions,
                # (k, K) when the cycle was sub-batch k of a K-deep
                # megacycle launch (ISSUE 12) — /debug/decisions readers
                # can join the K blocks of one launch
                **({"mega": outcome["mega"]}
                   if outcome.get("mega") is not None else {}),
                # queue-sharded replicas (ISSUE 14): dispatching replica
                # + reconciler commit sequence, so /debug/decisions
                # readers can reconstruct the cross-replica interleaving
                **({"replica": outcome["replica"]}
                   if outcome.get("replica") is not None else {}),
                **({"seq": outcome["seq"]}
                   if outcome.get("seq") is not None else {}),
            }
            self._ring.append(entry)
            self.cycles_total += 1
        m.LEDGER_CYCLES.inc()
        if self._q is None:
            return True
        if self._written + len(self._q) >= self.max_cycles:
            self._drop()
            return False
        with self._cv:
            if len(self._q) >= self._queue_capacity:
                self._drop()
                return False
            self._q.append((inputs, outcome))
            self._cv.notify()
        return True

    def _drop(self) -> None:
        with self._lock:
            self.dropped_total += 1
        m.LEDGER_DROPPED.inc()

    def record_event(self, entry: dict) -> None:
        """Ring-only append of a non-cycle event (ISSUE 19: autoscaler
        actuations) so /debug/decisions interleaves scale events with
        the scheduling cycles they bracket.  Never touches the binary
        file — the authoritative actuation record is the autoscaler's
        own JSONL ledger; this is the observability mirror."""
        with self._lock:
            self._ring.append(dict(entry))

    def decisions(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._ring)
        if limit is not None and limit >= 0:
            out = out[-limit:] if limit else []
        return out

    # ------------------------------------------------------------ writer

    def _writer_loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(0.1)
                if not self._q:
                    if self._closed:
                        return
                    continue
                inputs, outcome = self._q.popleft()
                self._busy = True
            try:
                if self._written >= self.max_cycles:
                    # authoritative cap check (the submit-side check is
                    # a cheap racy early-out): the file never exceeds
                    # max_cycles records
                    self._drop()
                    continue
                self._write_record(inputs, outcome)
            except Exception as e:  # noqa: BLE001 — never kill the loop
                klog.errorf("ledger write failed: %s", e)
                self._drop()
                # the delta base may be out of sync with the file now;
                # force the next record full
                self._prev_snap = None
            finally:
                self._busy = False

    def _serialize(self, inputs: dict, outcome: dict) -> bytes:
        arrays: Dict[str, np.ndarray] = {}
        meta = dict(outcome)
        cluster = inputs["cluster"]
        delta = snapshot_delta(self._prev_snap, cluster)
        for name, d in delta.items():
            if d[0] == "full":
                arrays[f"snap.full.{name}"] = np.asarray(d[1])
            else:
                arrays[f"snap.rows.{name}.idx"] = d[1]
                arrays[f"snap.rows.{name}.val"] = np.asarray(d[2])
        _pack_component(arrays, "batch", inputs["batch"])
        _pack_component(arrays, "ports", inputs["ports"])
        present = {}
        for key, prefix in (("nominated", "nom"), ("aff_state", "aff")):
            obj = inputs.get(key)
            present[key] = obj is not None
            if obj is not None:
                _pack_component(arrays, prefix, obj)
        for key in ("extra_mask", "extra_score"):
            arr = inputs.get(key)
            present[key] = arr is not None
            if arr is not None:
                arrays[key] = np.asarray(arr)
        arrays["winners"] = np.asarray(outcome["winners"], np.int32)
        meta.pop("winners", None)
        # optional quality top-k (ISSUE 13): the winner-pinned ranking +
        # feasible counts ride the block so bench --replay can recompute
        # margins offline without re-running a quality-enabled engine
        for key, dtype in (
            ("quality_top_nodes", np.int32),
            ("quality_top_scores", np.float32),
            ("quality_feasible", np.int32),
        ):
            arr = outcome.get(key)
            present[key] = arr is not None
            if arr is not None:
                arrays[key] = np.asarray(arr, dtype)
            meta.pop(key, None)
        meta["present"] = present
        meta["last_index0"] = int(inputs["last_index0"])
        arrays["__meta__"] = np.frombuffer(
            json.dumps({"kind": "cycle", **meta}).encode(), np.uint8
        )
        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        self._prev_snap = cluster
        return buf.getvalue()

    def _write_record(self, inputs: dict, outcome: dict) -> None:
        blocks = []
        if not self._header_written:
            hdr = io.BytesIO()
            np.savez_compressed(hdr, __meta__=np.frombuffer(
                json.dumps({"kind": "header", **self.meta}).encode(),
                np.uint8,
            ))
            blocks.append(hdr.getvalue())
        blocks.append(self._serialize(inputs, outcome))
        with open(self.path, "ab") as f:
            for b in blocks:
                f.write(_LEN.pack(len(b)))
                f.write(b)
        # only after the write landed: a failed first write must retry
        # the header with the next record, or the file never reconstructs
        self._header_written = True
        n = sum(len(b) + _LEN.size for b in blocks)
        with self._lock:
            self.bytes_total += n
            self._written += 1
        m.LEDGER_BYTES.inc(n)

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Wait for every enqueued record to reach the file (tests /
        bench exit).  True when drained."""
        if self._q is None:
            return True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                if not self._q and not self._busy:
                    return True
            time.sleep(0.002)
        return False

    def close(self, timeout_s: float = 10.0) -> None:
        self.flush(timeout_s)
        self._closed = True
        if self._cv is not None:
            with self._cv:
                self._cv.notify_all()


# process-wide default: the ring /debug/decisions serves when no
# instance was wired explicitly.  A Scheduler configured with
# decision_ledger=True installs its ledger here unless one was
# injected.  Replicas normally SHARE one ledger (replica id + commit
# seq in every block), so the registry usually holds one instance under
# several ids (runtime/defaults.py ProcessDefault).
from kubernetes_tpu.runtime.defaults import ProcessDefault  # noqa: E402

_DEFAULT = ProcessDefault("ledger", DecisionLedger)


def get_default() -> DecisionLedger:
    return _DEFAULT.get()


def set_default(ledger: DecisionLedger, replica: int = 0) -> None:
    _DEFAULT.set(ledger, replica)


def replica_instances() -> dict:
    """{replica id: DecisionLedger} of every install this process saw."""
    return _DEFAULT.replicas()


def __getattr__(name):  # legacy alias: ledger.LEDGER
    if name == "LEDGER":
        return _DEFAULT.get()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def bounded_json(render, limit: Optional[int],
                 cap: int = MAX_DEBUG_BODY_BYTES) -> bytes:
    """Render `render(limit) -> jsonable` and enforce the hard
    response-size cap by halving the entry limit until the body fits;
    if even one entry exceeds the cap, a tiny well-formed error body is
    served instead of truncated JSON."""
    lim = limit
    while True:
        body = json.dumps(render(lim)).encode()
        if len(body) <= cap:
            return body
        if lim == 1:
            return json.dumps(
                {"truncated": True,
                 "error": "single entry exceeds the response-size cap"}
            ).encode()
        # over cap: halve, seeding from a generous default when the
        # caller asked for everything
        lim = max(1, (lim if lim is not None else 4096) // 2)


def debug_query_limit(query: str) -> Optional[int]:
    """?limit=N from a raw query string (None = unbounded request)."""
    from urllib.parse import parse_qs

    try:
        v = parse_qs(query).get("limit")
        return max(0, int(v[0])) if v else None
    except (ValueError, TypeError):
        return None


def debug_body(render, query: str = "",
               cap: int = MAX_DEBUG_BODY_BYTES) -> bytes:
    """Shared /debug/* body builder (health server + apiserver):
    `render(limit) -> jsonable` (zero-arg callables tolerated — the cap
    then falls back to serving their full body or the error stub)."""
    limit = debug_query_limit(query)

    def _render(lim):
        try:
            return render(lim)
        except TypeError:
            return render()

    return bounded_json(_render, limit, cap)


# the debug surface, one line per endpoint — served at GET /debug/ by
# both the health server and the apiserver (inflight-exempt like its
# peers) so an operator can discover the whole family from any one URL
DEBUG_ENDPOINTS = {
    "/debug/traces": (
        "flight-recorder cycle spans + postmortems as Chrome "
        "trace-event JSON (Perfetto-loadable; ?limit=N)"
    ),
    "/debug/decisions": (
        "recent decision-ledger entries: per-pod winners + dominant "
        "rejection reasons, trace-id cross-linked (?limit=N)"
    ),
    "/debug/cluster": (
        "telemetry time series: cluster analytics, HBM, compile facts, "
        "SLO burn rates (?limit=N)"
    ),
    "/debug/perf": (
        "performance observatory: host/device cycle split, phase x "
        "width EWMA matrix, transfer byte accounting, profiler status "
        "(?limit=N)"
    ),
    "/debug/profile": (
        "start a bounded on-demand jax.profiler capture "
        "(?seconds=N; throttled, no-op where unsupported)"
    ),
    "/debug/quality": (
        "placement-quality observatory: winner margins, feasible "
        "counts, FFD-counterfactual regret, packing-drift detectors "
        "(?limit=N)"
    ),
    "/debug/replicas": (
        "queue-sharded scheduler replicas: per-replica cycle/conflict "
        "facts, the sequenced reconciler's stats, and the per-namespace "
        "usage/quota table (?limit=N bounds the tenant table)"
    ),
    "/debug/capacity": (
        "capacity planner: class-compressed what-if binpack of the "
        "pending backlog — scale-up/scale-down recommendation, "
        "compression/absorption/overflow facts (?limit=N)"
    ),
    "/debug/autoscaler": (
        "guarded autoscaler actuation: managed fleet, hysteresis "
        "streaks, cooldown window, cost (node-seconds), recent "
        "actuation records (?limit=N)"
    ),
    "/debug/capacity/enact": (
        "POST: run one guarded actuation round NOW against the live "
        "capacity plan (?dryRun=1 decides + records without mutating)"
    ),
    "/debug/timeline": (
        "metrics timeline store: sampled series over every registered "
        "family + typed event annotations + anomaly firings "
        "(?series=a,b* ?window=S ?step=S ?limit=N)"
    ),
}


def debug_index() -> dict:
    """GET /debug/ body: every debug endpoint with a one-line
    description."""
    return {"endpoints": dict(DEBUG_ENDPOINTS)}


# --------------------------------------------------- shared debug routing
# ONE table drives BOTH servers (ISSUE 20 satellite): the health server
# and the apiserver used to hand-code parallel if/elif chains over the
# same endpoints, so a new endpoint could be exposed on one and
# forgotten on the other.  Every GET /debug/* now routes through
# debug_dispatch() and every debug POST through debug_post(); the
# renderer table's keys are asserted against DEBUG_ENDPOINTS at import,
# so an endpoint cannot be listed without a handler or vice versa.
# Renderer factories take (query, overrides) and return the
# `render(limit) -> jsonable` callable debug_body expects; imports stay
# lazy inside each factory (the servers must not drag every subsystem
# in at import).  `overrides` carries caller-injected seams — the
# health server's constructor-injected `traces` callable.

def _r_traces(query, overrides):
    traces = overrides.get("traces")
    if traces is None:
        from kubernetes_tpu.runtime import flightrecorder

        traces = flightrecorder.get_default().chrome_trace
    return traces


def _r_decisions(query, overrides):
    return lambda lim: {"decisions": get_default().decisions(lim)}


def _r_cluster(query, overrides):
    from kubernetes_tpu.runtime import telemetry

    return telemetry.get_default().debug_payload


def _r_perf(query, overrides):
    from kubernetes_tpu.runtime import perfobs

    return perfobs.get_default().debug_payload


def _r_profile(query, overrides):
    from kubernetes_tpu.runtime import perfobs

    return lambda _lim=None: perfobs.profile_request(query)


def _r_quality(query, overrides):
    from kubernetes_tpu.runtime import quality

    return quality.get_default().debug_payload


def _r_replicas(query, overrides):
    from kubernetes_tpu.runtime import reconciler

    return reconciler.debug_payload


def _r_capacity(query, overrides):
    from kubernetes_tpu.runtime import capacity

    return capacity.get_default().debug_payload


def _r_autoscaler(query, overrides):
    from kubernetes_tpu.runtime import autoscaler

    ctrl = autoscaler.get_default()
    if ctrl is None:
        # tolerates no wired controller (reports disabled) — unlike
        # the planner, actuation is commonly off
        return lambda _lim=None: {"enabled": False}
    return ctrl.debug_payload


def _r_enact_peek(query, overrides):
    # GET is a status peek — the actuation verb is POST (debug_post);
    # serving the peek keeps the /debug/ index walk uniform (every
    # listed endpoint GETs 200)
    from kubernetes_tpu.runtime import autoscaler

    ctrl = autoscaler.get_default()
    return lambda _lim=None: {
        "method": "POST",
        "hint": "POST runs one guarded round now; ?dryRun=1 decides "
                "+ records without mutating",
        "enabled": ctrl is not None,
        "last": (ctrl.summary().get("last")
                 if ctrl is not None else None),
    }


def _r_timeline(query, overrides):
    from kubernetes_tpu.runtime import timeline

    return lambda lim: timeline.get_default().debug_payload(
        limit=lim, query=query
    )


DEBUG_RENDERERS = {
    "/debug/traces": _r_traces,
    "/debug/decisions": _r_decisions,
    "/debug/cluster": _r_cluster,
    "/debug/perf": _r_perf,
    "/debug/profile": _r_profile,
    "/debug/quality": _r_quality,
    "/debug/replicas": _r_replicas,
    "/debug/capacity": _r_capacity,
    "/debug/autoscaler": _r_autoscaler,
    "/debug/capacity/enact": _r_enact_peek,
    "/debug/timeline": _r_timeline,
}

# the can't-forget guarantee: a path listed without a renderer (or
# rendered without a listing) fails at import, not in production
assert set(DEBUG_RENDERERS) == set(DEBUG_ENDPOINTS), (
    set(DEBUG_RENDERERS) ^ set(DEBUG_ENDPOINTS)
)


def debug_dispatch(path: str, query: str = "",
                   overrides: Optional[dict] = None) -> Optional[bytes]:
    """Route one GET /debug/* request through the shared table.
    Returns the JSON body bytes, or None when the path is not a debug
    endpoint (the caller 404s)."""
    if path in ("/debug", "/debug/"):
        return debug_body(lambda _lim=None: debug_index(), query)
    factory = DEBUG_RENDERERS.get(path)
    if factory is None:
        return None
    return debug_body(factory(query, overrides or {}), query)


def debug_post(path: str, query: str = ""
               ) -> Optional[Tuple[int, bytes]]:
    """Route one debug POST verb.  Returns (status, body) or None when
    the path has no POST handler (the caller falls through/404s).
    Currently one verb: /debug/capacity/enact — run ONE guarded
    actuation round NOW (same lock as the loop, so a manual enact
    can't interleave with a scheduled one; ?dryRun=1 decides +
    records without mutating the fleet)."""
    if path != "/debug/capacity/enact":
        return None
    from urllib.parse import parse_qs

    from kubernetes_tpu.runtime import autoscaler

    ctrl = autoscaler.get_default()
    if ctrl is None:
        return 409, json.dumps({"error": "no autoscaler wired"}).encode()
    q = parse_qs(query)
    dry = None
    if "dryRun" in q:
        dry = q["dryRun"][-1] not in ("0", "false", "")
    try:
        return 200, json.dumps(ctrl.enact(dry_run=dry)).encode()
    except Exception as e:  # noqa: BLE001 — the verb reports, never raises
        return 500, json.dumps({"error": str(e)}).encode()


# ------------------------------------------------------------- replay

def read_ledger_stream(path: str) -> Tuple[dict, Iterator[dict]]:
    """Ledger file -> (header meta, LAZY cycle-record iterator).  Each
    record: {meta..., "winners", "cluster" (reconstructed
    ClusterTensors), "batch", "ports", "nominated", "aff_state",
    "extra_mask", "extra_score", "last_index0"}.  Streaming matters:
    only the running delta-base snapshot stays alive, so replaying a
    full 4096-cycle ledger holds one record's tensors at a time instead
    of the whole file's."""
    from kubernetes_tpu.models.batched import (
        BatchPortState,
        LeanBatchAffinity,
        NominatedState,
    )
    from kubernetes_tpu.codec.schema import PodBatch

    f = open(path, "rb")

    def _next_block():
        head = f.read(_LEN.size)
        if not head:
            return None
        (n,) = _LEN.unpack(head)
        blob = f.read(n)
        if len(blob) != n:
            raise ValueError(f"truncated ledger block in {path}")
        z = np.load(io.BytesIO(blob), allow_pickle=False)
        return z, json.loads(bytes(z["__meta__"]).decode())

    first = _next_block()
    header: dict = {}
    pending = None
    if first is not None:
        z0, meta0 = first
        if meta0.get("kind") == "header":
            header = meta0
        else:
            pending = first

    def _records():
        nonlocal pending
        prev: Optional[ClusterTensors] = None
        try:
            while True:
                block = pending if pending is not None else _next_block()
                pending = None
                if block is None:
                    return
                z, meta = block
                if meta.get("kind") == "header":
                    continue
                delta: dict = {}
                for key in z.files:
                    if key.startswith("snap.full."):
                        delta[key[len("snap.full."):]] = ("full", z[key])
                    elif (
                        key.startswith("snap.rows.")
                        and key.endswith(".idx")
                    ):
                        name = key[len("snap.rows."):-len(".idx")]
                        delta[name] = (
                            "rows", z[key], z[f"snap.rows.{name}.val"]
                        )
                cluster = apply_snapshot_delta(
                    prev, delta, cls=ClusterTensors
                )
                prev = cluster
                present = meta.get("present", {})
                rec = dict(meta)
                rec["cluster"] = cluster
                rec["batch"] = _unpack_component(z, "batch", PodBatch)
                rec["ports"] = _unpack_component(
                    z, "ports", BatchPortState
                )
                rec["nominated"] = (
                    _unpack_component(z, "nom", NominatedState)
                    if present.get("nominated") else None
                )
                rec["aff_state"] = (
                    _unpack_component(z, "aff", LeanBatchAffinity)
                    if present.get("aff_state") else None
                )
                rec["extra_mask"] = (
                    z["extra_mask"] if present.get("extra_mask") else None
                )
                rec["extra_score"] = (
                    z["extra_score"] if present.get("extra_score")
                    else None
                )
                rec["winners"] = z["winners"]
                rec["quality"] = (
                    {
                        "top_nodes": z["quality_top_nodes"],
                        "top_scores": z["quality_top_scores"],
                        "feasible": z["quality_feasible"],
                    }
                    if present.get("quality_top_nodes") else None
                )
                yield rec
        finally:
            f.close()

    return header, _records()


def read_ledger(path: str) -> Tuple[dict, List[dict]]:
    """Eager twin of read_ledger_stream (tests / small ledgers)."""
    header, records = read_ledger_stream(path)
    return header, list(records)


def replay_record(fn, rec: dict) -> np.ndarray:
    """Re-execute one recorded cycle through engine `fn`; returns the
    replayed winners i32[n_pods] (truncated to the live batch)."""
    out = fn(
        rec["cluster"], rec["batch"], rec["ports"],
        np.int32(rec["last_index0"]), rec["nominated"],
        rec["extra_mask"], rec["extra_score"], rec["aff_state"],
    )
    hosts = np.asarray(out[0])
    return hosts[: int(rec["n_pods"])]


def replay(path: str, engine: Optional[str] = None,
           cluster_stats: bool = True) -> dict:
    """Replay every recorded cycle and compare winners bit-for-bit.
    Returns {"cycles", "pods", "mismatches", "bit_identical",
    "engine", "mismatch_detail"} plus — with `cluster_stats` (the
    default) — per-run utilization/fragmentation columns computed from
    each reconstructed snapshot via the bit-exact numpy analytics
    reference (ops/analytics.py): the packing-quality series the
    offline weight-tuning loop (ROADMAP item 4) scores candidate
    weights against."""
    header, records = read_ledger_stream(path)
    fns: Dict[str, Any] = {}

    def fn_for(rec: dict):
        # degraded cycles were served by the CPU reference engine, whose
        # commit/tie-rotation semantics are the SEQUENTIAL scan's — they
        # replay bit-identically through it whatever the header engine
        kind = engine or rec.get("engine") or header.get(
            "engine", "speculative"
        )
        if kind == "cpu":
            kind = "sequential"
        if kind not in fns:
            fns[kind] = build_replay_fn(header, engine=kind)
        return fns[kind]

    mismatches = 0
    pods = 0
    cycles = 0
    detail: List[dict] = []
    util_cpu: List[float] = []
    util_mem: List[float] = []
    frag: List[float] = []
    # offline quality recompute (ISSUE 13): margins + feasible counts
    # re-derived from the recorded top-k blocks — the same math the
    # live observatory runs, so the replayed figures are directly
    # comparable to the /debug/quality ones banked alongside
    q_margins: List[float] = []
    q_feasible: List[int] = []
    q_cycles = 0
    for rec in records:
        cycles += 1
        qual = rec.get("quality")
        if qual is not None:
            from kubernetes_tpu.runtime.quality import normalized_margin

            q_cycles += 1
            n = int(rec["n_pods"])
            tn = np.asarray(qual["top_nodes"])[:n]
            ts = np.asarray(qual["top_scores"])[:n]
            q_feasible.extend(
                int(f) for f in np.asarray(qual["feasible"])[:n]
            )
            if tn.shape[-1] >= 2:
                two = (tn[:, 0] >= 0) & (tn[:, 1] >= 0)
                if two.any():
                    # THE shared margin formula (runtime/quality.py) —
                    # offline figures stay bit-comparable to the live
                    # /debug/quality ones by construction
                    q_margins.extend(
                        normalized_margin(ts[two, 0], ts[two, 1]).tolist()
                    )
        if cluster_stats:
            from kubernetes_tpu.ops.analytics import cluster_analytics_np

            snap = rec["cluster"]
            a = cluster_analytics_np(
                snap.allocatable, snap.requested, snap.valid
            )
            u = np.asarray(a.utilization)
            util_cpu.append(float(u[0, 0]))
            util_mem.append(float(u[1, 0]))
            frag.append(float(np.asarray(a.fragmentation)))
        got = replay_record(fn_for(rec), rec)
        want = np.asarray(rec["winners"])[: int(rec["n_pods"])]
        pods += len(want)
        if not np.array_equal(got, want):
            mismatches += 1
            if len(detail) < 8:
                bad = np.flatnonzero(got != want)
                detail.append({
                    "cycle": rec.get("cycle"),
                    "pods": [int(i) for i in bad[:16]],
                    "want": [int(want[i]) for i in bad[:16]],
                    "got": [int(got[i]) for i in bad[:16]],
                })
    out = {
        "cycles": cycles,
        "pods": pods,
        "mismatches": mismatches,
        "bit_identical": mismatches == 0,
        "engine": engine or header.get("engine", "?"),
        "mismatch_detail": detail,
    }
    if cluster_stats and cycles:
        def _col(series: List[float]) -> dict:
            return {
                "first": round(series[0], 4),
                "last": round(series[-1], 4),
                "mean": round(sum(series) / len(series), 4),
            }

        out["cluster"] = {
            "utilization_cpu_mean": _col(util_cpu),
            "utilization_memory_mean": _col(util_mem),
            "fragmentation": _col(frag),
        }
    if q_cycles:
        out["quality"] = {
            "cycles_with_topk": q_cycles,
            "margin_p50": (
                round(float(np.percentile(np.asarray(q_margins), 50)), 6)
                if q_margins else 0.0
            ),
            "margin_mean": (
                round(float(np.mean(q_margins)), 6) if q_margins else 0.0
            ),
            "margins": len(q_margins),
            "feasible_p50": (
                round(float(np.percentile(np.asarray(q_feasible), 50)), 1)
                if q_feasible else 0.0
            ),
        }
    return out
