"""Hollow nodes: scale testing without machines.

The reference measures 5k-node behavior with kubemark hollow nodes — a real
kubelet sync loop wired to fake runtime backends (pkg/kubemark/
hollow_kubelet.go:53-74, cmd/kubemark/hollow-node.go).  The analog here: a
HollowNode registers a Node object and runs the node-agent's observable
contract against the LocalCluster — acknowledge bound pods by driving
status.phase to Running (the statusManager PATCH analog) — without any
containers underneath.  The density harness (tests + bench) uses fleets of
these to exercise the full schedule->bind->run loop.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.runtime.cluster import ADDED, DELETED, MODIFIED, LocalCluster


class HollowNode:
    def __init__(self, cluster: LocalCluster, node: Node):
        self.cluster = cluster
        self.node = node
        self.running: Dict = {}
        cluster.add_node(node)

    def observe(self, event: str, kind: str, obj) -> None:
        """Pod-informer callback: claim pods bound to this node; release
        deleted ones (eviction/GC) so running never overcounts."""
        if kind != "pods":
            return
        if obj.spec.node_name != self.node.name:
            return
        key = (obj.namespace, obj.name)
        if event == DELETED:
            self.running.pop(key, None)
            return
        if event not in (ADDED, MODIFIED) or key in self.running:
            return
        self.running[key] = obj
        if obj.status.phase != "Running":
            import dataclasses

            from kubernetes_tpu.api.types import PodStatus

            self.cluster.update(
                "pods", dataclasses.replace(obj, status=PodStatus(phase="Running"))
            )


class HollowFleet:
    """N hollow nodes sharing one watch subscription."""

    def __init__(self, cluster: LocalCluster, nodes: List[Node]):
        self.cluster = cluster
        self.nodes = [HollowNode(cluster, n) for n in nodes]
        by_name = {h.node.name: h for h in self.nodes}

        def fanout(event, kind, obj):
            if kind == "pods" and obj.spec.node_name in by_name:
                by_name[obj.spec.node_name].observe(event, kind, obj)

        cluster.watch(fanout)

    @property
    def total_running(self) -> int:
        return sum(len(h.running) for h in self.nodes)
