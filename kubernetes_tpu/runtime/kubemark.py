"""Hollow nodes: scale testing without machines.

The reference measures 5k-node behavior with kubemark hollow nodes — a real
kubelet sync loop wired to fake runtime backends (pkg/kubemark/
hollow_kubelet.go:53-74, cmd/kubemark/hollow-node.go).  The analog here: a
HollowNode registers a Node object and runs the node-agent's observable
contract against the LocalCluster — acknowledge bound pods by driving
status.phase to Running (the statusManager PATCH analog) — without any
containers underneath.  The density harness (tests + bench) uses fleets of
these to exercise the full schedule->bind->run loop.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.runtime.cluster import ADDED, DELETED, MODIFIED, LocalCluster


class HollowNode:
    """`completer(pod) -> bool`: when given, pods it approves transition
    Running -> Succeeded — consulted on pod events for already-Running pods
    and on explicit `tick()` sweeps (a completer that declines keeps the
    pod Running until a later tick; call fleet.tick() from the drive loop
    for time-based completion)."""

    def __init__(self, cluster: LocalCluster, node: Node, completer=None):
        self.cluster = cluster
        self.node = node
        self.running: Dict = {}
        self.completer = completer
        cluster.add_node(node)

    def observe(self, event: str, kind: str, obj) -> None:
        """Pod-informer callback: claim pods bound to this node; release
        deleted ones (eviction/GC) so running never overcounts."""
        if kind != "pods":
            return
        if obj.spec.node_name != self.node.name:
            return
        key = (obj.namespace, obj.name)
        if event == DELETED:
            self.running.pop(key, None)
            return
        if event not in (ADDED, MODIFIED):
            return
        import dataclasses

        from kubernetes_tpu.api.types import PodStatus

        if key in self.running:
            if (
                obj.status.phase == "Running"
                and self.completer is not None
                and self.completer(obj)
            ):
                self.running.pop(key, None)
                self.cluster.update(
                    "pods",
                    dataclasses.replace(obj, status=PodStatus(phase="Succeeded")),
                )
            return
        if obj.status.phase in ("Succeeded", "Failed"):
            return  # terminal pods are never (re)claimed
        self.running[key] = obj
        if (
            obj.status.phase == "Running"
            and self.completer is not None
            and self.completer(obj)
        ):
            # claimed already-Running (watch replay): complete immediately
            self.running.pop(key, None)
            self.cluster.update(
                "pods",
                dataclasses.replace(obj, status=PodStatus(phase="Succeeded")),
            )
            return
        if obj.status.phase != "Running":
            self.cluster.update(
                "pods", dataclasses.replace(obj, status=PodStatus(phase="Running"))
            )


class HollowFleet:
    """N hollow nodes sharing one watch subscription."""

    def __init__(self, cluster: LocalCluster, nodes: List[Node],
                 completer=None):
        self.cluster = cluster
        self.nodes = [HollowNode(cluster, n, completer) for n in nodes]
        by_name = {h.node.name: h for h in self.nodes}

        def fanout(event, kind, obj):
            if kind == "pods" and obj.spec.node_name in by_name:
                by_name[obj.spec.node_name].observe(event, kind, obj)

        cluster.watch(fanout)

    def tick(self) -> int:
        """Re-consult the completer for every running pod (the PLEG relist
        analog); returns how many completed this sweep."""
        import dataclasses

        from kubernetes_tpu.api.types import PodStatus

        done = 0
        for h in self.nodes:
            if h.completer is None:
                continue
            for key, pod in list(h.running.items()):
                if h.completer(pod):
                    h.running.pop(key, None)
                    self.cluster.update(
                        "pods",
                        dataclasses.replace(
                            pod, status=PodStatus(phase="Succeeded")
                        ),
                    )
                    done += 1
        return done

    @property
    def total_running(self) -> int:
        return sum(len(h.running) for h in self.nodes)
