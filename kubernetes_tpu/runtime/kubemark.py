"""Hollow nodes: scale testing without machines.

The reference measures 5k-node behavior with kubemark hollow nodes — a real
kubelet sync loop wired to fake runtime backends (pkg/kubemark/
hollow_kubelet.go:53-74, cmd/kubemark/hollow-node.go).  The analog is
literal here: a HollowNode IS the Kubelet (runtime/kubelet.py) over a
FakeRuntime — same configCh claim -> CRI sandbox -> Running status flow,
same completer hooks — just nothing underneath the runtime.  The density
harness (tests + bench) uses fleets of these to exercise the full
schedule -> bind -> run loop.
"""

from __future__ import annotations

from typing import List, Optional

from kubernetes_tpu.api.types import Node
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.kubelet import FakeRuntime, Kubelet


class HollowNode(Kubelet):
    """hollow_kubelet.go analog: the Kubelet over a FakeRuntime."""

    def __init__(self, cluster: LocalCluster, node: Node, completer=None,
                 register: bool = True, subscribe: bool = True):
        super().__init__(
            cluster, node, FakeRuntime(), completer,
            register=register, subscribe=subscribe,
        )


class HollowFleet:
    """N hollow nodes sharing ONE watch subscription (the informer fan-out
    a real fleet gets from per-process reflectors)."""

    def __init__(self, cluster: LocalCluster, nodes: List[Node],
                 completer=None, register=True):
        """register: bool, or a predicate(node) -> bool — a restarted
        hollow-node process passes `lambda n: not already_exists(n)` so
        pre-existing nodes still get kubelet loops without a duplicate
        registration."""
        self.cluster = cluster
        reg = register if callable(register) else (lambda n: register)
        self.nodes = [
            HollowNode(cluster, n, completer, register=reg(n),
                       subscribe=False)
            for n in nodes
        ]
        by_name = {h.node.name: h for h in self.nodes}

        def fanout(event, kind, obj):
            if kind == "pods" and obj.spec.node_name in by_name:
                by_name[obj.spec.node_name].observe(event, kind, obj)
            elif kind == "nodes" and obj.name in by_name:
                by_name[obj.name].observe(event, kind, obj)

        cluster.watch(fanout)

    def tick(self) -> int:
        """PLEG relist sweep across the fleet; returns completions."""
        return sum(h.pleg_relist() for h in self.nodes)

    def heartbeat_all(self, now: Optional[float] = None) -> None:
        for h in self.nodes:
            h.heartbeat(now=now)

    @property
    def total_running(self) -> int:
        return sum(len(h.sandbox_of) for h in self.nodes)
