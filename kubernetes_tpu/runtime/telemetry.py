"""Cluster + device telemetry hub and multi-window SLO burn alerting.

PRs 5 and 7 made the scheduler's *decisions* observable (spans, flight
recorder, ledger, attribution); this module reports the *state* — of the
fleet and of the device — and watches the SLOs an operator actually
pages on:

  * **Cluster analytics.**  Every `telemetryIntervalCycles` the hub
    dispatches ops/analytics.cluster_analytics as a side-launch over the
    DEVICE-RESIDENT snapshot buffers (DeviceSnapshotCache.resident —
    zero extra upload traffic; one tiny D2H for the ~50-float result),
    materializing the PREVIOUS launch's result first so the scheduling
    thread never blocks on analytics compute.  Degraded cycles (breaker
    open, resident buffers invalidated) fall back to the bit-exact
    numpy reference over the cycle's host snapshot.
  * **Device runtime.**  Per-device HBM live/peak/limit bytes via
    `device.memory_stats()` (a no-op on backends without stats — the
    CPU path reports nothing rather than zeros), compile-cache hit/miss
    and cumulative backend-compile seconds (utils/compilecache.py
    jax.monitoring listeners), and a launch-duration EWMA per
    executable batch width.
  * **SLO burn rates.**  The SRE-workbook multi-window scheme: each
    objective tracks good/bad events over a FAST and a SLOW window;
    burn = (bad fraction) / (error budget).  An alert fires when BOTH
    windows exceed the threshold (fast alone is noise, slow alone is
    stale), incrementing scheduler_slo_burn_alerts_total and dumping a
    throttled `slo_burn` flight-recorder postmortem via the scheduler's
    postmortem seam; the alert re-arms when the fast window recovers.

Samples land in a bounded time-series ring served at GET /debug/cluster
(health server + apiserver, ?limit= + the shared 4MB response cap).
`HUB`/`get_default`/`set_default` follow the flightrecorder.RECORDER
pattern: a Scheduler built with config.telemetry installs its hub as the
process default so the debug endpoints serve it without extra wiring.

The reference has no analog (kube-state-metrics + Prometheus recording
rules live OUTSIDE the scheduler); here the snapshot is already resident
on the engine's device, so fleet analytics are one fused reduction —
the same utilization/fragmentation criteria ROADMAP items 2 and 4 score
candidate packings with.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.ops.analytics import (
    analytics_to_dict,
    cluster_analytics_auto,
    cluster_analytics_np,
)
from kubernetes_tpu.utils import metrics as m
from kubernetes_tpu.utils.compilecache import (
    compile_stats,
    install_metrics_listeners,
)

# the snapshot fields the analytics launch consumes, in kernel-argument
# order (DeviceSnapshotCache.resident is keyed on these names)
ANALYTICS_FIELDS = ("allocatable", "requested", "valid")


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """{device id: {in_use, peak, limit}} from device.memory_stats(),
    updating the ktpu_device_hbm_bytes gauges.  Backends without stats
    (XLA:CPU returns None) yield {} — the documented no-op fallback, so
    callers can invoke this unconditionally on any backend."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    try:
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend init failure is not ours
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-device API optional
            stats = None
        if not stats:
            continue
        entry = {
            "in_use": int(stats.get("bytes_in_use", 0)),
            "peak": int(stats.get("peak_bytes_in_use", 0)),
            "limit": int(stats.get("bytes_limit", 0)),
        }
        out[str(getattr(d, "id", len(out)))] = entry
        for kind, v in entry.items():
            m.DEVICE_HBM.set(v, device=str(getattr(d, "id", 0)), kind=kind)
    return out


# ------------------------------------------------------------------- SLO


@dataclass(frozen=True)
class SLOObjective:
    """One service-level objective watched by the burn evaluator.

    `objective` is the target GOOD fraction (0.99 = a 1% error budget);
    burn rate = observed bad fraction / (1 - objective), so burn 1.0
    means spending the budget exactly as fast as allowed."""

    name: str
    objective: float = 0.99
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_threshold: float = 1.0

    @staticmethod
    def from_dict(d: dict) -> "SLOObjective":
        """The KubeSchedulerConfiguration `sloObjectives` entry shape."""
        return SLOObjective(
            name=str(d["name"]),
            objective=float(d.get("objective", 0.99)),
            fast_window_s=float(d.get("fastWindowSeconds", 60.0)),
            slow_window_s=float(d.get("slowWindowSeconds", 300.0)),
            burn_threshold=float(d.get("burnThreshold", 1.0)),
        )


# the objectives a telemetry-enabled scheduler watches by default:
#  * cycle_deadline — cycles finishing inside cycleDeadlineSeconds
#    (observed only when a deadline is configured; the express-lane p99
#    story rides this: the deadline is the per-cycle latency budget)
#  * goodput — offered pods served (scheduled OR a verdict) vs shed
#  * degraded — cycles served by the device fast path vs the CPU
#    fallback (breaker-open time, in cycle units)
DEFAULT_OBJECTIVES: Tuple[SLOObjective, ...] = (
    SLOObjective("cycle_deadline", objective=0.99),
    SLOObjective("goodput", objective=0.99),
    SLOObjective("degraded", objective=0.99),
)


def build_objectives(raw: Optional[list]) -> Tuple[SLOObjective, ...]:
    """Config `sloObjectives` (list of dicts) -> objectives; None/empty
    keeps the defaults."""
    if not raw:
        return DEFAULT_OBJECTIVES
    return tuple(
        o if isinstance(o, SLOObjective) else SLOObjective.from_dict(o)
        for o in raw
    )


class _Window:
    """One rolling window: a deque of (t, good, bad) plus RUNNING sums
    maintained on add/expiry, so a burn-rate read is O(1) instead of a
    rescan of every event in the window — at production cycle rates a
    300s window holds tens of thousands of events, and the evaluator
    runs every committed cycle."""

    __slots__ = ("seconds", "events", "good", "bad")

    def __init__(self, seconds: float):
        self.seconds = float(seconds)
        self.events: deque = deque()
        self.good = 0.0
        self.bad = 0.0

    def add(self, t: float, good: float, bad: float) -> None:
        self.events.append((t, good, bad))
        self.good += good
        self.bad += bad

    def prune(self, now: float) -> None:
        horizon = now - self.seconds
        ev = self.events
        while ev and ev[0][0] < horizon:
            _, g, b = ev.popleft()
            self.good -= g
            self.bad -= b
        if not ev:
            # zero the sums whenever the window empties so float
            # accumulation error cannot drift them over long uptimes
            self.good = 0.0
            self.bad = 0.0

    def burn(self, budget: float) -> float:
        total = self.good + self.bad
        frac = self.bad / total if total > 0 else 0.0
        return frac / budget


class SLOEvaluator:
    """Multi-window burn-rate math over per-objective good/bad event
    streams.  Thread-safe: the scheduling thread observes/evaluates
    while HTTP reader threads (snapshot via /debug/cluster) read burn
    rates; `clock` keeps the window tests deterministic."""

    def __init__(
        self,
        objectives: Tuple[SLOObjective, ...] = DEFAULT_OBJECTIVES,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.objectives: Dict[str, SLOObjective] = {
            o.name: o for o in objectives
        }
        self._clock = clock
        self._lock = threading.Lock()
        # name -> (fast window, slow window) with rolling sums
        self._windows: Dict[str, Tuple[_Window, _Window]] = {
            name: (_Window(o.fast_window_s), _Window(o.slow_window_s))
            for name, o in self.objectives.items()
        }
        # alert hysteresis: fire once on crossing, re-arm when the FAST
        # window recovers (so a sustained burn is one alert, not one per
        # cycle — the recorder's per-trigger throttle backstops this)
        self._alert_active: Dict[str, bool] = {}
        # last rates computed by evaluate(): snapshot() reuses them so a
        # per-cycle sample does not recompute every objective twice
        self._last_rates: Dict[str, Tuple[float, float]] = {}
        self.alerts_total = 0

    def observe(self, name: str, good: float = 0.0, bad: float = 0.0,
                t: Optional[float] = None) -> None:
        """Record `good` successes and `bad` budget-burning events for
        one objective (unknown names are ignored so callers need not
        mirror the configured set)."""
        windows = self._windows.get(name)
        if windows is None or (good == 0.0 and bad == 0.0):
            return
        now = self._clock() if t is None else t
        with self._lock:
            for w in windows:
                w.add(now, float(good), float(bad))
                w.prune(now)

    def burn_rates(self, name: str,
                   t: Optional[float] = None) -> Tuple[float, float]:
        """(fast, slow) burn rates for one objective: bad fraction over
        the window divided by the error budget; 0.0 with no events."""
        obj = self.objectives[name]
        now = self._clock() if t is None else t
        budget = max(1.0 - obj.objective, 1e-9)
        fast, slow = self._windows[name]
        with self._lock:
            fast.prune(now)
            slow.prune(now)
            return fast.burn(budget), slow.burn(budget)

    def evaluate(self, t: Optional[float] = None) -> List[Tuple[str, float, float]]:
        """Update every objective's burn gauges; return the objectives
        whose alert NEWLY fired (both windows over threshold while the
        alert was armed)."""
        fired: List[Tuple[str, float, float]] = []
        for name, obj in self.objectives.items():
            fast, slow = self.burn_rates(name, t)
            self._last_rates[name] = (fast, slow)
            m.SLO_BURN_RATE.set(fast, objective=name, window="fast")
            m.SLO_BURN_RATE.set(slow, objective=name, window="slow")
            burning = (
                fast >= obj.burn_threshold and slow >= obj.burn_threshold
            )
            if burning and not self._alert_active.get(name, False):
                self._alert_active[name] = True
                self.alerts_total += 1
                m.SLO_ALERTS.inc(objective=name)
                fired.append((name, fast, slow))
            elif fast < obj.burn_threshold:
                self._alert_active[name] = False
        return fired

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """{objective: {fast, slow, threshold, objective}} for samples —
        served from evaluate()'s cached rates when available (the
        per-cycle sampling path must not rescan the deques twice)."""
        out: Dict[str, Dict[str, float]] = {}
        for name, obj in self.objectives.items():
            cached = self._last_rates.get(name)
            fast, slow = (
                cached if cached is not None else self.burn_rates(name)
            )
            out[name] = {
                "fast": round(fast, 4),
                "slow": round(slow, 4),
                "threshold": obj.burn_threshold,
                "objective": obj.objective,
            }
        return out


# ------------------------------------------------------------------- hub


class TelemetryHub:
    """Per-scheduler telemetry aggregation point.

    The scheduling thread calls `on_cycle` once per committed cycle
    (runtime/scheduler.py stamps the call's cost into
    scheduler_telemetry_seconds_total — the <2% budget perf_smoke pins);
    readers (metrics scrape, /debug/cluster, heartbeat, bench) come from
    other threads and take the hub lock only around ring/summary state.

    Analytics cadence is AMORTIZED: on each due cycle the hub first
    materializes the launch dispatched one interval ago (a ~50-float
    D2H that has long since landed) and only then dispatches the next —
    the scheduling thread never waits on analytics compute."""

    def __init__(
        self,
        interval_cycles: int = 1,
        objectives: Tuple[SLOObjective, ...] = DEFAULT_OBJECTIVES,
        ring_capacity: int = 512,
        postmortem: Optional[Callable[[str, str], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        ewma_alpha: float = 0.2,
    ):
        self.interval_cycles = max(1, int(interval_cycles))
        self.slo = SLOEvaluator(objectives, clock=clock)
        self._postmortem = postmortem
        self._clock = clock
        self._ewma_alpha = float(ewma_alpha)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(ring_capacity)))
        # in-flight analytics: (cycle, tier, device-output pytree or
        # host ClusterAnalytics, source tag)
        self._pending: Optional[Tuple[int, str, object, str]] = None
        self.analytics: Optional[dict] = None  # last materialized sample
        self.analytics_cycle = -1
        self.samples_total = 0
        self._cycles_since_dispatch = self.interval_cycles  # first is due
        self._launch_ewma: Dict[int, float] = {}
        self._pressure: Optional[Dict[str, int]] = None
        self.last_hbm: Dict[str, Dict[str, int]] = {}
        # elastic-ladder state (ISSUE 10): live mesh width, rung,
        # per-shard breaker states, invariant-checker totals.  Stamped
        # FRESH by the scheduler every committed cycle — the hub must
        # never cache startup topology, because the mesh can now change
        # at runtime (shrink/restore) and a stale width/sharding here
        # would misreport every sample after the first rebuild.
        self._mesh: Optional[dict] = None
        self.cycles_total = 0
        install_metrics_listeners()

    # ------------------------------------------------------ hot-path API

    def note_launch(self, width: int, seconds: float) -> None:
        """Fold one device launch window (dispatch -> copy-complete)
        into the per-width EWMA.  Locked: HTTP reader threads iterate
        the width map while the scheduling thread inserts new widths."""
        with self._lock:
            prev = self._launch_ewma.get(width)
            cur = (
                seconds if prev is None
                else prev + self._ewma_alpha * (seconds - prev)
            )
            self._launch_ewma[width] = cur
        m.LAUNCH_EWMA.set(cur, width=str(width))

    def prune_widths(self, keep) -> None:
        """Retire EWMA series for widths no longer dispatchable (an AIMD
        cap change) so the labeled family stays bounded."""
        keep = set(int(w) for w in keep)
        with self._lock:
            stale = [w for w in self._launch_ewma if w not in keep]
            for w in stale:
                del self._launch_ewma[w]
        for w in stale:
            m.LAUNCH_EWMA.remove(width=str(w))

    def _ewma_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                str(w): round(s, 6)
                for w, s in sorted(self._launch_ewma.items())
            }

    def on_cycle(
        self,
        cycle: int,
        tier: str,
        cycle_s: float,
        placed: int,
        unschedulable: int,
        shed: int = 0,
        degraded: bool = False,
        deadline_s: float = 0.0,
        resident: Optional[tuple] = None,
        host_snapshot: Optional[tuple] = None,
        span=None,
    ) -> None:
        """One committed scheduling cycle's telemetry: SLO events, burn
        evaluation (firing slo_burn postmortems through the scheduler's
        seam), pending-pressure gauges, and the amortized analytics
        side-launch.  `resident` is DeviceSnapshotCache.resident(
        ANALYTICS_FIELDS) — None routes this interval through the numpy
        reference over `host_snapshot` (the degraded path)."""
        self.cycles_total += 1
        now = self._clock()
        if deadline_s > 0:
            over = cycle_s > deadline_s
            self.slo.observe(
                "cycle_deadline", good=0.0 if over else 1.0,
                bad=1.0 if over else 0.0, t=now,
            )
        served = placed + unschedulable
        self.slo.observe("goodput", good=float(served), bad=float(shed),
                         t=now)
        self.slo.observe(
            "degraded", good=0.0 if degraded else 1.0,
            bad=1.0 if degraded else 0.0, t=now,
        )
        for name, fast, slow in self.slo.evaluate(now):
            if self._postmortem is not None:
                self._postmortem(
                    "slo_burn",
                    f"objective {name}: burn fast={fast:.1f} "
                    f"slow={slow:.1f} >= "
                    f"{self.slo.objectives[name].burn_threshold}",
                )
        self._cycles_since_dispatch += 1
        if self._cycles_since_dispatch < self.interval_cycles:
            return
        self._cycles_since_dispatch = 0
        # materialize the PREVIOUS interval's launch (long since landed),
        # then dispatch the next — the amortization that keeps this hook
        # off the critical path
        sample = self._materialize_pending()
        if sample is not None and span is not None:
            span.annotate(
                cluster_util_cpu=sample["analytics"]["utilization"]["cpu"][
                    "mean"
                ],
                cluster_fragmentation=sample["analytics"]["fragmentation"],
            )
        if resident is not None:
            # mesh-aware dispatch (ops/analytics.py): sharded resident
            # buffers reduce per-shard with a cross-shard fold — the full
            # node tensor never gathers to one chip — and stay bit-exact
            # vs the numpy reference; single-chip buffers take the
            # classic kernel unchanged
            out = cluster_analytics_auto(*resident)
            self._pending = (cycle, tier, out, "device")
        elif host_snapshot is not None:
            out = cluster_analytics_np(*host_snapshot)
            self._pending = (cycle, tier, out, "host")

    def record_mesh(
        self,
        width: int,
        full_width: int = 0,
        rung: str = "single_chip",
        shard_states: Optional[Dict[int, str]] = None,
        invariants: Optional[dict] = None,
    ) -> None:
        """Per-cycle ladder facts from the scheduler: live mesh width vs
        the startup width, the rung serving cycles, each shard's breaker
        state, and the invariant checker's totals.  Joined into every
        /debug/cluster sample and the summary."""
        with self._lock:
            self._mesh = {
                "width": int(width),
                "full_width": int(full_width),
                "rung": rung,
                "shards": (
                    {str(k): v for k, v in shard_states.items()}
                    if shard_states else None
                ),
                "invariants": invariants,
            }

    def record_pressure(self, bulk: int, express: int, parked: int) -> None:
        """Per-tier pending pressure (queue depths, stamped by the
        scheduler alongside on_cycle)."""
        m.PENDING_PRESSURE.set(float(bulk), tier="bulk")
        m.PENDING_PRESSURE.set(float(express), tier="express")
        m.PENDING_PRESSURE.set(float(parked), tier="parked")
        with self._lock:
            self._pressure = {
                "bulk": int(bulk), "express": int(express),
                "parked": int(parked),
            }

    # ------------------------------------------------------ materialize

    def _materialize_pending(self) -> Optional[dict]:
        """Fetch the in-flight analytics launch (if any) into a ring
        sample, updating the cluster gauges.  Cheap by construction: the
        launch is one interval old and its output is ~50 floats."""
        with self._lock:  # readers race the scheduling thread here
            pending, self._pending = self._pending, None
        if pending is None:
            return None
        cycle, tier, out, source = pending
        try:
            host = type(out)(
                *(np.asarray(x) for x in _leaves_in_order(out))
            )
        except Exception:  # noqa: BLE001 — a faulted launch loses ONE
            #                 sample, never the telemetry stream
            return None
        a = analytics_to_dict(host)
        self._set_cluster_gauges(a)
        sample = {
            "time": time.time(),
            "cycle": cycle,
            "tier": tier,
            "source": source,
            "analytics": a,
            "pending": self._pressure,
            "mesh": self._mesh,
            "hbm": device_memory_stats(),
            "compile": compile_stats(),
            "launch_ewma_s": self._ewma_snapshot(),
            "slo": self.slo.snapshot(),
        }
        with self._lock:
            self.last_hbm = sample["hbm"]
            self.analytics = a
            self.analytics_cycle = cycle
            self._ring.append(sample)
            self.samples_total += 1
        m.TELEMETRY_SAMPLES.inc()
        return sample

    @staticmethod
    def _set_cluster_gauges(a: dict) -> None:
        for res, stats in a["utilization"].items():
            for stat, v in stats.items():
                m.CLUSTER_UTILIZATION.set(v, resource=res, stat=stat)
        for res, v in a["largest_free"].items():
            m.CLUSTER_LARGEST_FREE.set(v, resource=res)
        for res, v in a["stranded"].items():
            m.CLUSTER_STRANDED.set(v, resource=res)
        m.CLUSTER_FRAGMENTATION.set(a["fragmentation"])
        m.CLUSTER_IMBALANCE.set(a["imbalance"])
        for i, n in enumerate(a["occupancy"]):
            m.CLUSTER_OCCUPANCY.set(float(n), decile=str(i))
        m.CLUSTER_NODES.set(float(a["nodes"]))
        m.CLUSTER_PODS_RUNNING.set(a["pods_running"])

    # ----------------------------------------------------------- readers

    def hbm_in_use(self) -> int:
        """Total live bytes across devices from the last sample (0 on
        statless backends) — the heartbeat's HBM figure."""
        with self._lock:
            return sum(d.get("in_use", 0) for d in self.last_hbm.values())

    def summary(self) -> dict:
        """Latest materialized analytics + hub accounting — the bench
        `cluster_health` stage body."""
        self._materialize_pending()
        with self._lock:
            out = {
                "analytics": self.analytics,
                "cycle": self.analytics_cycle,
                "samples": self.samples_total,
                "cycles": self.cycles_total,
                "pending": self._pressure,
                "mesh": self._mesh,
                "hbm": dict(self.last_hbm),
                "launch_ewma_s": {
                    str(w): round(s, 6)
                    for w, s in sorted(self._launch_ewma.items())
                },
            }
        out["compile"] = compile_stats()
        out["slo"] = self.slo.snapshot()
        return out

    def debug_payload(self, limit: Optional[int] = None) -> dict:
        """GET /debug/cluster body: newest-first bounded sample series +
        the summary.  `limit` keeps the newest n samples (the shared
        debug_body halves it further until the body fits the 4MB cap)."""
        self._materialize_pending()
        with self._lock:
            samples = list(self._ring)
        if limit is not None and limit >= 0:
            samples = samples[-limit:] if limit else []
        return {
            "summary": self.summary(),
            "samples": samples,
            "interval_cycles": self.interval_cycles,
        }


def _leaves_in_order(out):
    """ClusterAnalytics dataclass leaves in field order (works for both
    the jitted pytree output and the numpy reference)."""
    import dataclasses

    return [getattr(out, f.name) for f in dataclasses.fields(out)]


# process-wide default: the hub /debug/cluster serves when none was
# wired explicitly; a Scheduler built with config.telemetry installs
# its own here.  Replica 0 wins the process default, siblings register
# alongside for /debug/replicas (runtime/defaults.py ProcessDefault —
# the shared install/default/replica-registry discipline)
from kubernetes_tpu.runtime.defaults import ProcessDefault  # noqa: E402

_DEFAULT = ProcessDefault("telemetry", TelemetryHub)


def get_default() -> TelemetryHub:
    return _DEFAULT.get()


def set_default(hub: TelemetryHub, replica: int = 0) -> None:
    _DEFAULT.set(hub, replica)


def replica_instances() -> dict:
    """{replica id: TelemetryHub} of every install this process saw."""
    return _DEFAULT.replicas()


def __getattr__(name):  # legacy alias: telemetry.HUB
    if name == "HUB":
        return _DEFAULT.get()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
