"""Scheduling queue: active / backoff / unschedulable, priority-ordered.

Mirrors SchedulingQueue / PriorityQueue semantics
(ref pkg/scheduler/internal/queue/scheduling_queue.go:57-811):
  * activeQ — heap ordered by (pod priority desc, enqueue time asc)
  * podBackoffQ — heap by backoff expiry; moved to active when expired
  * unschedulableQ — parking lot, flushed to active/backoff by
    move_all_to_active (cluster events) or the 60s leftover flush
    (flushUnschedulableQLeftover)
  * schedulingCycle / moveRequestCycle counters decide whether a failed pod
    saw the latest cluster event (scheduling_queue.go:107-137)

Heap deletion is lazy (entries carry a valid flag), so delete/re-add cannot
double-pop a pod.  Backoff mirrors pod_backoff.go: initial 1s, doubling,
max 10s.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.utils import metrics as m

UNSCHEDULABLE_TIME_LIMIT = 60.0  # flushUnschedulableQLeftover interval

# shed reasons (scheduler_queue_shed_pods_total{reason=} label values +
# the on_shed callback's second argument)
SHED_EVICTED = "evicted"   # a parked pod dropped for a higher-priority arrival
SHED_ARRIVAL = "arrival"   # the incoming pod itself rejected at capacity

# latency tiers (ISSUE 6): classified once at queue ADMISSION (_push_active),
# so requeues/backoff re-route a pod to its lane without re-deciding policy
# anywhere else.  The express lane is a small pre-compiled batch shape the
# scheduler interleaves with the bulk AIMD lane.  The canonical tier label
# values live with the metric family that carries them (utils/metrics).
TIER_BULK = m.TIER_BULK
TIER_EXPRESS = m.TIER_EXPRESS
# annotation opt-in/out: "express" forces the express lane, "bulk" forces
# the bulk lane even above the priority threshold
LATENCY_TIER_ANNOTATION = "kubernetes-tpu.io/latency-tier"


def classify_tier(pod: Pod, priority_threshold: Optional[int] = None) -> str:
    """Admission-time latency-tier classification: the pod's explicit
    annotation wins in both directions; otherwise the priority-class
    threshold (spec.priority >= threshold -> express; None disables the
    priority route); default bulk."""
    ann = pod.metadata.annotations.get(LATENCY_TIER_ANNOTATION, "")
    if ann == TIER_EXPRESS:
        return TIER_EXPRESS
    if ann == TIER_BULK:
        return TIER_BULK
    if (
        priority_threshold is not None
        and pod.spec.priority >= priority_threshold
    ):
        return TIER_EXPRESS
    return TIER_BULK


class PodBackoff:
    """ref internal/queue/pod_backoff.go PodBackoffMap."""

    def __init__(self, initial: float = 1.0, max_duration: float = 10.0):
        self.initial = initial
        self.max = max_duration
        self._attempts: Dict[Tuple[str, str], int] = {}
        self._last_update: Dict[Tuple[str, str], float] = {}

    def backoff_time(self, key: Tuple[str, str]) -> float:
        n = self._attempts.get(key, 0)
        if n == 0:
            return 0.0
        d = min(self.initial * (2 ** (n - 1)), self.max)
        return self._last_update.get(key, 0.0) + d

    def boost(self, key: Tuple[str, str], now: Optional[float] = None) -> None:
        self._attempts[key] = self._attempts.get(key, 0) + 1
        self._last_update[key] = now if now is not None else time.monotonic()

    def clear(self, key: Tuple[str, str]) -> None:
        self._attempts.pop(key, None)
        self._last_update.pop(key, None)


def _pod_key(pod: Pod) -> Tuple[str, str]:
    return (pod.namespace, pod.name)


# entry layout: [sort_key..., pod, valid]
_VALID = -1  # index of the valid flag


class _CmpKey:
    """Adapts a framework QueueSort LessFunc to heapq's `<` protocol.

    Ties (neither less) compare equal so list comparison falls through to
    the FIFO sequence number."""

    __slots__ = ("info", "less")

    def __init__(self, info, less):
        self.info = info
        self.less = less

    def __lt__(self, other):
        return self.less(self.info, other.info)

    def __eq__(self, other):
        return not self.less(self.info, other.info) and not self.less(
            other.info, self.info
        )


class PriorityQueue:
    """Blocking pop; thread-safe.  Ordering: higher .spec.priority first, then
    FIFO by add time (the default queue-sort plugin semantics).  A framework
    QueueSort plugin's LessFunc (`less`) replaces the default ordering
    (scheduling_queue.go NewPriorityQueueWithClock activeQComp /
    framework.QueueSortFunc)."""

    def __init__(self, backoff: Optional[PodBackoff] = None, less=None,
                 capacity: Optional[int] = None,
                 on_shed: Optional[Callable[[Pod, str], None]] = None,
                 tier_of: Optional[Callable[[Pod], str]] = None,
                 on_requeue: Optional[Callable[[Pod], None]] = None,
                 shards: int = 1):
        # overload protection: bound the TOTAL queue population
        # (active + backoff + unschedulable).  None = unbounded (the
        # historical behavior).  At capacity, a NEW arrival sheds the
        # lowest-priority pod — preferring longest-parked unschedulable
        # pods, never touching the backoff queue (the starvation guard:
        # pods mid-retry cannot be evicted by a flood of fresh arrivals)
        # — or is itself rejected when nothing lower-priority remains.
        # Requeues (add_unschedulable / move_all_to_active) never shed:
        # they return a pod the scheduler already popped, so the bound
        # holds without them.
        self.capacity = capacity
        self.on_shed = on_shed
        # latency-tier classifier (classify_tier partial, typically wired
        # by a Scheduler with config.express_lane): pods it maps to
        # TIER_EXPRESS enter the express heap and surface ONLY through
        # pop_express_batch — pop()/pop_batch() keep serving the bulk
        # lane.  None = single-lane (every pod bulk, the legacy behavior).
        self.tier_of = tier_of
        # requeue observer (typically the scheduler's invariant checker,
        # runtime/invariants.py): called once per pod re-admitted through
        # ANY requeue seam — add_unschedulable(_batch) and readd — so
        # "every popped pod ends bound/requeued/shed" is checkable at the
        # one place all requeue paths funnel through.  Called OUTSIDE the
        # queue lock, like on_shed.  None = no observer (the default).
        self.on_requeue = on_requeue
        self.shed_total = 0
        # lower bound on the priority of any TRACKED pod (monotone under
        # admits, reset when the queue is observed empty): lets the
        # at-capacity shed check reject a can't-win arrival WITHOUT the
        # O(population) candidate scan — the storm hot path.  A stale-LOW
        # floor is always safe: it only means candidates are >= incoming,
        # which is exactly the reject-the-arrival case.
        self._prio_floor = float("inf")
        self._less = less
        self._lock = threading.Condition()
        self._counter = itertools.count()
        # queue-sharded replicas (ISSUE 14): the bulk lane is a LIST of
        # heaps, one per stable hash-shard (shard = crc32(ns/name) % N),
        # so N scheduler replicas each drain a disjoint slice of the
        # active population without contending on pop order.  shards=1
        # (the default) is the classic single-heap queue bit-for-bit;
        # pop()/pop_batch() without a shard argument pop the GLOBAL best
        # across all heaps (same priority-FIFO order as one heap).
        # Requeues return to the owner shard by construction (the shard
        # is a pure function of the pod key); the shed candidate scan and
        # the backoff starvation guard work over the entry maps, which
        # span every shard.
        self._shards_n = max(1, int(shards))
        self._active: List[List[list]] = [
            [] for _ in range(self._shards_n)
        ]                                      # per-shard [-prio, seq, pod, valid] heaps
        # express-lane heap: same entry layout and ordering as the bulk
        # heaps (a single cross-shard lane — the express interleave is
        # served by one replica); entries of ALL heaps share
        # _active_entry, so delete/shedding/depth accounting see one
        # active population
        self._express: List[list] = []
        self._active_entry: Dict[Tuple[str, str], list] = {}
        self._backoffq: List[list] = []        # [expiry, seq, pod, valid]
        self._backoff_entry: Dict[Tuple[str, str], list] = {}
        # key -> (pod, cycle, parked_at)
        self._unschedulable: Dict[Tuple[str, str], Tuple[Pod, int, float]] = {}
        # nominatedPods map (scheduling_queue.go:107-137): pods that preempted
        # victims and expect to land on a node; consulted by the two-pass fit
        # evaluation (generic_scheduler.go:598-664 podFitsOnNode)
        self._nominated: Dict[Tuple[str, str], Tuple[Pod, str]] = {}
        self.backoff = backoff or PodBackoff()
        self.scheduling_cycle = 0
        self.move_request_cycle = -1
        self._closed = False
        # key -> monotonic first-enqueue time (cleared on delete / taken at
        # bind-commit for the e2e_scheduling_duration histogram)
        self._enqueued_at: Dict[Tuple[str, str], float] = {}
        # displaced-pod shed protection (ISSUE 18): pods re-admitted via
        # readd_displaced (a lifecycle event revoked their binding) are
        # not shed candidates until their next pop — a mass drain must
        # not convert running pods into shed ones before the scheduler
        # gets one retry at placing them.  Cleared on pop and delete.
        self._shed_protected: set = set()

    # ---- sharding ----

    @staticmethod
    def shard_of(pod, of: int) -> int:
        """STABLE hash shard of a pod (or (ns, name) key) for an N-way
        split: crc32 of "ns/name" mod N — deterministic across processes
        and runs (python's hash() is seed-randomized), so a pod always
        lands on the same shard through add/delete/readd and every
        requeue returns it to its owner replica."""
        if of <= 1:
            return 0
        key = pod if isinstance(pod, tuple) else _pod_key(pod)
        return zlib.crc32(f"{key[0]}/{key[1]}".encode()) % of

    def _set_shards_locked(self, n: int) -> None:
        """Re-shard the bulk lane to n heaps (lock held): existing valid
        entries redistribute by their stable hash; entry OBJECTS are
        preserved so _active_entry identity (lazy deletion) still holds."""
        n = max(1, int(n))
        if n == self._shards_n:
            return
        entries = [e for h in self._active for e in h if e[_VALID]]
        self._shards_n = n
        self._active = [[] for _ in range(n)]
        for e in entries:
            heapq.heappush(
                self._active[self.shard_of(_pod_key(e[2]), n)], e
            )
        self._lock.notify_all()

    def set_shards(self, n: int) -> None:
        """Configure the bulk lane's shard count (SchedulerReplicaSet
        wires N = replica count).  Idempotent; safe while populated."""
        with self._lock:
            self._set_shards_locked(n)

    @property
    def shards(self) -> int:
        return self._shards_n

    # ---- internal (lock held) ----

    def _push_active(self, pod: Pod) -> None:
        key = _pod_key(pod)
        self._prio_floor = min(self._prio_floor, pod.spec.priority)
        # first-seen enqueue stamp: survives backoff/unschedulable requeues
        # so queue-add -> bind-commit latency covers the pod's whole wait
        # (the density SLO measures create -> scheduled the same way)
        self._enqueued_at.setdefault(key, time.monotonic())
        if key in self._active_entry:
            return
        if self._less is not None:
            from kubernetes_tpu.framework.v1alpha1 import PodInfo

            sort_key = _CmpKey(PodInfo(pod, time.monotonic()), self._less)
        else:
            sort_key = -pod.spec.priority
        entry = [sort_key, next(self._counter), pod, True]
        if self.tier_of is not None and self.tier_of(pod) == TIER_EXPRESS:
            heap = self._express
        else:
            heap = self._active[self.shard_of(key, self._shards_n)]
        heapq.heappush(heap, entry)
        self._active_entry[key] = entry

    def _push_backoff(self, pod: Pod, expiry: float) -> None:
        key = _pod_key(pod)
        self._prio_floor = min(self._prio_floor, pod.spec.priority)
        old = self._backoff_entry.get(key)
        if old is not None:
            old[_VALID] = False
        entry = [expiry, next(self._counter), pod, True]
        heapq.heappush(self._backoffq, entry)
        self._backoff_entry[key] = entry

    def _size_locked(self) -> int:
        return (
            len(self._active_entry)
            + len(self._backoff_entry)
            + len(self._unschedulable)
        )

    def _shed_candidate_locked(self, incoming: Pod) -> Optional[Tuple[str, str]]:
        """Pick the pod a full queue drops to admit `incoming`, or None
        when the arrival itself must be rejected.  Policy: lowest
        priority first; at equal priority, an unschedulable-parked pod
        (it already failed to place) is preferred over an active one and
        the longest-parked goes first; among active pods the YOUNGEST
        arrival is dropped (long-waiters keep their place).  Backoff
        entries are never candidates — the starvation guard: a pod
        mid-retry cannot be evicted by a flood of fresh arrivals.  The
        candidate sheds only if it is strictly lower priority than the
        arrival, or equal priority AND parked unschedulable."""
        # fast path: when the arrival cannot beat the tracked-priority
        # floor there is nothing to scan for (the common storm case —
        # thousands of equal-priority arrivals/s against a full queue
        # must not pay an O(population) scan under the lock each)
        if incoming.spec.priority < self._prio_floor or (
            incoming.spec.priority == self._prio_floor
            and not self._unschedulable
        ):
            return None
        now = time.monotonic()
        best = None  # (priority, class, tiebreak) + key
        for key, (pod, _, parked) in self._unschedulable.items():
            if key in self._shed_protected:
                continue  # displaced: not sheddable before one retry
            cand = (pod.spec.priority, 0, parked)
            if best is None or cand < best[0]:
                best = (cand, key)
        for key, entry in self._active_entry.items():
            if not entry[_VALID] or key in self._shed_protected:
                continue
            cand = (entry[2].spec.priority, 1,
                    -self._enqueued_at.get(key, now))
            if best is None or cand < best[0]:
                best = (cand, key)
        if best is None:
            return None
        (prio, cls, _), key = best
        if prio < incoming.spec.priority or (
            prio == incoming.spec.priority and cls == 0
        ):
            return key
        return None

    def _drop_locked(self, key: Tuple[str, str]) -> Pod:
        """Remove a shed victim from every structure (delete(), minus the
        backoff-entry half — victims are never in the backoff queue)."""
        rec = self._unschedulable.pop(key, None)
        if rec is not None:
            pod = rec[0]
        else:
            entry = self._active_entry.pop(key)
            entry[_VALID] = False
            pod = entry[2]
        self._nominated.pop(key, None)
        self.backoff.clear(key)
        self._enqueued_at.pop(key, None)
        return pod

    # ---- producers ----

    def add(self, pod: Pod) -> None:
        shed: List[Tuple[Pod, str]] = []
        with self._lock:
            if self._size_locked() == 0:
                # natural reset point for the priority floor: an empty
                # queue tracks nothing, so the bound starts over
                self._prio_floor = float("inf")
            key = _pod_key(pod)
            tracked = (
                key in self._active_entry
                or key in self._backoff_entry
                or key in self._unschedulable
            )
            admitted = True
            if (
                not tracked
                and self.capacity is not None
                and self._size_locked() >= self.capacity
            ):
                victim = self._shed_candidate_locked(pod)
                self.shed_total += 1
                if victim is None:
                    # nothing lower-priority is sheddable: the ARRIVAL is
                    # dropped (a higher-priority pod is never evicted for
                    # a lower-priority one)
                    shed.append((pod, SHED_ARRIVAL))
                    admitted = False
                else:
                    shed.append((self._drop_locked(victim), SHED_EVICTED))
            if admitted:
                self._unschedulable.pop(key, None)
                self._push_active(pod)
                self._lock.notify()
        # metric + callback OUTSIDE the lock: on_shed typically records an
        # Event (and must never deadlock against a queue re-entry)
        for p, reason in shed:
            m.QUEUE_SHED.inc(reason=reason)
            if self.on_shed is not None:
                self.on_shed(p, reason)

    def readd(self, pod: Pod) -> None:
        """Re-admit a pod the scheduler already POPPED (a gang's surplus
        member, rollback paths): EXEMPT from capacity shedding, like
        every other requeue — the pod was admitted once, and dropping it
        here would silently lose a popped pod."""
        with self._lock:
            self._unschedulable.pop(_pod_key(pod), None)
            self._push_active(pod)
            self._lock.notify()
        if self.on_requeue is not None:
            self.on_requeue(pod)

    def readd_displaced(self, pod: Pod) -> None:
        """Re-admit a pod whose BINDING a cluster-lifecycle event revoked
        (NodeLifecycleController eviction, a drain wave, a zone outage —
        ISSUE 18).  Shed-EXEMPT like every requeue — the pod was running,
        and a capacity drop here would turn a node drain into silent pod
        loss — and additionally shed-PROTECTED until its next pop: a
        displaced pod is never a shed candidate before the scheduler gets
        one retry at placing it (the mass-requeue guarantee a rolling
        drain leans on).  No on_requeue call: the pod was not popped by
        this scheduler's current conservation window — the displaced
        seam (InvariantChecker.note_displaced) already closed its bound
        mark, so this is a fresh admission, not a resolution."""
        key = _pod_key(pod)
        with self._lock:
            self._unschedulable.pop(key, None)
            self._shed_protected.add(key)
            self._push_active(pod)
            self._lock.notify()

    def _add_unschedulable_locked(self, pod: Pod, cycle: int) -> None:
        key = _pod_key(pod)
        self.backoff.boost(key)
        if self.move_request_cycle >= cycle:
            self._push_backoff(pod, self.backoff.backoff_time(key))
        else:
            self._unschedulable[key] = (pod, cycle, time.monotonic())
        self._lock.notify()

    def add_unschedulable(self, pod: Pod, cycle: int) -> None:
        """Failed-to-schedule pod (scheduling_queue.go AddUnschedulableIfNotPresent):
        if a move request happened after this pod's cycle began, it goes to
        backoff (a cluster event might have made it schedulable); otherwise it
        parks in unschedulableQ until an event or the 60s leftover flush."""
        with self._lock:
            self._add_unschedulable_locked(pod, cycle)
        if self.on_requeue is not None:
            self.on_requeue(pod)

    def add_unschedulable_batch(self, pods, cycle: int) -> None:
        """add_unschedulable for a whole failed batch under ONE lock
        acquisition (the batched commit path's loser requeue; the
        Condition wraps an RLock)."""
        if not pods:
            return
        with self._lock:
            for pod in pods:
                self._add_unschedulable_locked(pod, cycle)
        if self.on_requeue is not None:
            for pod in pods:
                self.on_requeue(pod)

    def move_all_to_active(self) -> None:
        """Cluster event: flush unschedulableQ (MoveAllToActiveQueue,
        scheduling_queue.go:73; wired from eventhandlers.go:319-378)."""
        with self._lock:
            self.move_request_cycle = self.scheduling_cycle
            for key, (pod, _, _) in list(self._unschedulable.items()):
                self._push_backoff(pod, self.backoff.backoff_time(key))
            self._unschedulable.clear()
            self._lock.notify()

    def tracks(self, pod: Pod) -> bool:
        """Membership across all three sub-queues (active/backoff/
        unschedulable) — the conservation scorer's "still queued"
        bucket (runtime/scenario.py): an unbound pod the queue does NOT
        track and that was never shed has been lost."""
        key = _pod_key(pod)
        with self._lock:
            return (
                key in self._active_entry
                or key in self._backoff_entry
                or key in self._unschedulable
            )

    def delete(self, pod: Pod) -> None:
        with self._lock:
            key = _pod_key(pod)
            self._unschedulable.pop(key, None)
            self._nominated.pop(key, None)
            entry = self._active_entry.pop(key, None)
            if entry is not None:
                entry[_VALID] = False
            entry = self._backoff_entry.pop(key, None)
            if entry is not None:
                entry[_VALID] = False
            self.backoff.clear(key)
            self._enqueued_at.pop(key, None)
            self._shed_protected.discard(key)

    def take_enqueue_time(self, pod: Pod) -> Optional[float]:
        """Pop and return the pod's first-enqueue monotonic timestamp (None
        if the pod never passed through this queue — e.g. direct
        schedule_cycle calls in tests)."""
        with self._lock:
            return self._enqueued_at.pop(_pod_key(pod), None)

    def take_enqueue_times(self, pods) -> List[Optional[float]]:
        """take_enqueue_time for a whole bound batch, one lock acquisition.

        The batched commit tail takes stamps BEFORE its bind fan-out: a
        bind's informer echo (pod update -> queue.delete) would otherwise
        race the take and drop the queue-wait from the e2e histogram."""
        with self._lock:
            return [self._enqueued_at.pop(_pod_key(p), None) for p in pods]

    def restore_enqueue_time(self, pod, t: Optional[float]) -> None:
        """Put back a stamp taken optimistically for a pod whose bind then
        failed: the requeued pod's eventual e2e must still cover its whole
        wait from FIRST enqueue (matching the per-pod loop, which only
        consumes the stamp on a successful bind)."""
        if t is None:
            return
        with self._lock:
            self._enqueued_at[_pod_key(pod)] = t

    def backlog_pods(self, limit: Optional[int] = None) -> List[Pod]:
        """READ-ONLY snapshot of every tracked pod — active (both
        lanes), backoff, and unschedulable-parked — under one lock
        acquisition, newest-admission-last within each tier.  The
        capacity planner's backlog source (runtime/capacity.py): what
        would the fleet need to place ALL of this?  `limit` bounds the
        walk (a 1M-pod storm queue must not be copied wholesale onto
        the scheduling thread)."""
        with self._lock:
            out: List[Pod] = [
                e[2] for e in self._active_entry.values() if e[_VALID]
            ]
            out += [
                e[2] for e in self._backoff_entry.values() if e[_VALID]
            ]
            out += [rec[0] for rec in self._unschedulable.values()]
        return out if limit is None else out[:limit]

    def has_nominated(self) -> bool:
        with self._lock:
            return bool(self._nominated)

    def active_depth(self) -> int:
        """Pods that will reach the active queue without an external
        cluster event (active + backoff entries): the adaptive-batch
        pressure signal (unschedulable-parked pods are excluded — they
        exert no demand until an event revives them)."""
        with self._lock:
            return len(self._active_entry) + len(self._backoff_entry)

    def has_schedulable(self) -> bool:
        """Anything that can reach the active queue WITHOUT an external
        cluster event: active entries, or backoff entries whose expiry the
        flusher will promote.  Unschedulable-parked pods don't count (they
        need move_all_to_active or the 60s leftover flush) — drain loops
        use this to stop instead of spinning on a parked remainder."""
        with self._lock:
            return bool(self._active_entry or self._backoff_entry)

    def delete_nominated_batch(self, pods) -> None:
        with self._lock:
            for pod in pods:
                self._nominated.pop(_pod_key(pod), None)

    # ---- nominated pods (UpdateNominatedPodForNode / DeleteNominatedPodIfExists) ----

    def update_nominated_pod(self, pod: Pod, node_name: str) -> None:
        with self._lock:
            self._nominated[_pod_key(pod)] = (pod, node_name)

    def delete_nominated_pod_if_exists(self, pod: Pod) -> None:
        with self._lock:
            self._nominated.pop(_pod_key(pod), None)

    def nominated_pods(self) -> List[Tuple[Pod, str]]:
        """Snapshot of (pod, nominated node name) pairs."""
        with self._lock:
            return list(self._nominated.values())

    def nominated_pods_for_node(self, node_name: str) -> List[Pod]:
        with self._lock:
            return [p for p, n in self._nominated.values() if n == node_name]

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    # ---- consumer ----

    def _flush(self, now: float) -> None:
        # expired backoff -> active
        while self._backoffq and (
            not self._backoffq[0][_VALID] or self._backoffq[0][0] <= now
        ):
            entry = heapq.heappop(self._backoffq)
            if not entry[_VALID]:
                continue
            pod = entry[2]
            key = _pod_key(pod)
            if self._backoff_entry.get(key) is entry:
                del self._backoff_entry[key]
            self._push_active(pod)
        # unschedulable leftovers past the 60s limit -> backoff
        # (flushUnschedulableQLeftover)
        for key, (pod, _, parked) in list(self._unschedulable.items()):
            if now - parked >= UNSCHEDULABLE_TIME_LIMIT:
                del self._unschedulable[key]
                self._push_backoff(pod, self.backoff.backoff_time(key))

    def _pop_from_locked(self, heap: List[list]) -> Optional[Pod]:
        """Pop the highest-priority valid entry from one lane's heap (lock
        held); None when the heap holds only lazily-deleted entries."""
        while heap:
            entry = heapq.heappop(heap)
            if not entry[_VALID]:
                continue
            pod = entry[2]
            key = _pod_key(pod)
            if self._active_entry.get(key) is entry:
                del self._active_entry[key]
            # the displaced pod got its retry: normal shed policy resumes
            self._shed_protected.discard(key)
            self.scheduling_cycle += 1
            return pod
        return None

    def _pop_bulk_locked(self, shard: Optional[int]) -> Optional[Pod]:
        """Pop the best valid bulk entry (lock held).  shard=None pops the
        GLOBAL best across every shard heap (identical order to a single
        heap: entries compare by [sort_key, seq], and seq is unique);
        shard=i pops only shard i's heap (a replica's slice)."""
        if shard is not None:
            return self._pop_from_locked(self._active[shard])
        if self._shards_n == 1:
            return self._pop_from_locked(self._active[0])
        best_h = None
        for h in self._active:
            while h and not h[0][_VALID]:  # shed dead heads before compare
                heapq.heappop(h)
            if h and (best_h is None or h[0][:2] < best_h[0][:2]):
                best_h = h
        if best_h is None:
            return None
        return self._pop_from_locked(best_h)

    def _express_ready_locked(self) -> bool:
        """Any valid express entry pending?  (Lock held; sheds the heap's
        lazily-deleted head entries as a side effect, so the check stays
        O(dead entries), not O(heap).)"""
        h = self._express
        while h and not h[0][_VALID]:
            heapq.heappop(h)
        return bool(h)

    def pop(self, timeout: Optional[float] = None,
            yield_to_express: bool = False,
            shard: Optional[int] = None,
            of: Optional[int] = None) -> Optional[Pod]:
        """Blocking pop from the BULK lane.  With yield_to_express, an
        express arrival interrupts the wait (returns None) so the tiered
        run loop can serve the express lane instead of letting a
        latency-sensitive pod sit out the bulk poll timeout.

        shard=i (with of=N) pops only pods whose stable hash-shard is i —
        the queue re-shards itself to N heaps on first use, so N replica
        consumers drain disjoint slices; a replica's blocking wait still
        wakes on any arrival and re-checks only its own shard."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            if of is not None and of != self._shards_n:
                self._set_shards_locked(of)
            if shard is not None and not (0 <= shard < self._shards_n):
                raise ValueError(
                    f"shard {shard} out of range for {self._shards_n} shards"
                )
            while True:
                self._flush(time.monotonic())
                pod = self._pop_bulk_locked(shard)
                if pod is not None:
                    return pod
                if yield_to_express and self._express_ready_locked():
                    return None
                if self._closed:
                    return None
                wait = None
                if self._backoffq:
                    wait = max(self._backoffq[0][0] - time.monotonic(), 0.01)
                if self._unschedulable:
                    oldest = min(t for _, _, t in self._unschedulable.values())
                    leftover = max(oldest + UNSCHEDULABLE_TIME_LIMIT - time.monotonic(), 0.01)
                    wait = leftover if wait is None else min(wait, leftover)
                if deadline is not None:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        return None
                    wait = remain if wait is None else min(wait, remain)
                self._lock.wait(wait)

    def pop_batch(self, max_batch: int, timeout: Optional[float] = None,
                  batch_window: float = 0.0,
                  yield_to_express: bool = False,
                  shard: Optional[int] = None,
                  of: Optional[int] = None) -> List[Pod]:
        """Drain up to max_batch pods; waits `timeout` for the first pod then
        `batch_window` more for stragglers (deadline-driven batch formation).
        yield_to_express (tiered run loop): an express arrival cuts both the
        first-pod wait and the straggler window short.  shard=i, of=N
        (ISSUE 14): drain only the stable hash-shard i of an N-way split —
        the scheduler-replica consumer API."""
        out = []
        first = self.pop(timeout, yield_to_express=yield_to_express,
                         shard=shard, of=of)
        if first is None:
            return out
        out.append(first)
        deadline = time.monotonic() + batch_window
        while len(out) < max_batch:
            remain = deadline - time.monotonic()
            nxt = self.pop(max(remain, 0.0) if batch_window else 0.0,
                           yield_to_express=yield_to_express, shard=shard)
            if nxt is None:
                break
            out.append(nxt)
        return out

    def pop_express_batch(self, max_batch: int) -> List[Pod]:
        """Drain up to max_batch pods from the EXPRESS lane, non-blocking
        (the tiered run loop polls this before every bulk pop; express
        batch formation never waits — a latency tier that batches by
        timer would re-create the latency it exists to remove)."""
        out: List[Pod] = []
        with self._lock:
            self._flush(time.monotonic())
            while len(out) < max_batch:
                pod = self._pop_from_locked(self._express)
                if pod is None:
                    break
                out.append(pod)
        return out

    def express_depth(self) -> int:
        """Valid express-lane entries pending (observability/tests)."""
        with self._lock:
            self._express_ready_locked()
            return sum(1 for e in self._express if e[_VALID])

    def __len__(self) -> int:
        with self._lock:
            return (
                len(self._active_entry)
                + len(self._backoff_entry)
                + len(self._unschedulable)
            )
