"""Controllers: the reconcile layer over the LocalCluster blackboard.

The reference runs ~30 reconcilers sharing one shape (SURVEY.md section 3.5;
list at cmd/kube-controller-manager/app/controllermanager.go:372-413):

  informer event -> workqueue.Add(key)
  worker: key := queue.Get() -> sync<Kind>(key):
      desired (lister) vs observed (lister) -> diff -> client writes
      error -> queue.AddRateLimited(key)

Implemented here:
  * WorkQueue — the client-go util/workqueue analog (dedup while queued,
    mark-dirty while processing, per-key exponential requeue backoff).
  * ReplicaSetController — pkg/controller/replicaset: keeps
    spec.replicas pods matching the selector alive; creates through the
    store (so the scheduler sees them) and deletes surplus.  This is the
    controller-created-pods density pattern of test/utils/runners.go:1118
    (NewSimpleWithControllerCreatePodStrategy).
  * NodeLifecycleController — pkg/controller/nodelifecycle: watches node
    lease heartbeats ("kube-node-lease" objects in the store); a node whose
    lease outlives the grace period is marked NotReady + tainted
    unreachable:NoExecute, and its pods are evicted (deleted) so owning
    controllers replace them elsewhere.  Recovery removes the taint.

Everything communicates through LocalCluster create/update/delete + watch —
no controller talks to another directly (blackboard architecture).
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from kubernetes_tpu.api import labels as klabels
from kubernetes_tpu.api.types import (
    Node,
    Pod,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    Taint,
)
from kubernetes_tpu.runtime.cluster import (
    ADDED,
    DELETED,
    DISPLACED_BY_ANNOTATION,
    MODIFIED,
    ConflictError,
    LocalCluster,
)

TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_NOT_READY = "node.kubernetes.io/not-ready"
LEASE_NAMESPACE = "kube-node-lease"

# NodeLifecycleController eviction modes (ISSUE 18): "delete" is the
# reference behavior (TaintBasedEviction deletes; owning controllers
# recreate), "displace" revokes the binding in place — the pod keeps its
# identity, gets the displaced-by annotation, and re-enters the
# scheduling queue through the shed-exempt displaced requeue path
# (wire_scheduler), so a node loss is a mass RESCHEDULE of the same
# pods, trackable end to end by the invariant checker
EVICT_DELETE = "delete"
EVICT_DISPLACE = "displace"


class EvictionBlocked(Exception):
    """A PDB vetoed the eviction (the 429 TooManyRequests analog of the
    pods/eviction subresource).  Carries the Retry-After pacing hint and
    the blocking budget's name so drain loops can back off instead of
    spinning — apiserver/server.py constructs the same refusal over HTTP."""

    def __init__(self, pdb_name: str, retry_after_s: float):
        super().__init__(
            "Cannot evict pod as it would violate the pod's disruption "
            f"budget {pdb_name!r}"
        )
        self.pdb_name = pdb_name
        self.retry_after_s = retry_after_s


def try_evict(cluster: LocalCluster, pod: Pod, *,
              mode: str = EVICT_DELETE,
              reason: str = "eviction",
              retry_after_s: float = 1.0,
              invariants=None) -> bool:
    """The pods/eviction subresource's store-level analog (registry/core/
    pod/rest/eviction.go; the HTTP twin lives in apiserver/server.py):
    grant the eviction only if every PDB matching the pod still allows a
    disruption, consuming one unit of each matching budget immediately
    (the async DisruptionController recompute closes behind it — the
    thundering-drain race the reference decrements against too).

    Blocked -> raises EvictionBlocked carrying `retry_after_s` (the
    Retry-After pacing a drain wave must honor); granted -> True after
    deleting (EVICT_DELETE) or displacing (EVICT_DISPLACE, ISSUE 18) the
    pod; False when the pod is already gone/unbound (nothing to evict).
    The PDB check + budget decrement + pod write run under the store
    lock, exactly like the apiserver path runs under its write lock."""
    with cluster._lock:
        cur = cluster.get("pods", pod.namespace, pod.name)
        if cur is None:
            return False
        matching = [
            pdb for pdb in cluster.list("poddisruptionbudgets")
            if pdb.namespace == pod.namespace and pdb.matches(cur)
        ]
        blocked = next(
            (p.name for p in matching if p.disruptions_allowed <= 0), None
        )
        if blocked is not None:
            raise EvictionBlocked(blocked, retry_after_s)
        debited = 0
        for pdb in matching:
            cluster.update(
                "poddisruptionbudgets",
                dataclasses.replace(
                    pdb,
                    disruptions_allowed=max(0, pdb.disruptions_allowed - 1),
                ),
            )
            debited += 1
        if mode == EVICT_DISPLACE:
            granted = cluster.displace_pod(cur, reason)
        else:
            cluster.delete("pods", pod.namespace, pod.name)
            granted = True
    # RULE_EVICTION_BUDGET audit (ISSUE 19): report the grant OUTSIDE the
    # store lock — note_evicted takes the checker's own lock and may fire
    # callbacks; nesting it under cluster._lock invites the AB/BA deadlock
    # the checker's _pending_cb design exists to avoid
    if granted and invariants is not None:
        invariants.note_evicted(cur, len(matching), debited)
    return granted


def cordon_node(cluster: LocalCluster, node_name: str) -> bool:
    """kubectl cordon: spec.unschedulable = True (the scheduler's
    node-unschedulable filter stops NEW placements; running pods stay
    until evicted).  Returns True when this call flipped the bit."""
    node = cluster.get("nodes", "", node_name)
    if node is None or node.spec.unschedulable:
        return False
    cluster.update(
        "nodes",
        dataclasses.replace(
            node,
            spec=dataclasses.replace(node.spec, unschedulable=True),
        ),
    )
    return True


def uncordon_node(cluster: LocalCluster, node_name: str) -> bool:
    """Undo a cordon (post-upgrade / rollback return to service)."""
    node = cluster.get("nodes", "", node_name)
    if node is None or not node.spec.unschedulable:
        return False
    cluster.update(
        "nodes",
        dataclasses.replace(
            node,
            spec=dataclasses.replace(node.spec, unschedulable=False),
        ),
    )
    return True


def drain_waves(
    cluster: LocalCluster,
    nodes: List[str],
    *,
    wave_size: int = 2,
    mode: str = EVICT_DISPLACE,
    retry_rounds: int = 8,
    retry_after_s: float = 0.05,
    cordon: bool = True,
    reason: str = "drain",
    invariants=None,
    abort: Optional[Callable[[], bool]] = None,
) -> dict:
    """The ONE cordon+evict+Retry-After wave loop (ISSUE 19 satellite):
    chaos.Disruptions.rolling_drain (the upgrade monkey) and the
    autoscaler's scale-down actuation both delegate here so the two
    drain paths cannot drift.  Cordon each node in a wave of
    `wave_size`, then push its pending pods through the PDB-respecting
    eviction seam (try_evict — the pods/eviction subresource's 429 +
    Retry-After semantics).

    A PDB-blocked eviction is retried up to `retry_rounds` times, each
    round paced by the refusal's Retry-After hint (capped at
    `retry_after_s` so tests stay fast) — bounded progress, never a
    spin.  Pods still blocked after the rounds are SKIPPED: the wave
    records them, emits a DrainBlocked Warning event on the node, and
    moves on.  `abort` (checked between rounds and waves) lets a caller
    with a deadline — the autoscaler's stuck-drain rollback — stop the
    loop early; remaining pods land in "skipped" without the event, and
    the result carries aborted=True so the caller knows to uncordon.

    Returns {"order", "waves", "evicted", "blocked_retries", "skipped",
    "aborted"} — skipped non-empty means PDBs (or the abort) held the
    line."""
    nodes = list(nodes)
    wave_size = max(1, int(wave_size))
    evicted: List[tuple] = []
    skipped: List[tuple] = []
    retries = 0
    waves = 0
    aborted = False
    for w0 in range(0, len(nodes), wave_size):
        if abort is not None and abort():
            aborted = True
            break
        wave = nodes[w0:w0 + wave_size]
        waves += 1
        if cordon:
            for name in wave:
                cordon_node(cluster, name)
        pending = [
            p for p in cluster.list("pods")
            if p.spec.node_name in wave
            and p.status.phase not in ("Succeeded", "Failed")
        ]
        for round_i in range(retry_rounds + 1):
            if abort is not None and abort():
                aborted = True
                break
            blocked: List[tuple] = []
            pause = 0.0
            for p in pending:
                try:
                    if try_evict(cluster, p, mode=mode, reason=reason,
                                 retry_after_s=retry_after_s,
                                 invariants=invariants):
                        evicted.append((p.namespace, p.name,
                                        p.spec.node_name))
                except EvictionBlocked as e:
                    blocked.append((p, e))
                    pause = max(pause, min(e.retry_after_s,
                                           retry_after_s))
            if not blocked:
                pending = []
                break
            pending = [p for p, _ in blocked]
            retries += len(blocked)
            if round_i < retry_rounds and pause > 0:
                time.sleep(pause)  # the Retry-After pacing bound
        for p in pending:  # budget never reopened: skip, don't spin
            skipped.append((p.namespace, p.name, p.spec.node_name))
            if not aborted:
                cluster.events.eventf(
                    "Node", "", p.spec.node_name, "Warning",
                    "DrainBlocked",
                    "pod %s/%s eviction blocked by PDB after %d rounds; "
                    "skipping", p.namespace, p.name, retry_rounds,
                )
        if aborted:
            break
    return {
        "order": nodes,
        "waves": waves,
        "evicted": evicted,
        "blocked_retries": retries,
        "skipped": skipped,
        "aborted": aborted,
    }


# ---------------------------------------------------------------- workqueue


class WorkQueue:
    """client-go util/workqueue: a key queued twice before processing is
    worked once; a key re-added DURING processing is re-queued after done()
    (the dirty set); add_rate_limited applies per-key exponential delay."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1.0):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._dirty: Set = set()
        self._processing: Set = set()
        self._failures: Dict = {}
        self._base, self._max = base_delay, max_delay
        self._closed = False

    def add(self, key) -> None:
        with self._cond:
            if key in self._dirty:
                return
            self._dirty.add(key)
            if key in self._processing:
                return
            self._queue.append(key)
            self._cond.notify()

    def add_rate_limited(self, key) -> None:
        with self._lock:
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
            delay = min(self._base * (2 ** n), self._max)
        t = threading.Timer(delay, self.add, args=(key,))
        t.daemon = True
        t.start()

    def forget(self, key) -> None:
        with self._lock:
            self._failures.pop(key, None)

    def get(self, timeout: Optional[float] = None):
        with self._cond:
            deadline = time.monotonic() + timeout if timeout is not None else None
            while not self._queue:
                if self._closed:
                    return None
                left = None if deadline is None else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return None
                self._cond.wait(left)
            key = self._queue.popleft()
            self._dirty.discard(key)
            self._processing.add(key)
            return key

    def done(self, key) -> None:
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty:
                self._queue.append(key)
                self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)


# ---------------------------------------------------------------- reconciler


class Reconciler:
    """The shared controller worker shape (SURVEY.md section 3.5): a
    WorkQueue of keys + sync(key), with rate-limited requeue on ANY error
    (client-go HandleError semantics — a bad object must not kill the
    thread).  Subclasses implement sync() and enqueue from watch events.

    Event source: by default the store's raw watch (embedded mode); when
    an informer factory is passed AND the subclass declares WATCH_KINDS,
    events arrive through per-kind shared informers instead — the
    reference's informer->workqueue->reconcile pipeline
    (shared_informer.go handlers feeding controller workqueues), which
    also decouples handler latency from the store's write lock."""

    #: kinds this controller subscribes to via informers (empty =
    #: firehose raw watch; the informer path needs the explicit list)
    WATCH_KINDS: Tuple[str, ...] = ()

    def __init__(self, cluster: LocalCluster, informers=None):
        self.cluster = cluster
        self.queue = WorkQueue()
        if informers is not None and self.WATCH_KINDS:
            for kind in self.WATCH_KINDS:
                informers.informer(kind).add_event_handler(
                    on_add=lambda o, k=kind: self._on_event(ADDED, k, o),
                    on_update=lambda _old, new, k=kind: self._on_event(
                        MODIFIED, k, new),
                    on_delete=lambda o, k=kind: self._on_event(
                        DELETED, k, o),
                )
        else:
            cluster.watch(self._on_event)

    def _on_event(self, event: str, kind: str, obj) -> None:  # pragma: no cover
        raise NotImplementedError

    def sync(self, key) -> None:  # pragma: no cover
        raise NotImplementedError

    def process_one(self, timeout: float = 0.2) -> bool:
        key = self.queue.get(timeout)
        if key is None:
            return False
        try:
            self.sync(key)
            self.queue.forget(key)
        except Exception:
            self.queue.add_rate_limited(key)
        finally:
            self.queue.done(key)
        return True

    def run(self, stop: threading.Event, workers: int = 1) -> List[threading.Thread]:
        def worker():
            while not stop.is_set():
                self.process_one(timeout=0.05)

        threads = [
            threading.Thread(target=worker, daemon=True) for _ in range(workers)
        ]
        for t in threads:
            t.start()
        return threads


# --------------------------------------------------------------- ReplicaSet


@dataclass
class ReplicaSet:
    """The scheduler-relevant slice of apps/v1 ReplicaSet."""

    namespace: str
    name: str
    replicas: int
    selector: Dict[str, str]                 # matchLabels
    template: dict                           # pod dict (k8s JSON form); its
                                             # metadata.labels must satisfy
                                             # the selector
    uid: str = field(default_factory=lambda: uuid.uuid4().hex)
    owner_uid: str = ""   # owning Deployment's uid ("" = standalone)
    # deployment.kubernetes.io/revision etc. (rollout history reads it)
    annotations: Dict[str, str] = field(default_factory=dict)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)


REVISION_ANNOTATION = "deployment.kubernetes.io/revision"


class ControllerExpectations:
    """pkg/controller/controller_utils.go ControllerExpectations: a sync
    that just created/deleted N children must not run again until the
    watch has delivered those N events — otherwise a controller reading a
    LAGGING cache (the remote-mirror deployment) sees stale counts and
    over-creates.  Expectations expire after a timeout so one lost event
    can't wedge a key forever (ExpectationsTimeout, 5 min there)."""

    TIMEOUT = 60.0

    def __init__(self):
        self._lock = threading.Lock()
        self._exp: Dict[object, List[float]] = {}  # key -> [adds, dels, t0]

    def expect(self, key, adds: int = 0, dels: int = 0) -> None:
        with self._lock:
            self._exp[key] = [float(adds), float(dels), time.monotonic()]

    def creation_observed(self, key) -> None:
        with self._lock:
            e = self._exp.get(key)
            if e is not None and e[0] > 0:
                e[0] -= 1

    def deletion_observed(self, key) -> None:
        with self._lock:
            e = self._exp.get(key)
            if e is not None and e[1] > 0:
                e[1] -= 1

    def satisfied(self, key) -> bool:
        with self._lock:
            e = self._exp.get(key)
            if e is None:
                return True
            if e[0] <= 0 and e[1] <= 0:
                del self._exp[key]
                return True
            if time.monotonic() - e[2] > self.TIMEOUT:
                del self._exp[key]  # lost event: give up and resync
                return True
            return False


class ReplicaSetController(Reconciler):
    """pkg/controller/replicaset syncReplicaSet: observed = store pods owned
    by the RS (owner_uid) and matching the selector; diff against
    spec.replicas; create/delete through the store.

    The class is kind-parameterized: ReplicationControllerController below
    reuses the whole reconcile (the reference's replication controller is
    the same loop over the older core/v1 kind,
    pkg/controller/replication/replication_controller.go delegating to
    replicaset.NewBaseController)."""

    KIND = "replicasets"
    OWNER_KIND = "ReplicaSet"
    WATCH_KINDS = ("replicasets", "pods")

    def __init__(self, cluster: LocalCluster, informers=None):
        self._seq = 0
        self.expectations = ControllerExpectations()
        super().__init__(cluster, informers=informers)

    # ------------------------------------------------------ informer seam

    def _resolve_owner(self, obj):
        for rs in self.cluster.list(self.KIND):
            if rs.uid == obj.metadata.owner_uid:
                return rs
        return None

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == self.KIND:
            self.queue.add(obj.key)
        elif kind == "pods" and getattr(obj.metadata, "owner_uid", ""):
            # resolve owner RS by uid (resolveControllerRef)
            rs = self._resolve_owner(obj)
            if rs is not None:
                if event == ADDED:
                    self.expectations.creation_observed(rs.key)
                elif event == DELETED:
                    self.expectations.deletion_observed(rs.key)
                self.queue.add(rs.key)

    # ------------------------------------------------------------- sync

    def _owned_pods(self, rs: ReplicaSet) -> List[Pod]:
        # FilterActivePods: terminal pods don't count toward replicas, so an
        # Evicted (Failed) pod gets replaced
        sel = klabels.selector_from_match_labels(rs.selector)
        return [
            p for p in self.cluster.list("pods")
            if p.namespace == rs.namespace
            and p.metadata.owner_uid == rs.uid
            and sel.matches(p.labels)
            and p.status.phase not in ("Succeeded", "Failed")
        ]

    def sync(self, key: Tuple[str, str]) -> None:
        ns, name = key
        rs = self.cluster.get(self.KIND, ns, name)
        if rs is None:
            # deleted: cascade-delete pods whose owner uid no longer
            # resolves to a live owner (the garbagecollector analog)
            live = {r.uid for r in self.cluster.list(self.KIND)}
            for p in self.cluster.list("pods"):
                if (
                    p.namespace == ns
                    and p.metadata.owner_kind == self.OWNER_KIND
                    and p.metadata.owner_uid not in live
                ):
                    self.cluster.delete("pods", p.namespace, p.name)
            return
        if not self.expectations.satisfied(key):
            # a previous sync's creates/deletes haven't round-tripped the
            # watch yet (remote mirror lag): acting on stale counts would
            # over-create — requeue and wait (syncReplicaSet's
            # rsNeedsSync gate)
            self.queue.add_rate_limited(key)
            return
        owned = self._owned_pods(rs)
        diff = rs.replicas - len(owned)
        if diff > 0:
            self.expectations.expect(key, adds=diff)
            done = 0
            try:
                for _ in range(diff):
                    self._seq += 1
                    d = dict(rs.template)
                    meta = dict(d.get("metadata") or {})
                    meta["name"] = f"{rs.name}-{self._seq:05d}"
                    meta["namespace"] = rs.namespace
                    meta["ownerReferences"] = [
                        {"kind": self.OWNER_KIND, "name": rs.name,
                         "uid": rs.uid, "controller": True}
                    ]
                    d["metadata"] = meta
                    self.cluster.create("pods", Pod.from_dict(d))
                    done += 1
            finally:
                # a failed create produces no watch event: lower the
                # expectation for every pod NOT created, or the key stalls
                # until the expectations timeout (controller_utils.go
                # CreationObserved on failure)
                for _ in range(diff - done):
                    self.expectations.creation_observed(key)
        elif diff < 0:
            # delete surplus: prefer unassigned, then youngest (the
            # getPodsToDelete ranking, abbreviated; names carry the creation
            # sequence so name-descending = youngest-first)
            self.expectations.expect(key, dels=-diff)
            owned.sort(key=lambda p: p.name, reverse=True)
            owned.sort(key=lambda p: bool(p.spec.node_name))  # stable
            done = 0
            try:
                for p in owned[:-diff]:
                    self.cluster.delete("pods", p.namespace, p.name)
                    done += 1
            finally:
                for _ in range(-diff - done):
                    self.expectations.deletion_observed(key)


def add_replicaset(cluster: LocalCluster, rs: ReplicaSet) -> None:
    cluster.create("replicasets", rs)


@dataclass
class ReplicationController(ReplicaSet):
    """core/v1 ReplicationController: the pre-apps workload kind — same
    reconcile semantics as ReplicaSet with a plain-map selector
    (pkg/apis/core/types.go ReplicationControllerSpec.Selector)."""


class ReplicationControllerController(ReplicaSetController):
    """pkg/controller/replication: replicaset.NewBaseController over the
    core kind."""

    KIND = "replicationcontrollers"
    OWNER_KIND = "ReplicationController"
    WATCH_KINDS = ("replicationcontrollers", "pods")


# ------------------------------------------------------------ node lifecycle


def renew_node_lease(cluster: LocalCluster, node_name: str,
                     now: Optional[float] = None) -> None:
    """The kubelet heartbeat (NodeLease): upsert the node's lease object
    with renewTime = now."""
    now = time.monotonic() if now is None else now
    lease = {"namespace": LEASE_NAMESPACE, "name": node_name, "renew_time": now}
    try:
        cluster.create("leases", lease)
    except ConflictError:
        cluster.update("leases", lease)


class NodeLifecycleController:
    """pkg/controller/nodelifecycle, lease-heartbeat slice: monitor() is the
    monitorNodeHealth tick — nodes with expired leases get Ready=False +
    the unreachable NoExecute taint and their pods evicted; recovered nodes
    are restored.  Drive monitor(now) from a loop or directly in tests."""

    def __init__(self, cluster: LocalCluster, grace_period: float = 40.0,
                 eviction_mode: str = EVICT_DELETE):
        if eviction_mode not in (EVICT_DELETE, EVICT_DISPLACE):
            raise ValueError(
                f"eviction_mode {eviction_mode!r}: "
                f"expected {EVICT_DELETE!r} or {EVICT_DISPLACE!r}"
            )
        self.cluster = cluster
        self.grace = grace_period
        # "delete" = the reference TaintBasedEviction (controllers
        # recreate); "displace" = revoke the binding in place so the SAME
        # pod re-enters the scheduling queue shed-exempt (ISSUE 18)
        self.eviction_mode = eviction_mode
        self.evictions: List[Tuple[str, str, str]] = []  # (ns, pod, node)

    def _lease_age(self, node_name: str, now: float) -> Optional[float]:
        lease = self.cluster.get("leases", LEASE_NAMESPACE, node_name)
        if lease is None:
            return None
        return now - lease["renew_time"]

    @staticmethod
    def _is_tainted(node: Node) -> bool:
        return any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)

    @staticmethod
    def _has_not_ready(node: Node) -> bool:
        return any(t.key == TAINT_NOT_READY for t in node.spec.taints)

    def monitor(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        for node in self.cluster.list("nodes"):
            age = self._lease_age(node.name, now)
            if age is None:
                continue  # never heartbeated: agent not started yet
            if age > self.grace:
                if not self._is_tainted(node):
                    self._mark_unreachable(node)
                else:
                    # the NoExecute taint manager evicts CONTINUOUSLY: a pod
                    # that slipped onto an already-tainted node (bind raced
                    # the taint) goes next tick
                    self._evict_pods(node)
            elif age <= self.grace and (
                self._is_tainted(node) or self._has_not_ready(node)
            ):
                # a heartbeating node sheds BOTH condition taints: the
                # unreachable pair this controller added and the
                # registration not-ready taint the TaintNodesByCondition
                # admission plugin added (nodetaint/admission.go — the
                # reference's nodelifecycle reconciles condition taints,
                # nodelifecycle/node_lifecycle_controller.go taintMap)
                self._restore(node)

    def _mark_unreachable(self, node: Node) -> None:
        tainted = dataclasses.replace(
            node,
            spec=dataclasses.replace(
                node.spec,
                taints=tuple(node.spec.taints) + (
                    Taint(key=TAINT_UNREACHABLE, value="",
                          effect=TAINT_NO_EXECUTE),
                    Taint(key=TAINT_UNREACHABLE, value="",
                          effect=TAINT_NO_SCHEDULE),
                ),
            ),
            status=dataclasses.replace(
                node.status,
                conditions={**node.status.conditions, "Ready": "Unknown"},
            ),
        )
        self.cluster.update("nodes", tainted)
        self.cluster.events.eventf(
            "Node", "", node.name, "Warning", "NodeNotReady",
            "lease expired; tainting %s", TAINT_UNREACHABLE,
        )
        self._evict_pods(node)

    def _evict_pods(self, node: Node) -> None:
        # TaintBasedEviction: NoExecute evicts everything without a matching
        # toleration (zero tolerationSeconds path)
        for p in self.cluster.list("pods"):
            if (
                p.spec.node_name == node.name
                and p.status.phase not in ("Succeeded", "Failed")
                and not _tolerates_noexecute(p)
            ):
                if self.eviction_mode == EVICT_DISPLACE:
                    if not self.cluster.displace_pod(p, "node-lifecycle"):
                        continue  # already unbound/gone: nothing to do
                else:
                    self.cluster.delete("pods", p.namespace, p.name)
                self.evictions.append((p.namespace, p.name, node.name))

    def _restore(self, node: Node) -> None:
        restored = dataclasses.replace(
            node,
            spec=dataclasses.replace(
                node.spec,
                taints=tuple(
                    t for t in node.spec.taints
                    if t.key not in (TAINT_UNREACHABLE, TAINT_NOT_READY)
                ),
            ),
            status=dataclasses.replace(
                node.status,
                conditions={**node.status.conditions, "Ready": "True"},
            ),
        )
        self.cluster.update("nodes", restored)
        self.cluster.events.eventf(
            "Node", "", node.name, "Normal", "NodeReady", "lease renewed"
        )

    def run(self, stop: threading.Event, period: float = 5.0) -> threading.Thread:
        def loop():
            while not stop.is_set():
                self.monitor()
                stop.wait(period)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t


def _tolerates_noexecute(pod: Pod) -> bool:
    taint = Taint(key=TAINT_UNREACHABLE, value="", effect=TAINT_NO_EXECUTE)
    return any(t.tolerates(taint) for t in pod.spec.tolerations)


class ControllerManager:
    """cmd/kube-controller-manager shape: start every controller against one
    cluster; stop() tears all of them down."""

    def __init__(self, cluster: LocalCluster, grace_period: float = 40.0,
                 use_informers: bool = False, csr_ca=None):
        self.cluster = cluster
        self.informers = None
        if use_informers:
            # the reference wiring: one shared informer factory, each
            # controller subscribing per-kind (controllermanager.go builds
            # a SharedInformerFactory handed to every controller ctor)
            from kubernetes_tpu.client.informer import SharedInformerFactory

            self.informers = SharedInformerFactory(cluster)
        self.replicaset = ReplicaSetController(cluster,
                                               informers=self.informers)
        self.replication = ReplicationControllerController(
            cluster, informers=self.informers)
        self.nodelifecycle = NodeLifecycleController(cluster, grace_period)
        self.disruption = DisruptionController(cluster)
        self.deployment = DeploymentController(cluster)
        self.job = JobController(cluster)
        from kubernetes_tpu.runtime.network import EndpointsController

        self.endpoints = EndpointsController(cluster)
        self.namespace = NamespaceController(cluster)
        self.gc = GarbageCollector(cluster)
        self.podgc = PodGCController(cluster)
        self.quota = ResourceQuotaController(cluster)
        self.daemonset = DaemonSetController(cluster)
        self.statefulset = StatefulSetController(cluster)
        self.cronjob = CronJobController(cluster)
        self.hpa = HPAController(cluster)
        self.ttl = TTLAfterFinishedController(cluster)
        from kubernetes_tpu.runtime.volumecontrollers import (
            AttachDetachController,
            PersistentVolumeController,
            ServiceAccountController,
            TokenController,
        )

        from kubernetes_tpu.runtime.volumecontrollers import (
            NodeIpamController,
            TokenCleaner,
        )

        self.pv = PersistentVolumeController(cluster,
                                             informers=self.informers)
        from kubernetes_tpu.runtime.certificates import CSRApproverSigner

        self.tokencleaner = TokenCleaner(cluster, informers=self.informers)
        self.csr = CSRApproverSigner(cluster, ca=csr_ca,
                                     informers=self.informers)
        self.nodeipam = NodeIpamController(cluster,
                                           informers=self.informers)
        self.attachdetach = AttachDetachController(cluster,
                                                   informers=self.informers)
        self.serviceaccount = ServiceAccountController(
            cluster, informers=self.informers)
        self.token = TokenController(cluster, informers=self.informers)
        from kubernetes_tpu.runtime.protection import (
            BootstrapSigner,
            ClusterRoleAggregationController,
            CSRCleaner,
            ExpandController,
            NodeTTLController,
            PVCProtectionController,
            PVProtectionController,
            RootCACertPublisher,
        )

        self.pvcprotection = PVCProtectionController(
            cluster, informers=self.informers)
        self.pvprotection = PVProtectionController(
            cluster, informers=self.informers)
        self.clusterroleagg = ClusterRoleAggregationController(
            cluster, informers=self.informers)
        self.nodettl = NodeTTLController(cluster, informers=self.informers)
        self.bootstrapsigner = BootstrapSigner(
            cluster, informers=self.informers)
        self.csrcleaner = CSRCleaner(cluster)
        self.expand = ExpandController(cluster, informers=self.informers)
        self.rootca = RootCACertPublisher(cluster, informers=self.informers)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def start(self, rs_workers: int = 2, monitor_period: float = 5.0) -> None:
        if self.informers is not None:
            self.informers.start()
            self.informers.wait_for_cache_sync(30.0)
        self._threads += self.replicaset.run(self._stop, workers=rs_workers)
        self._threads += self.replication.run(self._stop)
        self._threads.append(
            self.nodelifecycle.run(self._stop, period=monitor_period)
        )
        self._threads += self.disruption.run(self._stop)
        self._threads += self.deployment.run(self._stop)
        self._threads += self.job.run(self._stop)
        self._threads += self.endpoints.run(self._stop)
        self._threads += self.namespace.run(self._stop)
        self._threads += self.gc.run(self._stop)
        self._threads.append(self.podgc.run(self._stop))
        self._threads += self.quota.run(self._stop)
        self._threads += self.daemonset.run(self._stop)
        self._threads += self.statefulset.run(self._stop)
        self._threads.append(self.cronjob.run(self._stop))
        self._threads.append(self.hpa.run(self._stop))
        self._threads.append(self.ttl.run(self._stop))
        self._threads += self.pv.run(self._stop)
        self._threads += self.tokencleaner.run(self._stop)
        self._threads += self.csr.run(self._stop)
        self._threads += self.nodeipam.run(self._stop)

        for r in (self.pvcprotection, self.pvprotection,
                  self.clusterroleagg, self.nodettl, self.bootstrapsigner,
                  self.expand, self.rootca):
            self._threads += r.run(self._stop)

        def token_sweep():
            while not self._stop.wait(30.0):
                try:
                    self.tokencleaner.tick()
                    self.csrcleaner.tick()
                except Exception:
                    pass

        t_sw = threading.Thread(target=token_sweep, daemon=True)
        t_sw.start()
        self._threads.append(t_sw)
        self._threads += self.attachdetach.run(self._stop)
        self._threads += self.serviceaccount.run(self._stop)
        self._threads += self.token.run(self._stop)

        def gc_resweep():
            while not self._stop.wait(30.0):
                self.gc.sweep_all()

        t = threading.Thread(target=gc_resweep, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self.informers is not None:
            self.informers.stop()
        self.replicaset.queue.close()
        self.replication.queue.close()
        self.disruption.queue.close()
        self.deployment.queue.close()
        self.job.queue.close()
        self.endpoints.queue.close()
        self.namespace.queue.close()
        self.gc.queue.close()
        self.quota.queue.close()
        self.daemonset.queue.close()
        self.statefulset.queue.close()
        self.pv.queue.close()
        self.tokencleaner.queue.close()
        self.csr.queue.close()
        self.nodeipam.queue.close()
        self.attachdetach.queue.close()
        self.serviceaccount.queue.close()
        self.token.queue.close()
        for r in (self.pvcprotection, self.pvprotection,
                  self.clusterroleagg, self.nodettl, self.bootstrapsigner,
                  self.expand, self.rootca):
            r.queue.close()


# ---------------------------------------------------------------- disruption


def _int_or_percent(v, total: int, round_up: bool = True) -> int:
    """intstr.GetValueFromIntOrPercent: "50%" scales against total (the
    disruption controller rounds UP for both minAvailable and
    maxUnavailable; Deployment maxUnavailable rounds DOWN), ints pass
    through."""
    if isinstance(v, str) and v.endswith("%"):
        import math

        scaled = int(v[:-1]) * total / 100.0
        return math.ceil(scaled) if round_up else math.floor(scaled)
    return int(v)


class DisruptionController(Reconciler):
    """pkg/controller/disruption: maintains each PodDisruptionBudget's
    status.disruptionsAllowed = currentHealthy - desiredHealthy, where
    desiredHealthy comes from spec.minAvailable or expected -
    spec.maxUnavailable — BOTH percentage forms round UP
    (GetValueFromIntOrPercent(..., true) in the disruption controller;
    floor-for-maxUnavailable is the Deployment rollout rule, not this one).
    Healthy = matching pods that are assigned and Running.  The scheduler's
    PDB-aware preemption consumes the result (filterPodsWithPDBViolation)."""

    def _on_event(self, event: str, kind: str, obj) -> None:
        # watch callbacks run under the store lock: never list/match here —
        # enqueue a marker and resolve matching PDBs in the worker
        if kind == "poddisruptionbudgets":
            self.queue.add((obj.namespace, obj.name))
        elif kind == "pods":
            self.queue.add(("@pod", obj.namespace))

    def sync(self, key) -> None:
        if key[0] == "@pod":
            # a pod in the namespace changed: re-sync every PDB there
            for pdb in self.cluster.list("poddisruptionbudgets"):
                if pdb.namespace == key[1]:
                    self.sync((pdb.namespace, pdb.name))
            return
        ns, name = key
        pdb, rv = self.cluster.get_with_rv("poddisruptionbudgets", ns, name)
        if pdb is None:
            return
        matching = [p for p in self.cluster.list("pods") if pdb.matches(p)]
        expected = len(matching)
        healthy = sum(
            1 for p in matching
            if p.spec.node_name and p.status.phase == "Running"
        )
        if pdb.min_available is not None:
            desired = _int_or_percent(pdb.min_available, expected)
        elif pdb.max_unavailable is not None:
            desired = expected - _int_or_percent(pdb.max_unavailable, expected)
        else:
            desired = expected  # no budget spec: nothing disruptable
        allowed = max(healthy - desired, 0)
        if allowed != pdb.disruptions_allowed:
            # CAS against the read revision: a concurrent spec update wins
            # and the ConflictError requeues this key (process_one)
            self.cluster.update(
                "poddisruptionbudgets",
                dataclasses.replace(pdb, disruptions_allowed=allowed),
                expect_rv=rv,
            )


# ---------------------------------------------------------------- deployment


def _template_hash(template: dict) -> str:
    """Stable pod-template hash (the pod-template-hash label value)."""
    import hashlib
    import json as _json

    return hashlib.sha1(
        _json.dumps(template, sort_keys=True).encode()
    ).hexdigest()[:10]


@dataclass
class Deployment:
    """apps/v1 Deployment slice: declarative rollout over ReplicaSets
    (pkg/controller/deployment)."""

    namespace: str
    name: str
    replicas: int
    selector: Dict[str, str]                  # matchLabels
    template: dict                            # pod dict (k8s JSON form)
    strategy: str = "RollingUpdate"           # or "Recreate"
    max_surge: object = "25%"                 # int or percent (round UP)
    max_unavailable: object = "25%"           # int or percent (round DOWN)
    uid: str = field(default_factory=lambda: uuid.uuid4().hex)
    # metadata.labels/annotations round-trip (kubectl apply's
    # last-applied lives in annotations; the controller reads neither)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)


class DeploymentController(Reconciler):
    """pkg/controller/deployment, rolling-update slice: one ReplicaSet per
    pod-template hash; the current template's RS scales up bounded by
    maxSurge (ceil) while old RSs scale down bounded by maxUnavailable
    (floor) against the READY pod count — each pod/RS event re-syncs, so
    the rollout progresses as replacements come up (rolling.go
    reconcileNewReplicaSet / reconcileOldReplicaSets shape).  "Recreate"
    scales old to zero first and only then brings the new set up."""

    def _on_event(self, event: str, kind: str, obj) -> None:
        # under the store lock: enqueue markers only, resolve in the worker
        if kind == "deployments":
            self.queue.add(obj.key)
        elif kind == "replicasets":
            self.queue.add(("@rs-owner", obj.namespace,
                            getattr(obj, "owner_uid", "")))
        elif kind == "pods" and obj.metadata.owner_uid:
            self.queue.add(("@pod-owner", obj.namespace,
                            obj.metadata.owner_uid))

    def _owned_rs(self, dep: Deployment) -> List[ReplicaSet]:
        return [
            rs for rs in self.cluster.list("replicasets")
            if rs.namespace == dep.namespace
            and getattr(rs, "owner_uid", "") == dep.uid
        ]

    def _ready(self, rs: ReplicaSet) -> int:
        sel = klabels.selector_from_match_labels(rs.selector)
        return sum(
            1 for p in self.cluster.list("pods")
            if p.namespace == rs.namespace
            and p.metadata.owner_uid == rs.uid
            and sel.matches(p.labels)
            and p.spec.node_name and p.status.phase == "Running"
        )

    def sync(self, key) -> None:
        if key[0] == "@pod-owner":
            # pod -> owning RS -> owning deployment (resolveControllerRef)
            _, ns, pod_owner = key
            rs = next(
                (r for r in self.cluster.list("replicasets")
                 if r.uid == pod_owner), None,
            )
            if rs is not None and rs.owner_uid:
                self.sync(("@rs-owner", ns, rs.owner_uid))
            return
        if key[0] == "@rs-owner":
            _, ns, dep_uid = key
            if not dep_uid:
                return
            dep = next(
                (d for d in self.cluster.list("deployments")
                 if d.uid == dep_uid), None,
            )
            if dep is not None:
                self.sync(dep.key)
            else:
                # owner gone: cascade-delete the orphaned RSs (the
                # garbagecollector analog; RS deletion cascades its pods)
                for rs in self.cluster.list("replicasets"):
                    if rs.namespace == ns and rs.owner_uid == dep_uid:
                        self.cluster.delete(
                            "replicasets", rs.namespace, rs.name
                        )
            return
        ns, name = key
        dep = self.cluster.get("deployments", ns, name)
        if dep is None:
            # deleted: drop every RS still claiming a now-dead owner
            live = {d.uid for d in self.cluster.list("deployments")}
            for rs in self.cluster.list("replicasets"):
                if (
                    rs.namespace == ns and rs.owner_uid
                    and rs.owner_uid not in live
                ):
                    self.cluster.delete("replicasets", rs.namespace, rs.name)
            return
        h = _template_hash(dep.template)
        owned = self._owned_rs(dep)
        new_rs = next(
            (rs for rs in owned if rs.selector.get("pod-template-hash") == h),
            None,
        )
        # revision bookkeeping (deployment/sync.go getNewReplicaSet): the
        # current-template RS carries the HIGHEST revision; rolling back
        # to an old template bumps that old RS to a fresh revision number
        max_rev = max(
            (int(rs.annotations.get(REVISION_ANNOTATION, "0"))
             for rs in owned), default=0,
        )
        if new_rs is None:
            tmpl = dict(dep.template)
            meta = dict(tmpl.get("metadata") or {})
            meta["labels"] = {**(meta.get("labels") or {}),
                              "pod-template-hash": h}
            tmpl["metadata"] = meta
            new_rs = ReplicaSet(
                dep.namespace, f"{dep.name}-{h}", 0,
                {**dep.selector, "pod-template-hash": h}, tmpl,
            )
            new_rs.owner_uid = dep.uid
            new_rs.annotations = {REVISION_ANNOTATION: str(max_rev + 1)}
            self.cluster.create("replicasets", new_rs)
            owned.append(new_rs)
        elif int(new_rs.annotations.get(REVISION_ANNOTATION, "0")) < max_rev:
            new_rs.annotations = {
                **new_rs.annotations, REVISION_ANNOTATION: str(max_rev + 1)}
            self.cluster.update("replicasets", new_rs)
        old = [rs for rs in owned if rs is not new_rs]
        old_total = sum(rs.replicas for rs in old)
        ready_total = sum(self._ready(rs) for rs in owned)

        if dep.strategy == "Recreate":
            for rs in old:
                if rs.replicas:
                    self._scale(rs, 0)
            if any(self._ready(rs) for rs in old) or old_total:
                return  # old still draining; new waits
            if new_rs.replicas != dep.replicas:
                self._scale(new_rs, dep.replicas)
            return

        surge = _int_or_percent(dep.max_surge, dep.replicas)
        unavail = _int_or_percent(dep.max_unavailable, dep.replicas, round_up=False)
        # cleanupUnhealthyReplicas analog: old replicas that never became
        # ready cost no availability, so they scale down unconditionally —
        # without this, one stuck old pod deadlocks the whole rollout
        for rs in old:
            unhealthy = rs.replicas - self._ready(rs)
            if rs.replicas and unhealthy > 0:
                self._scale(rs, rs.replicas - unhealthy)
        old_total = sum(rs.replicas for rs in old)
        max_total = dep.replicas + surge
        # scale the new RS up into the surge headroom
        new_target = min(dep.replicas, max(
            new_rs.replicas, max_total - old_total
        ))
        if new_target != new_rs.replicas:
            self._scale(new_rs, new_target)
        # scale old down as availability allows
        min_available = dep.replicas - unavail
        budget = ready_total - min_available
        for rs in sorted(old, key=lambda r: r.name):
            if budget <= 0 or rs.replicas == 0:
                continue
            step = min(rs.replicas, budget)
            self._scale(rs, rs.replicas - step)
            budget -= step

    def _scale(self, rs: ReplicaSet, replicas: int) -> None:
        rs.replicas = replicas
        self.cluster.update("replicasets", rs)


def add_deployment(cluster: LocalCluster, dep: Deployment) -> None:
    cluster.create("deployments", dep)


# ----------------------------------------------------------------------- job


@dataclass
class Job:
    """batch/v1 Job slice: run pods to completion (pkg/controller/job).
    completions = successful pods required; parallelism = max concurrently
    active (Pending/Running) pods."""

    namespace: str
    name: str
    completions: int = 1
    parallelism: int = 1
    template: dict = field(default_factory=dict)
    backoff_limit: int = 6
    # delete this long after reaching Complete/Failed (None = keep forever;
    # pkg/controller/ttlafterfinished)
    ttl_seconds_after_finished: Optional[int] = None
    uid: str = field(default_factory=lambda: uuid.uuid4().hex)
    owner_uid: str = ""   # owning CronJob's uid ("" = standalone)
    # status (controller-maintained; succeeded/complete are MONOTONIC —
    # deleting a terminal pod cannot un-complete finished work)
    succeeded: int = 0
    failed: int = 0
    complete: bool = False
    failed_state: bool = False  # backoffLimit exceeded ("Failed" condition)
    finished_at: float = 0.0    # epoch seconds the terminal condition landed

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)


class JobController(Reconciler):
    """pkg/controller/job syncJob: keep min(parallelism, completions -
    succeeded) pods active until `completions` pods have Succeeded; mark the
    Job complete and stop creating.  Failed pods count toward backoffLimit;
    exceeding it fails the Job (no more pods)."""

    def __init__(self, cluster: LocalCluster):
        self._seq = 0
        super().__init__(cluster)

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "jobs":
            self.queue.add(obj.key)
        elif kind == "pods" and obj.metadata.owner_kind == "Job":
            self.queue.add(("@job-owner", obj.namespace,
                            obj.metadata.owner_uid))

    def sync(self, key) -> None:
        if key[0] == "@job-owner":
            _, ns, uid = key
            job = next(
                (j for j in self.cluster.list("jobs") if j.uid == uid), None
            )
            if job is not None:
                self.sync(job.key)
            return
        ns, name = key
        job, rv = self.cluster.get_with_rv("jobs", ns, name)
        if job is None:
            # cascade: pods of deleted jobs
            live = {j.uid for j in self.cluster.list("jobs")}
            for p in self.cluster.list("pods"):
                if (
                    p.namespace == ns and p.metadata.owner_kind == "Job"
                    and p.metadata.owner_uid not in live
                ):
                    self.cluster.delete("pods", p.namespace, p.name)
            return
        owned = [
            p for p in self.cluster.list("pods")
            if p.namespace == job.namespace
            and p.metadata.owner_uid == job.uid
        ]
        # monotonic counters: a deleted terminal pod must not revert status
        succeeded = max(
            job.succeeded,
            sum(1 for p in owned if p.status.phase == "Succeeded"),
        )
        failed = max(
            job.failed,
            sum(1 for p in owned if p.status.phase == "Failed"),
        )
        active = [
            p for p in owned if p.status.phase in ("Pending", "Running")
        ]
        complete = job.complete or succeeded >= job.completions
        failed_state = job.failed_state or failed > job.backoff_limit
        if complete or failed_state:
            # terminal: a failed job terminates its still-active pods
            # (k8s deletes them); a complete one has none by construction
            if failed_state:
                for p in active:
                    self.cluster.delete("pods", p.namespace, p.name)
        else:
            want_active = min(
                job.parallelism, job.completions - succeeded
            ) - len(active)
            for _ in range(max(want_active, 0)):
                self._seq += 1
                d = dict(job.template)
                meta = dict(d.get("metadata") or {})
                meta["name"] = f"{job.name}-{self._seq:05d}"
                meta["namespace"] = job.namespace
                meta["ownerReferences"] = [
                    {"kind": "Job", "name": job.name, "uid": job.uid,
                     "controller": True}
                ]
                d["metadata"] = meta
                self.cluster.create("pods", Pod.from_dict(d))
        if (
            succeeded != job.succeeded or failed != job.failed
            or complete != job.complete
            or failed_state != job.failed_state
        ):
            newly_terminal = (
                (complete or failed_state)
                and not (job.complete or job.failed_state)
            )
            self.cluster.update(
                "jobs",
                dataclasses.replace(
                    job, succeeded=succeeded, failed=failed,
                    complete=complete, failed_state=failed_state,
                    # the TTL-after-finished clock starts at the terminal
                    # condition (ttlafterfinished timeLeft semantics)
                    finished_at=(
                        time.time() if newly_terminal else job.finished_at
                    ),
                ),
                expect_rv=rv,
            )


def add_job(cluster: LocalCluster, job: Job) -> None:
    cluster.create("jobs", job)


# ---------------------------------------------------------------- namespace


# every namespaced kind the deletion sweep must empty (the reference
# discovers these dynamically; pkg/controller/namespace/deletion/
# namespaced_resources_deleter.go:388-480) — single source of truth shared
# with the NamespaceLifecycle admission plugin
from kubernetes_tpu.apiserver.admission import NAMESPACED_KINDS  # noqa: E402


class NamespaceController(Reconciler):
    """pkg/controller/namespace: a namespace in phase Terminating is emptied
    of every namespaced object, then removed from the store (the finalizer
    step).  The API server only flips the phase; this controller does the
    actual teardown."""

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind != "namespaces" or event == "DELETED":
            return
        name = obj.get("name") if isinstance(obj, dict) else None
        if name:
            self.queue.add(name)

    def sync(self, key) -> None:
        ns = self.cluster.get("namespaces", "", key)
        if ns is None or not isinstance(ns, dict):
            return
        if (ns.get("status") or {}).get("phase") != "Terminating":
            return
        def contents():
            found = []
            for kind in NAMESPACED_KINDS:
                for obj in self.cluster.list(kind):
                    obj_ns = (
                        obj.get("namespace") if isinstance(obj, dict)
                        else getattr(obj, "namespace", "")
                    )
                    if obj_ns != key:
                        continue
                    obj_name = (
                        obj.get("name") if isinstance(obj, dict)
                        else getattr(obj, "name", "")
                    )
                    found.append((kind, obj_name))
            return found

        for kind, obj_name in contents():
            self.cluster.delete(kind, key, obj_name)
        # finalize only against an observed-empty namespace: deletes fan out
        # watch events that may create more work (an RS observed mid-delete
        # re-creating pods), so re-check and requeue until quiescent
        if contents():
            raise RuntimeError("namespace not yet empty; requeue")
        self.cluster.delete("namespaces", "", key)


# --------------------------------------------------------- garbage collector


class GarbageCollector(Reconciler):
    """pkg/controller/garbagecollector: cascade deletion through
    ownerReferences.  The object model flattens the controller ownerRef to
    (owner_kind, owner_uid) on pods and RS/Deployment records; when an
    owner disappears, its dependents are deleted (background propagation
    policy, the default).

    The RS/Deployment/Job reconcilers already cascade their own dependents
    promptly; this controller is the ownerRef BACKSTOP (the reference's
    controllers rely on the GC entirely) — it reacts to owner deletions
    and resweeps periodically via sweep_all() so a dependent created after
    its owner's DELETED event is still collected.  Deletes are idempotent,
    so racing the per-controller cascades is harmless."""

    # owner store kind -> the owner_kind string its dependents carry
    OWNER_KINDS = {
        "replicasets": "ReplicaSet",
        "replicationcontrollers": "ReplicationController",
        "jobs": "Job",
        "daemonsets": "DaemonSet",
        "statefulsets": "StatefulSet",
        # edge owners with non-pod dependents handled in sync():
        "deployments": "Deployment",   # -> ReplicaSets
        "cronjobs": "CronJob",         # -> Jobs
    }

    def _on_event(self, event: str, kind: str, obj) -> None:
        if event == "DELETED" and kind in self.OWNER_KINDS:
            self.queue.add(("sweep", kind))

    def sweep_all(self) -> None:
        """Periodic full resweep (graph_builder's monitors resync analog)."""
        for kind in self.OWNER_KINDS:
            self.queue.add(("sweep", kind))

    def _owner_uids(self, kind: str) -> set:
        return {getattr(o, "uid", "") for o in self.cluster.list(kind)}

    def sync(self, key) -> None:
        _, owner_kind = key
        live = self._owner_uids(owner_kind)
        if owner_kind == "deployments":
            # Deployment -> ReplicaSet edge: orphaned RSes cascade (their
            # own deletion events then sweep their pods)
            for rs in list(self.cluster.list("replicasets")):
                if rs.owner_uid and rs.owner_uid not in live:
                    self.cluster.delete("replicasets", rs.namespace, rs.name)
            return
        if owner_kind == "cronjobs":
            # CronJob -> Job edge (the Job's own deletion sweeps its pods)
            for job in list(self.cluster.list("jobs")):
                if job.owner_uid and job.owner_uid not in live:
                    self.cluster.delete("jobs", job.namespace, job.name)
            return
        owner_name = self.OWNER_KINDS[owner_kind]
        for pod in list(self.cluster.list("pods")):
            ou = pod.metadata.owner_uid
            if (
                ou
                and pod.metadata.owner_kind == owner_name
                and ou not in live
            ):
                self.cluster.delete("pods", pod.namespace, pod.name)


# ------------------------------------------------------------------- pod GC


class PodGCController:
    """pkg/controller/podgc: periodically delete (a) terminated pods beyond
    a threshold, oldest first, and (b) pods bound to nodes that no longer
    exist (gc_controller.go:152-197 gcTerminated / gcOrphaned)."""

    def __init__(self, cluster: LocalCluster, terminated_threshold: int = 12500):
        self.cluster = cluster
        self.threshold = terminated_threshold

    def gc_once(self) -> int:
        deleted = 0
        nodes = {n.name for n in self.cluster.list("nodes")}
        terminated = []
        for pod in list(self.cluster.list("pods")):
            if pod.spec.node_name and pod.spec.node_name not in nodes:
                self.cluster.delete("pods", pod.namespace, pod.name)
                deleted += 1
                continue
            if pod.status.phase in ("Succeeded", "Failed"):
                terminated.append(pod)
        excess = len(terminated) - self.threshold
        if excess > 0:
            terminated.sort(key=lambda p: p.status.start_time or 0.0)
            for pod in terminated[:excess]:
                self.cluster.delete("pods", pod.namespace, pod.name)
                deleted += 1
        return deleted

    def run(self, stop: threading.Event, period: float = 20.0) -> threading.Thread:
        def loop():
            while not stop.wait(period):
                self.gc_once()

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t


# ------------------------------------------------------------ resourcequota


class ResourceQuotaController(Reconciler):
    """pkg/controller/resourcequota: keeps each quota's status.used in sync
    with live usage (the admission plugin enforces; this controller
    reports)."""

    @property
    def _RESOURCES(self):
        from kubernetes_tpu.apiserver.admission import _QUOTA_POD_RESOURCES

        return _QUOTA_POD_RESOURCES

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "resourcequotas":
            if isinstance(obj, dict):
                self.queue.add((obj.get("namespace", ""), obj.get("name", "")))
        elif kind == "pods":
            ns = (
                obj.get("namespace") if isinstance(obj, dict)
                else getattr(obj, "namespace", "")
            )
            for q in self.cluster.list("resourcequotas"):
                if q.get("namespace") == ns:
                    self.queue.add((ns, q.get("name", "")))

    def sync(self, key) -> None:
        from kubernetes_tpu.apiserver.admission import quota_usage

        ns, name = key
        q, rv = self.cluster.get_with_rv("resourcequotas", ns, name)
        if q is None:
            return
        hard = (q.get("spec") or {}).get("hard") or {}
        tracked = [r for r in hard if r in self._RESOURCES]
        used = {
            r: str(v) for r, v in quota_usage(self.cluster, ns, tracked).items()
        }
        status = dict(q.get("status") or {})
        if status.get("used") != used:
            new = dict(q)
            new["status"] = {**status, "hard": dict(hard), "used": used}
            self.cluster.update("resourcequotas", new, expect_rv=rv)


# ----------------------------------------------------------------- daemonset


@dataclass
class DaemonSet:
    """apps/v1 DaemonSet slice: one pod per eligible node."""

    namespace: str
    name: str
    selector: Dict[str, str]
    template: dict
    uid: str = field(default_factory=lambda: uuid.uuid4().hex)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)


class DaemonSetController(Reconciler):
    """pkg/controller/daemon syncDaemonSet: ensure exactly one owned pod on
    every node that should run the daemon.  Placement follows the classic
    controller-scheduled behavior (spec.nodeName set directly by the
    controller; the ScheduleDaemonSetPods feature moved this to the default
    scheduler in later versions — daemon pods here bypass the queue the
    same way).  Node eligibility: schedulable nodes whose NoSchedule/
    NoExecute taints the template tolerates (nodeShouldRunDaemonPod)."""

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "daemonsets":
            self.queue.add(obj.key)
        elif kind in ("nodes",):
            for ds in self.cluster.list("daemonsets"):
                self.queue.add(ds.key)
        elif kind == "pods" and getattr(obj.metadata, "owner_kind", "") == "DaemonSet":
            for ds in self.cluster.list("daemonsets"):
                if ds.uid == obj.metadata.owner_uid:
                    self.queue.add(ds.key)
                    break

    def _eligible(self, ds: DaemonSet) -> List[Node]:
        tmpl_tols = [
            t for t in (ds.template.get("spec") or {}).get("tolerations") or []
        ]
        from kubernetes_tpu.api.types import Toleration

        tols = [Toleration.from_dict(t) for t in tmpl_tols]
        out = []
        for node in self.cluster.list("nodes"):
            if node.spec.unschedulable:
                continue
            blocked = False
            for taint in node.spec.taints:
                if taint.effect not in (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE):
                    continue
                if not any(t.tolerates(taint) for t in tols):
                    blocked = True
                    break
            if not blocked:
                out.append(node)
        return out

    def sync(self, key: Tuple[str, str]) -> None:
        ns, name = key
        ds = self.cluster.get("daemonsets", ns, name)
        if ds is None:
            live = {d.uid for d in self.cluster.list("daemonsets")}
            for p in self.cluster.list("pods"):
                if (
                    p.metadata.owner_kind == "DaemonSet"
                    and p.metadata.owner_uid not in live
                ):
                    self.cluster.delete("pods", p.namespace, p.name)
            return
        want = {n.name for n in self._eligible(ds)}
        have: Dict[str, Pod] = {}
        for p in list(self.cluster.list("pods")):
            if p.namespace != ns or p.metadata.owner_uid != ds.uid:
                continue
            if p.status.phase in ("Succeeded", "Failed"):
                # a dead daemon pod holds its deterministic name; delete it
                # so the replacement create below can't name-conflict
                self.cluster.delete("pods", p.namespace, p.name)
                continue
            have[p.spec.node_name] = p
        for node_name in want - set(have):
            d = dict(ds.template)
            meta = dict(d.get("metadata") or {})
            meta["name"] = f"{ds.name}-{node_name}"
            meta["namespace"] = ns
            meta["ownerReferences"] = [
                {"kind": "DaemonSet", "name": ds.name, "uid": ds.uid,
                 "controller": True}
            ]
            d["metadata"] = meta
            spec = dict(d.get("spec") or {})
            spec["nodeName"] = node_name  # controller-scheduled
            d["spec"] = spec
            try:
                self.cluster.create("pods", Pod.from_dict(d))
            except ConflictError:
                pass  # stale view; next event reconverges
        for node_name in set(have) - want:
            p = have[node_name]
            self.cluster.delete("pods", p.namespace, p.name)


# ---------------------------------------------------------------- statefulset


@dataclass
class StatefulSet:
    """apps/v1 StatefulSet slice: ordered, stable-identity replicas.
    volume_claim_templates: PVC dicts (spec form) stamped per ordinal as
    <template-name>-<set>-<ordinal>, retained on scale-down (the
    reference never deletes them)."""

    namespace: str
    name: str
    replicas: int
    selector: Dict[str, str]
    template: dict
    volume_claim_templates: Tuple[dict, ...] = ()
    uid: str = field(default_factory=lambda: uuid.uuid4().hex)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)


class StatefulSetController(Reconciler):
    """pkg/controller/statefulset: pods are <name>-0..<name>-N-1 with stable
    identity; OrderedReady semantics — pod i is created only after pods
    0..i-1 exist and are Running, scale-down removes the highest ordinal
    first (one step per sync; events drive reconvergence)."""

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "statefulsets":
            self.queue.add(obj.key)
        elif kind == "pods" and getattr(obj.metadata, "owner_kind", "") == "StatefulSet":
            for st in self.cluster.list("statefulsets"):
                if st.uid == obj.metadata.owner_uid:
                    self.queue.add(st.key)
                    break

    def sync(self, key: Tuple[str, str]) -> None:
        ns, name = key
        st = self.cluster.get("statefulsets", ns, name)
        if st is None:
            live = {s.uid for s in self.cluster.list("statefulsets")}
            for p in self.cluster.list("pods"):
                if (
                    p.metadata.owner_kind == "StatefulSet"
                    and p.metadata.owner_uid not in live
                ):
                    self.cluster.delete("pods", p.namespace, p.name)
            return
        owned: Dict[int, Pod] = {}
        prefix = f"{st.name}-"
        for p in list(self.cluster.list("pods")):
            if (
                p.namespace == ns
                and p.metadata.owner_uid == st.uid
                and p.name.startswith(prefix)
            ):
                if p.status.phase in ("Succeeded", "Failed"):
                    # stable identity means replace-in-place: delete the
                    # dead pod so its ordinal can be recreated (the
                    # reference StatefulSet controller does the same)
                    self.cluster.delete("pods", p.namespace, p.name)
                    continue
                try:
                    owned[int(p.name[len(prefix):])] = p
                except ValueError:
                    pass
        # scale down: highest ordinal first, one at a time
        extra = [i for i in sorted(owned, reverse=True) if i >= st.replicas]
        if extra:
            p = owned[extra[0]]
            self.cluster.delete("pods", p.namespace, p.name)
            return
        # scale up: lowest missing ordinal, only if all predecessors Running
        for i in range(st.replicas):
            if i in owned:
                if owned[i].status.phase != "Running":
                    return  # OrderedReady: wait for predecessor
                continue
            d = dict(st.template)
            meta = dict(d.get("metadata") or {})
            meta["name"] = f"{st.name}-{i}"
            meta["namespace"] = ns
            meta["ownerReferences"] = [
                {"kind": "StatefulSet", "name": st.name, "uid": st.uid,
                 "controller": True}
            ]
            d["metadata"] = meta
            # per-ordinal PVCs from volumeClaimTemplates (statefulset
            # pod_control.go createPersistentVolumeClaims): claim name
            # <template>-<set>-<ordinal>; the pod mounts it by that name
            if st.volume_claim_templates:
                from kubernetes_tpu.api.storage import (
                    PersistentVolumeClaim,
                )

                spec_d = dict(d.get("spec") or {})
                vols = list(spec_d.get("volumes") or [])
                for tmpl in st.volume_claim_templates:
                    t_meta = tmpl.get("metadata") or {}
                    t_name = t_meta.get("name", "data")
                    claim_name = f"{t_name}-{st.name}-{i}"
                    if self.cluster.get("persistentvolumeclaims", ns,
                                        claim_name) is None:
                        body = {
                            "metadata": {"name": claim_name,
                                         "namespace": ns},
                            "spec": tmpl.get("spec") or {},
                        }
                        try:
                            self.cluster.create(
                                "persistentvolumeclaims",
                                PersistentVolumeClaim.from_dict(body))
                        except ConflictError:
                            pass
                    if not any(v.get("name") == t_name for v in vols):
                        vols.append({
                            "name": t_name,
                            "persistentVolumeClaim": {
                                "claimName": claim_name},
                        })
                spec_d["volumes"] = vols
                d["spec"] = spec_d
            try:
                self.cluster.create("pods", Pod.from_dict(d))
            except ConflictError:
                pass
            return  # one creation per sync; the pod's Running event resumes


# -------------------------------------------------------------------- cronjob


def cron_matches(expr: str, t: time.struct_time) -> bool:
    """5-field cron (minute hour dom month dow) with *, */N, N, and
    comma lists — the subset cronjob schedules actually use
    (pkg/controller/cronjob uses robfig/cron)."""
    fields = expr.split()
    if len(fields) != 5:
        raise ValueError(f"bad cron expression {expr!r}")
    vals = (t.tm_min, t.tm_hour, t.tm_mday, t.tm_mon, (t.tm_wday + 1) % 7)

    def field_ok(spec: str, v: int) -> bool:
        ok = False
        for part in spec.split(","):
            if part == "*":
                ok = True
            elif part.startswith("*/"):
                step = int(part[2:])  # raises on junk / ZeroDivision below
                if step <= 0:
                    raise ValueError(f"bad cron step {part!r}")
                if v % step == 0:
                    ok = True
            elif part.isdigit():
                if int(part) == v:
                    ok = True
            else:
                raise ValueError(f"bad cron field {part!r} in {expr!r}")
        return ok

    # evaluate EVERY field (no short-circuit): malformed later fields must
    # raise regardless of whether an earlier field already failed to match,
    # so write-path validation is time-independent
    results = [field_ok(f, v) for f, v in zip(fields, vals)]
    return all(results)


@dataclass
class CronJob:
    """batch/v1beta1 CronJob slice."""

    namespace: str
    name: str
    schedule: str
    job_template: dict                     # {"spec": {... Job spec ...}}
    concurrency_policy: str = "Allow"      # Allow | Forbid
    suspend: bool = False
    uid: str = field(default_factory=lambda: uuid.uuid4().hex)
    last_schedule_minute: int = -1         # epoch-minute of last trigger

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)


class CronJobController:
    """pkg/controller/cronjob syncAll: a 10s poll (not watch-driven in the
    reference either) that creates a Job whenever the schedule matches a
    new minute; Forbid skips the tick while an owned Job is still active."""

    def __init__(self, cluster: LocalCluster):
        self.cluster = cluster

    def tick(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        minute = int(now // 60)
        created = 0
        for cj in self.cluster.list("cronjobs"):
            # HandleError semantics PER CRONJOB: one bad schedule must not
            # starve the others
            try:
                created += self._tick_one(cj, now, minute)
            except Exception:
                continue
        return created

    def _tick_one(self, cj: "CronJob", now: float, minute: int) -> int:
        if cj.suspend or cj.last_schedule_minute == minute:
            return 0
        if not cron_matches(cj.schedule, time.localtime(now)):
            return 0
        if cj.concurrency_policy == "Forbid":
            active = any(
                j.owner_uid == cj.uid and not j.complete and not j.failed_state
                for j in self.cluster.list("jobs")
            )
            if active:
                return 0
        spec = (cj.job_template.get("spec") or {})
        job = Job(
            namespace=cj.namespace,
            name=f"{cj.name}-{minute}",
            completions=int(spec.get("completions", 1)),
            parallelism=int(spec.get("parallelism", 1)),
            template=spec.get("template") or {},
            backoff_limit=int(spec.get("backoffLimit", 6)),
            owner_uid=cj.uid,
        )
        try:
            self.cluster.create("jobs", job)
        except ConflictError:
            return 0
        cj2, rv = self.cluster.get_with_rv("cronjobs", cj.namespace, cj.name)
        if cj2 is not None:
            self.cluster.update(
                "cronjobs",
                dataclasses.replace(cj2, last_schedule_minute=minute),
                expect_rv=rv,
            )
        return 1

    def run(self, stop: threading.Event, period: float = 10.0) -> threading.Thread:
        def loop():
            while not stop.wait(period):
                try:
                    self.tick()
                except Exception:
                    pass  # HandleError semantics: a bad cronjob can't kill the loop

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t


# ------------------------------------------------------------------- HPA


@dataclass
class HorizontalPodAutoscaler:
    """autoscaling/v1 slice: scale a Deployment/ReplicaSet between
    [min_replicas, max_replicas] toward target CPU utilization."""

    namespace: str
    name: str
    target_kind: str          # "Deployment" | "ReplicaSet"
    target_name: str
    min_replicas: int = 1
    max_replicas: int = 10
    target_cpu_utilization: int = 80   # percent of requests
    uid: str = field(default_factory=lambda: uuid.uuid4().hex)
    # status
    current_replicas: int = 0
    desired_replicas: int = 0

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)


class HPAController:
    """pkg/controller/podautoscaler: the classic utilization loop —
    desired = ceil(current * currentUtilization / targetUtilization),
    clamped to [min, max] (replica_calculator.go GetResourceReplicas).

    Usage comes through the resource-metrics seam (`usage_fn(pod) ->
    milliCPU`); the default reads requests — exactly what this framework's
    metrics.k8s.io endpoint reports for hollow pods — so a real cadvisor
    would plug in at the same point."""

    # rescale only when |usage/requested/target - 1| exceeds this band
    # (replica_calculator.go defaultTolerance = 0.1).  NOTE: the default
    # requests-based usage_fn always reads utilization == 100%, so with
    # target < ~91 an HPA ratchets toward max unless a real usage source
    # (metrics.k8s.io observed values) is plugged in.
    TOLERANCE = 0.1

    def __init__(self, cluster: LocalCluster, usage_fn=None):
        self.cluster = cluster
        self.usage_fn = usage_fn or self._requests_usage

    @staticmethod
    def _requests_usage(pod: Pod) -> float:
        cpu = 0.0
        for c in pod.spec.containers:
            if "cpu" in c.requests:
                cpu += c.requests["cpu"].milli
        return cpu

    def _target(self, hpa: HorizontalPodAutoscaler):
        kind = {"Deployment": "deployments",
                "ReplicaSet": "replicasets"}.get(hpa.target_kind)
        if kind is None:
            return None, None
        return kind, self.cluster.get(kind, hpa.namespace, hpa.target_name)

    def sync_one(self, hpa: HorizontalPodAutoscaler):
        """Returns the applied desired replica count, or None when the HPA
        did not act (missing target, or autoscaling suspended because the
        target was manually scaled to zero — horizontal.go: spec.replicas
        == 0 disables the autoscaler for that target)."""
        import math

        kind, target = self._target(hpa)
        if target is None:
            return None
        if target.replicas == 0:
            return None  # manual scale-to-zero pauses the workload
        # pods selected by the scale target, Running only (the metrics
        # client returns samples only for running pods)
        sel = klabels.selector_from_match_labels(target.selector)
        pods = [
            p for p in self.cluster.list("pods")
            if p.namespace == hpa.namespace and sel.matches(p.labels)
            and p.status.phase == "Running"
        ]
        current = target.replicas
        if pods and hpa.target_cpu_utilization > 0:
            usage = sum(self.usage_fn(p) for p in pods)
            requested = sum(self._requests_usage(p) for p in pods)
            if requested > 0:
                utilization = 100.0 * usage / requested
                ratio = utilization / hpa.target_cpu_utilization
                if abs(ratio - 1.0) <= self.TOLERANCE:
                    # within the tolerance band: no rescale
                    # (replica_calculator.go:71-76) — without this, steady
                    # utilization slightly off target rescales every tick
                    desired = current
                else:
                    desired = math.ceil(len(pods) * ratio)
            else:
                desired = current
        else:
            desired = current
        desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))
        hpa2, rv = self.cluster.get_with_rv(
            "horizontalpodautoscalers", hpa.namespace, hpa.name
        )
        if hpa2 is not None and (
            hpa2.current_replicas != len(pods)
            or hpa2.desired_replicas != desired
        ):
            self.cluster.update(
                "horizontalpodautoscalers",
                dataclasses.replace(
                    hpa2, current_replicas=len(pods),
                    desired_replicas=desired,
                ),
                expect_rv=rv,
            )
        if desired != current:
            tgt, trv = self.cluster.get_with_rv(kind, hpa.namespace,
                                                hpa.target_name)
            if tgt is not None:
                self.cluster.update(
                    kind, dataclasses.replace(tgt, replicas=desired),
                    expect_rv=trv,
                )
                self.cluster.events.eventf(
                    "HorizontalPodAutoscaler", hpa.namespace, hpa.name,
                    "Normal", "SuccessfulRescale",
                    "scaled %s/%s to %d", hpa.target_kind,
                    hpa.target_name, desired,
                )
        return desired

    def tick(self) -> int:
        """Reconciles every HPA; returns how many acted.  Per-HPA error
        isolation (HandleError): one broken usage_fn or conflicting write
        must not starve the HPAs after it in list order."""
        acted = 0
        for hpa in self.cluster.list("horizontalpodautoscalers"):
            try:
                if self.sync_one(hpa) is not None:
                    acted += 1
            except Exception:
                continue  # incl. ConflictError: next tick re-reads
        return acted

    def run(self, stop: threading.Event, period: float = 15.0) -> threading.Thread:
        def loop():
            while not stop.wait(period):
                try:
                    self.tick()
                except Exception:
                    pass

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t


# --------------------------------------------------------- ttl-after-finished


class TTLAfterFinishedController:
    """pkg/controller/ttlafterfinished: delete finished Jobs once their
    ttlSecondsAfterFinished elapses (the Job's own deletion cascades its
    pods through the per-controller sweep / GC backstop)."""

    def __init__(self, cluster: LocalCluster):
        self.cluster = cluster

    def tick(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        deleted = 0
        for job in list(self.cluster.list("jobs")):
            if job.ttl_seconds_after_finished is None:
                continue
            if not (job.complete or job.failed_state):
                continue
            if not job.finished_at:
                continue
            if now - job.finished_at >= job.ttl_seconds_after_finished:
                self.cluster.delete("jobs", job.namespace, job.name)
                deleted += 1
        return deleted

    def run(self, stop: threading.Event, period: float = 10.0) -> threading.Thread:
        def loop():
            while not stop.wait(period):
                try:
                    self.tick()
                except Exception:
                    pass

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t
