"""Optimistic cross-replica conflict reconciler + shared snapshot hub.

ISSUE 14 / ROADMAP item 3: N `Scheduler` replicas (threads in one
process) each pop a stable hash-shard of the PriorityQueue and dispatch
engine launches against the SAME resident device snapshot generation —
Omega-style optimistic concurrency.  Nothing is locked during the
device window; instead every cycle's winners pass through this module's
SEQUENCED commit check before they assume:

  * zero-conflict fast path: if the encoder generation at commit still
    equals the generation the cycle dispatched against, no other
    replica committed in between — the engine's feasibility verdicts
    are exact and the whole batch admits with ONE integer comparison
    (allocation-free, pinned by test).

  * conflict scan: otherwise the candidate winners + requested matrices
    run through one fused check (a jitted lax.scan over the batch, with
    a bit-identical numpy twin for degraded cycles): per conflicted
    node row, requests are prefix-admitted against the LIVE headroom
    (allocatable - committed requested), so two replicas spending the
    same node's headroom beyond allocatable admit exactly the sequenced
    winner and requeue only the losers — shed-exempt, back to their
    owner shard, so no popped pod is ever lost.

  * fairness: within one reconciliation the candidate order is the
    dominant-resource-fairness order — the pod whose namespace holds
    the SMALLEST dominant share of cluster capacity goes first (ties by
    batch sequence), extending APF's request fairness (PR 4) to
    placement fairness.  Per-namespace usage/quota columns live in the
    snapshot encoder (SnapshotEncoder.a_ns_usage / a_ns_quota); a
    finite quota is enforced by the same scan (quota losers park
    unschedulable rather than spin).

`SnapshotHub` is the shared-device-state half: one DeviceSnapshotCache
all replicas dispatch through, refreshed ATOMICALLY (cache lock held
across snapshot + take_dirty_rows + device scatter) so the single-
consumer dirty-row contract holds with N dispatchers, and every launch
is tagged with the generation it ran against (the fencing the fast
path compares).

The module also keeps the process-level replica registry serving
GET /debug/replicas — the explicit aggregate the per-scheduler
telemetry/perfobs/quality installs roll up into.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from kubernetes_tpu.codec.schema import _pow2
from kubernetes_tpu.utils import klog
from kubernetes_tpu.utils import metrics as m

# same slack vocabulary as the invariant checker's capacity rule: the
# engines and the encoder accumulate requests in f32, so an exact
# comparison would fire on rounding dust
_EPS = 1e-3


def _lean_pod(pod) -> bool:
    """Can this pod's engine verdicts be trusted across a STALE
    generation fence?  Resources and node-static constraints (node
    selectors/affinity, taints) don't depend on other pods' placements
    — the admission scan re-checks the resource half against live
    truth.  Host ports, pod-(anti-)affinity, and volumes DO depend on
    what other pods committed since dispatch and have no vectorized
    re-check here, so a stale-fence winner carrying them must requeue
    and re-dispatch against fresh state instead of committing
    optimistically (spread counts are score-only — stale is suboptimal,
    never invalid)."""
    if pod.spec.volumes or pod.host_ports():
        return False
    a = pod.spec.affinity
    if a is not None and (
        a.pod_affinity is not None or a.pod_anti_affinity is not None
    ):
        return False
    return True


class SnapshotHub:
    """THE resident device snapshot N replicas share.

    refresh() is the only writer: under the cache lock it snapshots the
    encoder, takes the dirty-row stream (single consumer — replicas in
    hub mode must NOT take it themselves), scatters the delta into the
    one DeviceSnapshotCache, and records the generation.  Holding the
    cache lock across the scatter is what makes N dispatchers safe: a
    commit can never interleave between the snapshot and the upload, so
    the resident buffers always equal some exact host generation.
    JAX arrays are immutable (the CPU scatter path copies; donation is
    an accelerator-only in-place move the hub's serialized refresh
    keeps single-writer), so a launch enqueued against generation G
    keeps computing against G's buffers while the hub refreshes to G+1.
    """

    def __init__(self, cache, devcache):
        self.cache = cache
        self.dev = devcache
        self._lock = threading.Lock()  # guards dev + generation together
        self.generation = -1
        self.refreshes = 0
        self.refresh_hits = 0
        self._last = None  # (cluster, gen, dev) of the newest refresh

    def refresh(self):
        """Atomic host-snapshot -> device-scatter.  Returns
        (host ClusterTensors, generation, device ClusterTensors).
        Fast path: when NOTHING committed since the previous refresh
        (generation unchanged) the cached triple is returned as is —
        sibling replicas dispatching back-to-back against one
        generation pay one snapshot, not N."""
        with self.cache._lock:
            gen = self.cache.generation
            with self._lock:
                if gen == self.generation and self._last is not None:
                    self.refresh_hits += 1
                    return self._last
            cluster, gen = self.cache.snapshot()
            dirty = self.cache.encoder.take_dirty_rows()
            with self._lock:
                dev = self.dev.update(cluster, dirty_rows=dirty)
                self.generation = gen
                self.refreshes += 1
                self._last = (cluster, gen, dev)
            return self._last

    def invalidate(self) -> None:
        """Device fault: drop every resident buffer (the next refresh
        re-uploads the whole snapshot) and poison the generation so no
        fast path trusts state that predates the fault."""
        with self._lock:
            self.dev.invalidate()
            self.generation = -1
            self._last = None

    def resident(self, names):
        with self._lock:
            return self.dev.resident(names)


class ConflictReconciler:
    """Sequenced commit admission for optimistic replica cycles.

    One instance is shared by every replica; reconcile() runs under the
    cache lock (the commit critical section), stamps the cycle's commit
    sequence number, and returns the admitted winners plus the two
    loser classes (race-conflicted -> readd to the owner shard;
    quota-vetoed -> park unschedulable with backoff)."""

    def __init__(self, use_jit: bool = True):
        self.use_jit = use_jit
        self._seq_lock = threading.Lock()
        self.commit_seq = 0
        # stats (reads are approximate outside the cache lock — fine for
        # debug surfaces)
        self.fast_path_total = 0
        self.scans_total = 0
        self.conflicts_total = 0
        self.quota_vetoes_total = 0
        # stale-fence winners carrying constraints the scan cannot
        # re-validate (ports/pod-affinity/volumes, or any winner while
        # nominations are outstanding): requeued conservatively
        self.strict_requeues_total = 0
        self.kernel_calls = 0
        self._kernels: Dict[Tuple[int, int], object] = {}

    # ------------------------------------------------------------ kernel

    def _kernel(self, bp: int, r: int):
        """The fused admission check, jitted per padded (B, R) shape:
        ONE lax.scan over the DRF-ordered candidates carrying per-row
        and per-tenant spent matrices, so depletion chains exactly like
        a sequential admit loop — in one launch."""
        fn = self._kernels.get((bp, r))
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax import lax

        def run(u_node, u_ns, reqs, node_head, ns_head, order):
            z = jnp.zeros((bp, r), jnp.float32)

            def step(carry, x):
                spent_n, spent_t = carry
                un, ut, rq, hn, ht = x
                node_ok = jnp.all(rq <= hn - spent_n[un] + _EPS)
                ns_ok = jnp.all(rq <= ht - spent_t[ut] + _EPS)
                ok = node_ok & ns_ok
                w = jnp.where(ok, rq, 0.0)
                return (
                    (spent_n.at[un].add(w), spent_t.at[ut].add(w)),
                    (ok, ns_ok),
                )

            xs = (
                u_node[order], u_ns[order], reqs[order],
                node_head[order], ns_head[order],
            )
            _, (ok_s, ns_ok_s) = lax.scan(step, (z, z), xs)
            admit = jnp.zeros(bp, bool).at[order].set(ok_s)
            quota_ok = jnp.zeros(bp, bool).at[order].set(ns_ok_s)
            return admit, quota_ok

        fn = jax.jit(run)
        self._kernels[(bp, r)] = fn
        return fn

    @staticmethod
    def _admit_np(u_node, u_ns, reqs, node_head, ns_head, order):
        """Bit-identical numpy twin of the fused kernel (degraded-cycle
        path + the test oracle): the same DRF-ordered prefix admit."""
        bp, r = reqs.shape
        spent_n = np.zeros((bp, r), np.float32)
        spent_t = np.zeros((bp, r), np.float32)
        admit = np.zeros(bp, bool)
        quota_ok = np.zeros(bp, bool)
        for j in order:
            un, ut = u_node[j], u_ns[j]
            rq = reqs[j]
            node_ok = bool(np.all(rq <= node_head[j] - spent_n[un] + _EPS))
            ns_ok = bool(np.all(rq <= ns_head[j] - spent_t[ut] + _EPS))
            ok = node_ok and ns_ok
            if ok:
                spent_n[un] += rq
                spent_t[ut] += rq
            admit[j] = ok
            quota_ok[j] = ns_ok
        return admit, quota_ok

    # --------------------------------------------------------- reconcile

    def prewarm(self, max_width: int, r: int) -> None:
        """Pre-pay the admission kernel's compiles for the pow2 width
        ladder up to max_width (the bench/prewarm seam: a first-scan
        compile inside a timed or latency-sensitive window would read
        as a conflict-cost regression)."""
        if not self.use_jit:
            return
        w = 1
        while w <= _pow2(max(1, max_width)):
            fn = self._kernel(w, r)
            z = np.zeros((w, r), np.float32)
            u = np.zeros(w, np.int32)
            o = np.arange(w, dtype=np.int32)
            fn(u, u, z, z, z, o)
            w *= 2

    def next_seq(self) -> int:
        with self._seq_lock:
            self.commit_seq += 1
            return self.commit_seq

    def reconcile(self, sched, inf, winners, hosts):
        """Admission for one cycle's winners.  MUST run under the cache
        lock (the caller then assumes the admitted pods in the same
        critical section).  Returns (kept_winners, race_lost, quota_lost)
        where the loser lists hold (batch_index, pod) pairs.

        Fast path: generation unchanged since dispatch and no quota
        configured -> the input winners list is returned AS IS (no
        allocation, no kernel launch — pinned by test)."""
        enc = sched.cache.encoder
        inf.commit_seq = self.next_seq()
        if not winners:
            return winners, [], []
        gen_now = enc.generation
        quotas = enc.ns_quota_set
        stale = gen_now != inf.generation
        if not stale and not quotas:
            self.fast_path_total += 1
            return winners, [], []
        self.scans_total += 1
        # a STALE fence invalidates engine verdicts the scan cannot
        # re-check: winners carrying host ports / pod-(anti-)affinity /
        # volumes — and every winner while preemption nominations are
        # outstanding (the two-pass mask was host-computed at encode) —
        # requeue conservatively and re-dispatch against fresh state.
        # A quota-only scan (generation unchanged) trusts the verdicts.
        strict: list = []
        if stale:
            strict_all = bool(sched.queue.has_nominated())
            scanned = []
            for w in winners:
                if strict_all or not _lean_pod(w[1]):
                    strict.append((w[0], w[1]))
                else:
                    scanned.append(w)
            winners = scanned
        if strict:
            self.strict_requeues_total += len(strict)
            self.conflicts_total += len(strict)
            m.REPLICA_CONFLICTS.inc(
                len(strict), replica=str(sched._replica_id)
            )
            m.REPLICA_REQUEUED.inc(len(strict))
        if not winners:
            return [], strict, []
        B = len(winners)
        R = enc.dims.R
        idx = np.fromiter((w[0] for w in winners), np.int64, B)
        rows = np.asarray(hosts, np.int64)[idx]
        # per-winner requested vectors: the encoded batch's request
        # matrix (stashed at encode; R may have grown since — pad)
        reqs_src = np.asarray(inf.reqs, np.float32)
        reqs = np.zeros((B, R), np.float32)
        rc = min(R, reqs_src.shape[1])
        reqs[:, :rc] = reqs_src[idx][:, :rc]
        # tenant rows + DRF dominant shares (host-side: B-sized gathers)
        t_rows = np.fromiter(
            (enc._ns_row(w[1].namespace) for w in winners), np.int64, B
        )
        caps = enc.capacity_totals()
        with np.errstate(divide="ignore", invalid="ignore"):
            shares_t = np.where(
                caps > 0.0, enc.a_ns_usage[t_rows] / caps, 0.0
            )
        shares = shares_t.max(axis=1)
        order = np.lexsort((idx, shares)).astype(np.int32)
        # live headroom gathers (aligned per candidate position)
        node_head = (
            enc.a_allocatable[rows] - enc.a_requested[rows]
        ).astype(np.float32)
        ns_head = (
            enc.a_ns_quota[t_rows, :R] - enc.a_ns_usage[t_rows, :R]
        ).astype(np.float32)
        # first-occurrence index per row / tenant: the scan's segment ids
        u_node = np.zeros(B, np.int32)
        seen: Dict[int, int] = {}
        for j in range(B):
            u_node[j] = seen.setdefault(int(rows[j]), j)
        u_ns = np.zeros(B, np.int32)
        seen = {}
        for j in range(B):
            u_ns[j] = seen.setdefault(int(t_rows[j]), j)
        # pad to the pow2 ladder so the jitted kernel compiles a bounded
        # shape family; pad slots point at a dummy segment with zero
        # request and +inf headroom (always admitted, sliced off below)
        Bp = _pow2(B)
        if Bp != B:
            pad = Bp - B
            u_node = np.concatenate([u_node, np.full(pad, B, np.int32)])
            u_ns = np.concatenate([u_ns, np.full(pad, B, np.int32)])
            reqs = np.vstack([reqs, np.zeros((pad, R), np.float32)])
            inf_head = np.full((pad, R), np.inf, np.float32)
            node_head = np.vstack([node_head, inf_head])
            ns_head = np.vstack([ns_head, inf_head])
            order = np.concatenate(
                [order, np.arange(B, Bp, dtype=np.int32)]
            )
            # segment ids must stay in-range for the carry gather
            u_node = np.minimum(u_node, Bp - 1)
            u_ns = np.minimum(u_ns, Bp - 1)
        use_jit = self.use_jit and not inf.degraded
        if use_jit:
            try:
                self.kernel_calls += 1
                admit, quota_ok = self._kernel(Bp, R)(
                    u_node, u_ns, reqs, node_head, ns_head, order
                )
                admit = np.asarray(admit)[:B]
                quota_ok = np.asarray(quota_ok)[:B]
            except Exception as e:  # noqa: BLE001 — the numpy twin is
                # always available; a kernel fault must not lose a cycle
                klog.errorf("reconcile kernel failed (%s); numpy twin", e)
                use_jit = False
        if not use_jit:
            admit, quota_ok = self._admit_np(
                u_node, u_ns, reqs, node_head, ns_head, order
            )
            admit, quota_ok = admit[:B], quota_ok[:B]
        kept, race_lost, quota_lost = [], list(strict), []
        for j, w in enumerate(winners):
            if admit[j]:
                kept.append(w)
            elif not quota_ok[j]:
                quota_lost.append((w[0], w[1]))
            else:
                race_lost.append((w[0], w[1]))
        n_scan_lost = len(race_lost) - len(strict)  # strict counted above
        if n_scan_lost:
            self.conflicts_total += n_scan_lost
            m.REPLICA_CONFLICTS.inc(
                n_scan_lost, replica=str(sched._replica_id)
            )
        if quota_lost:
            self.quota_vetoes_total += len(quota_lost)
        if n_scan_lost or quota_lost:
            m.REPLICA_REQUEUED.inc(n_scan_lost + len(quota_lost))
        return kept, race_lost, quota_lost

    def stats(self) -> dict:
        return {
            "commit_seq": self.commit_seq,
            "fast_path_total": self.fast_path_total,
            "scans_total": self.scans_total,
            "conflicts_total": self.conflicts_total,
            "strict_requeues_total": self.strict_requeues_total,
            "quota_vetoes_total": self.quota_vetoes_total,
            "kernel_calls": self.kernel_calls,
        }


# ---------------------------------------------------- replica registry
#
# The explicit PROCESS AGGREGATE the per-scheduler observability
# installs roll up into (ISSUE 14 satellite): every Scheduler registers
# itself under its replica id (latest wins, the set_default discipline),
# and GET /debug/replicas on both servers serves this roll-up.

_REG_LOCK = threading.Lock()
_SCHEDULERS: Dict[int, object] = {}  # replica id -> weakref(Scheduler)


def register_scheduler(sched) -> None:
    import weakref

    with _REG_LOCK:
        _SCHEDULERS[int(getattr(sched, "_replica_id", 0))] = weakref.ref(
            sched
        )


def registered_schedulers() -> Dict[int, object]:
    """Live registered schedulers by replica id — weakly held, so a
    torn-down replica set disappears from /debug/replicas instead of
    reporting frozen stats (and pinning its cache) forever."""
    with _REG_LOCK:
        out = {}
        for rid, ref in sorted(_SCHEDULERS.items()):
            s = ref()
            if s is not None:
                out[rid] = s
        return out


def debug_payload(limit: Optional[int] = None) -> dict:
    """GET /debug/replicas body: per-replica cycle/outcome/conflict
    facts, the shared reconciler's sequencing stats, and the tenant
    usage/quota table.  `limit` bounds the tenant table (the shared
    debug_body cap discipline)."""
    per: Dict[str, dict] = {}
    recon = None
    tenants: Dict[str, dict] = {}
    n_live = 0
    for rid, s in registered_schedulers().items():
        try:
            per[str(rid)] = {
                "replica_of": getattr(s, "_replica_of", 1),
                # THIS replica's committed cycles (the per-scheduler
                # observatory counts its own on_cycle calls; the
                # queue's scheduling_cycle is process-global)
                "cycles": s.perfobs.summary().get("cycles", 0),
                "queue_cycles": s.queue.scheduling_cycle,
                "placed": s._outcome_totals.get("placed", 0),
                "unschedulable": s._outcome_totals.get("unschedulable", 0),
                "conflicts": getattr(s, "conflicts_total", 0),
                "race_requeued": getattr(s, "race_requeued_total", 0),
                "quota_vetoed": getattr(s, "quota_vetoed_total", 0),
                "megacycles": getattr(s, "megacycles_total", 0),
                "breaker": s.device_health.state,
                "engine": getattr(s, "_engine_kind", "?"),
                "queue_shard": getattr(s, "_replica_id", 0),
            }
            n_live += 1
            if recon is None and getattr(s, "_reconciler", None) is not None:
                recon = s._reconciler
            if not tenants:
                tenants = s.cache.encoder.namespace_usage()
        except Exception as e:  # noqa: BLE001 — a debug read must never
            # throw out of the HTTP handler
            per[str(rid)] = {"error": str(e)}
    if limit is not None and limit >= 0 and len(tenants) > limit:
        tenants = dict(list(tenants.items())[:limit])
    return {
        "replicas": n_live,
        "per_replica": per,
        "reconciler": recon.stats() if recon is not None else None,
        "tenants": tenants,
    }
