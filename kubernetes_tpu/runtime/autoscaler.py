"""Guarded autoscaler actuation: close the capacity-plan loop (ISSUE 19).

PR 15's CapacityPlanner *recommends* ("add 37 × shape-C; n12,n47
drainable") but nothing *enacts*.  AutoscalerController is the missing
actuator — the cluster-autoscaler analog scoped to this repo's store:

  plan (capacity.summary()["recommendation"])
      -> decide (PURE: dual-threshold hysteresis, stable-round streaks,
                 cooldown window bounding direction changes, batch caps,
                 fleet floor/ceiling)
      -> enact (REAL apiserver verbs: scale-up registers nodes built
                from the winning nodeShapeCatalog shape; scale-down
                cordons + drains through controllers.drain_waves — the
                same PDB/Retry-After wave loop as the chaos upgrade
                monkey — then deletes; displaced pods re-enter via the
                shed-exempt displaced requeue path, so conservation
                holds by construction)

Robustness is the headline:

  * Dual-threshold hysteresis: scale-up needs `up_stable_rounds`
    consecutive FRESH plans showing overflow; scale-down needs
    `down_stable_rounds` showing a drainable set AND zero overflow.
    Streaks reset after every actuation, so each move needs renewed
    conviction.
  * Cooldown window: at most `max_direction_changes` add<->remove
    direction changes per `cooldown_s` window — an oscillating plan
    cannot flap the fleet (pinned by test; blocked flips increment
    scheduler_autoscaler_flaps_total and HOLD).
  * Rollback: a scale-down whose drain strands pods past
    `drain_deadline_s` (or whose PDBs never reopen) un-cordons every
    victim and aborts — the fleet returns to its pre-actuation state; a
    scale-up failing mid-batch deregisters the partial batch.  Both
    increment scheduler_autoscaler_rollbacks_total{direction=...}.
  * Invariant rules: node-lifecycle conservation (every registered node
    ends active/removed — InvariantChecker.note_node_* seams), no
    eviction without budget debit (try_evict reports grants), and the
    capacity floor — a scale-down that would drop fleet allocatable
    below committed usage is REFUSED before the first cordon.
  * Replayable actuation ledger: every step appends one JSONL record
    {seq, t, plan, state, decision, outcome}; replay_actuations()
    re-runs the pure decide() over the recorded inputs and verifies the
    decisions are bit-identical (`bench.py --replay` sniffs the file
    type) — a scale event is re-verifiable offline, like a scheduling
    cycle.
  * Dry-run: decide + record, never mutate.

Chaos primitives for a MISBEHAVING actuator live in runtime/chaos.py:
stuck_drain (match-all zero-budget PDB), actuation_fault (mid-batch
register failure), plan_oscillation (flip-flopping plan source).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Set

from kubernetes_tpu.api.factory import make_node
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.controllers import (
    EVICT_DISPLACE,
    drain_waves,
    uncordon_node,
)
from kubernetes_tpu.utils import klog
from kubernetes_tpu.utils import metrics as m

# decision actions (the decide() vocabulary)
HOLD = "hold"
ADD = "add"
REMOVE = "remove"

# node label stamped on every node this actuator registers, so the
# managed set survives a controller restart (rebuilt from the store)
MANAGED_LABEL = "scheduler.kubernetes-tpu.io/autoscaled"
SHAPE_LABEL = "scheduler.kubernetes-tpu.io/shape"

# actuation-ledger framing
LEDGER_KIND = "autoscaler-actuations"
LEDGER_VERSION = 1


class ActuationFault(RuntimeError):
    """Injected mid-batch registration failure (chaos.actuation_fault):
    the cloud API returned 5xx halfway through a scale-up batch."""


@dataclass
class AutoscalerConfig:
    """Knobs for the guarded actuation loop (see README "Autoscaling")."""

    enabled: bool = True
    interval_s: float = 0.2          # actuation loop period
    up_overflow_threshold: int = 1   # overflow pods to arm scale-up
    down_drainable_threshold: int = 1  # drainable nodes to arm scale-down
    up_stable_rounds: int = 2        # fresh plans agreeing before adding
    down_stable_rounds: int = 3      # removal needs more conviction
    cooldown_s: float = 5.0          # direction-change window
    max_direction_changes: int = 2   # add<->remove flips per window
    max_nodes_per_round: int = 4     # batch cap per actuation
    drain_wave_size: int = 2
    drain_retry_rounds: int = 8
    drain_retry_after_s: float = 0.05
    drain_deadline_s: float = 5.0    # stuck-drain rollback deadline
    min_nodes: int = 1               # fleet floor (never drain below)
    max_nodes: int = 256             # fleet ceiling (never add above)
    dry_run: bool = False            # decide + record, never mutate
    node_prefix: str = "autoscale"   # registered node name prefix
    scale_down_unmanaged: bool = False  # allow draining base nodes


def _compact_plan(plan: Optional[dict]) -> Optional[dict]:
    """The slice of a capacity recommendation decide() consumes (plus
    backlog for humans) — this is what the actuation ledger records, so
    replay re-runs decide over byte-identical inputs."""
    if not plan:
        return None
    dr = plan.get("drainable") or {}
    return {
        "cycle": plan.get("cycle"),
        "backlog_pods": plan.get("backlog_pods"),
        "overflow_pods": plan.get("overflow_pods"),
        "scale_up": plan.get("scale_up"),
        "drainable": {
            "count": dr.get("count", 0),
            "nodes": list(dr.get("nodes") or []),
        },
    }


class AutoscalerController:
    """The guarded actuation loop.  Thread-safe: step() serializes under
    a lock, so the background loop, a POST /debug/capacity/enact, and a
    test driving step() directly cannot interleave an actuation."""

    def __init__(
        self,
        cluster: LocalCluster,
        *,
        planner=None,
        config: Optional[AutoscalerConfig] = None,
        invariants=None,
        clock: Callable[[], float] = time.monotonic,
        catalog: Optional[List[dict]] = None,
        ledger=None,
        ledger_path: Optional[str] = None,
    ):
        self.cluster = cluster
        self.planner = planner
        self.config = config or AutoscalerConfig()
        self.invariants = invariants
        self.clock = clock
        self.ledger = ledger  # DecisionLedger: record_event mirror
        self.ledger_path = ledger_path
        if catalog is not None:
            self.catalog = list(catalog)
        elif planner is not None and getattr(planner, "catalog", None):
            self.catalog = list(planner.catalog)
        else:
            from kubernetes_tpu.runtime.capacity import DEFAULT_SHAPE_CATALOG

            self.catalog = list(DEFAULT_SHAPE_CATALOG)

        self._lock = threading.Lock()        # serializes step()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._plan_source: Callable[[], Optional[dict]] = self._planner_plan
        self._t0 = self.clock()
        self._last_step_t: Optional[float] = None
        self._seq = 0
        self._node_seq = 0
        self._last_cycle: Optional[int] = None
        self._last_direction: Optional[str] = None
        self._changes: Deque[float] = deque()  # direction-change stamps
        self._up_streak = 0
        self._down_streak = 0
        self._cost_node_s = 0.0
        self._fleet_peak = 0
        self._fleet_min = 1 << 30
        self._counts: Dict[str, int] = {
            "add": 0, "remove": 0, "hold": 0, "flaps": 0, "rollbacks": 0,
        }
        self._history: Deque[dict] = deque(maxlen=256)
        self._fault: Optional[dict] = None  # {"after": n, "count": k}
        self._ledger_fh = None
        # rebuild the managed set from the store (restart survival)
        self._managed: Set[str] = {
            n.name for n in cluster.list("nodes")
            if (n.labels or {}).get(MANAGED_LABEL) == "true"
        }

    # ------------------------------------------------------------- decide

    @staticmethod
    def decide(plan: Optional[dict], state: dict,
               cfg: AutoscalerConfig) -> dict:
        """PURE actuation policy: (plan, observed state, config) -> one
        decision dict.  No clock, no store, no randomness — the
        actuation ledger records its exact inputs, and replay verifies
        the recorded decision falls out bit-identically.

        `state` keys: fleet (int), managed (sorted list of node names
        this actuator registered), pending_pods, idle_managed /
        idle_nodes (pod-free, uncordoned), last_cycle, last_direction,
        recent_changes (direction changes inside the cooldown window),
        up_streak, down_streak."""
        d: dict = {
            "action": HOLD,
            "reason": "",
            "up_streak": int(state.get("up_streak") or 0),
            "down_streak": int(state.get("down_streak") or 0),
        }
        managed = list(state.get("managed") or [])
        fleet = int(state.get("fleet") or 0)
        fresh = bool(plan) and plan.get("cycle") is not None and (
            plan.get("cycle") != state.get("last_cycle")
        )
        if fresh:
            d["cycle"] = plan.get("cycle")
            su = plan.get("scale_up") or None
            overflow = int(plan.get("overflow_pods") or 0)
            dr = plan.get("drainable") or {}
            want_up = (
                su is not None
                and int(su.get("count") or 0) > 0
                and overflow >= cfg.up_overflow_threshold
            )
            if cfg.scale_down_unmanaged:
                victims_all = list(dr.get("nodes") or [])
            else:
                victims_all = [
                    n for n in (dr.get("nodes") or []) if n in managed
                ]
            want_down = (
                not want_up
                and overflow == 0
                and int(dr.get("count") or 0) >= cfg.down_drainable_threshold
                and bool(victims_all)
            )
            down_reason = "plan-drainable"
        else:
            # stale or missing plan: never scale UP on old evidence, but
            # scale DOWN from direct observation — the planner only
            # solves during scheduling cycles, so an IDLE cluster's plan
            # is permanently stale.  Waiting for a fresh solve would pin
            # every autoscaled node forever; the live store (zero
            # pending pods, pod-free managed nodes) is itself fresh
            # evidence, re-verified each round by the hysteresis streak.
            su = None
            want_up = False
            victims_all = list(
                (state.get("idle_nodes") if cfg.scale_down_unmanaged
                 else state.get("idle_managed")) or []
            )
            want_down = (
                int(state.get("pending_pods") or 0) == 0
                and len(victims_all) >= cfg.down_drainable_threshold
            )
            down_reason = "idle-observed"
            if not want_down:
                d["down_streak"] = 0
                d["reason"] = (
                    "stale-plan"
                    if plan and plan.get("cycle") is not None else "no-plan"
                )
                return d

        # dual-threshold hysteresis: independent stable-round streaks
        d["up_streak"] = d["up_streak"] + 1 if want_up else 0
        d["down_streak"] = d["down_streak"] + 1 if want_down else 0
        if want_up and d["up_streak"] >= cfg.up_stable_rounds:
            direction = ADD
        elif want_down and d["down_streak"] >= cfg.down_stable_rounds:
            direction = REMOVE
        else:
            d["reason"] = "hysteresis"
            return d

        # cooldown: a direction CHANGE while the window is saturated is
        # a flap — hold instead of thrash
        last = state.get("last_direction")
        if (
            last is not None
            and direction != last
            and int(state.get("recent_changes") or 0)
            >= cfg.max_direction_changes
        ):
            d["reason"] = "cooldown"
            d["flap"] = True
            return d

        if direction == ADD:
            count = min(
                int(su.get("count") or 0),
                cfg.max_nodes_per_round,
                max(0, cfg.max_nodes - fleet),
            )
            if count <= 0:
                d["reason"] = "fleet-ceiling"
                return d
            d.update(
                action=ADD, reason="plan-overflow", count=count,
                shape=su.get("shape"), up_streak=0,
            )
        else:
            count = min(
                len(victims_all),
                cfg.max_nodes_per_round,
                max(0, fleet - cfg.min_nodes),
            )
            if count <= 0:
                d["reason"] = "fleet-floor"
                return d
            d.update(
                action=REMOVE, reason=down_reason, count=count,
                victims=victims_all[:count], down_streak=0,
            )
        return d

    # --------------------------------------------------------------- step

    def step(self, dry_run: Optional[bool] = None) -> dict:
        """One actuation round: read plan, decide, enact, record.
        Returns the ledger record.  `dry_run` overrides the config knob
        for this round only (the POST endpoint's ?dryRun=)."""
        with self._lock:
            return self._step_locked(dry_run)

    def _step_locked(self, dry_run: Optional[bool]) -> dict:
        now = self.clock()
        # cost objective: managed node-seconds, integrated per step
        if self._last_step_t is not None:
            self._cost_node_s += len(self._managed) * (now - self._last_step_t)
        self._last_step_t = now
        m.AUTOSCALER_COST.set(self._cost_node_s)
        m.AUTOSCALER_MANAGED.set(float(len(self._managed)))

        plan = None
        try:
            plan = self._plan_source()
        except Exception as e:  # noqa: BLE001 — a broken planner holds
            klog.errorf("autoscaler plan source failed: %s", e)
        state = self._state(now)
        self._fleet_peak = max(self._fleet_peak, state["fleet"])
        self._fleet_min = min(self._fleet_min, state["fleet"])
        decision = self.decide(plan, state, self.config)

        if "cycle" in decision:
            self._last_cycle = decision["cycle"]
        self._up_streak = decision["up_streak"]
        self._down_streak = decision["down_streak"]
        if decision.get("flap"):
            self._counts["flaps"] += 1
            m.AUTOSCALER_FLAPS.inc()

        dry = self.config.dry_run if dry_run is None else bool(dry_run)
        outcome: dict = {"enacted": False, "dry_run": dry}
        if decision["action"] == ADD:
            if dry:
                outcome["planned"] = decision["count"]
            else:
                outcome = self._scale_up(decision)
        elif decision["action"] == REMOVE:
            if dry:
                outcome["planned"] = decision["count"]
            else:
                outcome = self._scale_down(decision)
        else:
            self._counts["hold"] += 1

        if outcome.get("enacted"):
            self._counts[decision["action"]] += 1
            if (
                self._last_direction is not None
                and decision["action"] != self._last_direction
            ):
                self._changes.append(now)
            self._last_direction = decision["action"]
            # renewed conviction required after every actuation
            self._up_streak = 0
            self._down_streak = 0
        if outcome.get("rollback"):
            self._counts["rollbacks"] += 1

        rec = {
            "seq": self._seq,
            "t": round(now - self._t0, 6),
            "plan": _compact_plan(plan),
            "state": state,
            "decision": decision,
            "outcome": outcome,
        }
        self._seq += 1
        self._record(rec)
        # timeline annotation (ISSUE 20): non-hold decide() rounds mark
        # the metrics timeline (hold rounds would flood the bounded
        # event ring at loop cadence — the scaling story is the
        # add/remove edges).  Best-effort: the timeline must never
        # break an actuation round.
        if decision["action"] != HOLD:
            try:
                from kubernetes_tpu.runtime import timeline as timeline_mod

                timeline_mod.get_default().annotate(
                    "autoscaler",
                    f"{decision['action']} x{decision.get('count', 0)}"
                    f" (fleet {state['fleet']}"
                    f"{', enacted' if outcome.get('enacted') else ''}"
                    f"{', rollback' if outcome.get('rollback') else ''})",
                    action=decision["action"],
                    enacted=bool(outcome.get("enacted")),
                )
            except Exception as e:  # noqa: BLE001
                klog.errorf("autoscaler timeline annotate failed: %s", e)
        return rec

    def enact(self, dry_run: Optional[bool] = None) -> dict:
        """POST /debug/capacity/enact: one guarded actuation round NOW
        (same lock as the loop — no interleaving)."""
        return self.step(dry_run=dry_run)

    # -------------------------------------------------------------- enact

    def _scale_up(self, decision: dict) -> dict:
        shape = self._shape_entry(decision.get("shape"))
        added: List[str] = []
        try:
            for _ in range(int(decision["count"])):
                self._maybe_fault()
                name = f"{self.config.node_prefix}-{self._node_seq}"
                self._node_seq += 1
                node = make_node(
                    name,
                    cpu=str(shape.get("cpu", "4")),
                    mem=str(shape.get("memory", "8Gi")),
                    pods=int(float(shape.get("pods", 110))),
                    labels={
                        MANAGED_LABEL: "true",
                        SHAPE_LABEL: str(shape.get("name", "")),
                    },
                )
                if self.invariants is not None:
                    self.invariants.note_node_registered(name)
                self.cluster.add_node(node)
                self._managed.add(name)
                added.append(name)
                if self.invariants is not None:
                    self.invariants.note_node_active(name)
                m.AUTOSCALER_NODES_ADDED.inc()
        except Exception as e:  # noqa: BLE001 — incl. ActuationFault
            # mid-batch failure: deregister the partial batch so the
            # fleet never keeps a half-actuated scale event
            for name in added:
                try:
                    self.cluster.delete("nodes", "", name)
                except Exception:  # noqa: BLE001 — best-effort unwind
                    pass
                self._managed.discard(name)
                if self.invariants is not None:
                    self.invariants.note_node_removed(name)
            m.AUTOSCALER_ROLLBACKS.inc(direction="add")
            klog.errorf(
                "autoscaler scale-up failed mid-batch (%s); "
                "deregistered %d node(s)", e, len(added),
            )
            return {
                "enacted": False,
                "dry_run": False,
                "rollback": True,
                "error": str(e),
                "deregistered": added,
            }
        return {
            "enacted": True,
            "dry_run": False,
            "added": added,
            "shape": shape.get("name"),
        }

    def _scale_down(self, decision: dict) -> dict:
        victims = [
            v for v in decision.get("victims") or []
            if self.cluster.get("nodes", "", v) is not None
        ]
        if not victims:
            return {"enacted": False, "dry_run": False,
                    "refused": "victims-gone"}
        # capacity floor: AFTER removing the victims, the remaining
        # fleet's allocatable must still cover every bound pod's
        # requests (including pods about to be displaced off the
        # victims) — refuse BEFORE the first cordon otherwise
        if not self._floor_ok(victims):
            return {"enacted": False, "dry_run": False,
                    "refused": "capacity-floor"}
        if self.invariants is not None:
            for v in victims:
                self.invariants.note_node_draining(v)
        deadline = self.clock() + self.config.drain_deadline_s
        res = drain_waves(
            self.cluster,
            victims,
            wave_size=self.config.drain_wave_size,
            mode=EVICT_DISPLACE,
            retry_rounds=self.config.drain_retry_rounds,
            retry_after_s=self.config.drain_retry_after_s,
            reason="scale-down",
            invariants=self.invariants,
            abort=lambda: self.clock() > deadline or self._stop.is_set(),
        )
        stranded = [
            p for p in self.cluster.list("pods")
            if p.spec.node_name in victims
            and p.status.phase not in ("Succeeded", "Failed")
        ]
        if res["aborted"] or res["skipped"] or stranded:
            # rollback: return every victim to service; pods displaced
            # by the partial drain re-enter the queue shed-exempt and
            # reschedule — the fleet is back to its pre-actuation state
            for v in victims:
                uncordon_node(self.cluster, v)
                if self.invariants is not None:
                    self.invariants.note_node_active(v)
            m.AUTOSCALER_ROLLBACKS.inc(direction="remove")
            klog.warningf(
                "autoscaler scale-down rolled back: aborted=%s "
                "skipped=%d stranded=%d",
                res["aborted"], len(res["skipped"]), len(stranded),
            )
            return {
                "enacted": False,
                "dry_run": False,
                "rollback": True,
                "stranded": len(stranded),
                "skipped": len(res["skipped"]),
                "aborted": res["aborted"],
                "evicted": len(res["evicted"]),
            }
        removed: List[str] = []
        for v in victims:
            self.cluster.delete("nodes", "", v)
            self._managed.discard(v)
            removed.append(v)
            if self.invariants is not None:
                self.invariants.note_node_removed(v)
            m.AUTOSCALER_NODES_REMOVED.inc()
        return {
            "enacted": True,
            "dry_run": False,
            "removed": removed,
            "evicted": len(res["evicted"]),
            "waves": res["waves"],
        }

    # ------------------------------------------------------------ helpers

    def _planner_plan(self) -> Optional[dict]:
        p = self.planner
        if p is None:
            from kubernetes_tpu.runtime import capacity

            p = capacity.get_default()
        if p is None:
            return None
        return p.summary().get("recommendation")

    def set_plan_source(self, fn: Callable[[], Optional[dict]]) -> None:
        """Swap the plan input (chaos.plan_oscillation, tests)."""
        self._plan_source = fn

    def arm_register_fault(self, after: int = 0, count: int = 1) -> None:
        """Next scale-up batch: fail registration #after+1 .. #after+count
        (chaos.actuation_fault — the mid-batch cloud-API 5xx)."""
        self._fault = {"after": int(after), "count": int(count)}

    def _maybe_fault(self) -> None:
        f = self._fault
        if f is None:
            return
        if f["after"] > 0:
            f["after"] -= 1
            return
        if f["count"] > 0:
            f["count"] -= 1
            if f["count"] == 0:
                self._fault = None
            raise ActuationFault("injected actuation fault (chaos)")
        self._fault = None

    def _state(self, now: float) -> dict:
        nodes = list(self.cluster.list("nodes"))
        fleet = [n.name for n in nodes]
        # live occupancy: pod counts per node + store-visible backlog
        # (the observation half of decide()'s scale-down evidence)
        per_node: Dict[str, int] = {}
        pending = 0
        for p in self.cluster.list("pods"):
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            if p.spec.node_name:
                per_node[p.spec.node_name] = (
                    per_node.get(p.spec.node_name, 0) + 1
                )
            else:
                pending += 1
        idle_all = sorted(
            n.name for n in nodes
            if not n.spec.unschedulable and not per_node.get(n.name)
        )[:64]
        while self._changes and now - self._changes[0] > self.config.cooldown_s:
            self._changes.popleft()
        return {
            "fleet": len(fleet),
            "managed": sorted(self._managed & set(fleet)),
            "pending_pods": pending,
            "idle_nodes": idle_all,
            "idle_managed": [n for n in idle_all if n in self._managed],
            "last_cycle": self._last_cycle,
            "last_direction": self._last_direction,
            "recent_changes": len(self._changes),
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
        }

    def _shape_entry(self, name: Optional[str]) -> dict:
        for entry in self.catalog:
            if entry.get("name") == name:
                return entry
        return self.catalog[0] if self.catalog else {"name": "default"}

    def _floor_ok(self, victims: List[str]) -> bool:
        vset = set(victims)
        rem = [0.0, 0.0, 0.0]  # cpu(milli), memory(bytes), pod slots
        for n in self.cluster.list("nodes"):
            if n.name in vset or n.spec.unschedulable:
                continue
            alloc = n.status.allocatable
            rem[0] += float(alloc["cpu"].milli) if "cpu" in alloc else 0.0
            rem[1] += float(alloc["memory"]) if "memory" in alloc else 0.0
            rem[2] += float(alloc["pods"]) if "pods" in alloc else 0.0
        com = [0.0, 0.0, 0.0]
        for p in self.cluster.list("pods"):
            if not p.spec.node_name:
                continue
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            req = p.resource_request()
            com[0] += float(req["cpu"].milli) if "cpu" in req else 0.0
            com[1] += float(req["memory"]) if "memory" in req else 0.0
            com[2] += 1.0
        detail = "victims=" + ",".join(sorted(vset)[:4])
        if self.invariants is not None:
            return self.invariants.check_capacity_floor(rem, com, detail)
        return all(c <= r + 1e-3 for c, r in zip(com, rem))

    def managed_nodes(self) -> List[str]:
        return sorted(self._managed)

    # ------------------------------------------------------------- ledger

    def _record(self, rec: dict) -> None:
        self._history.append(rec)
        if self.ledger is not None:
            try:
                self.ledger.record_event({"autoscaler": rec})
            except Exception:  # noqa: BLE001 — telemetry never actuates
                pass
        if self.ledger_path:
            try:
                if self._ledger_fh is None:
                    self._ledger_fh = open(  # noqa: SIM115 — long-lived
                        self.ledger_path, "a", encoding="utf-8",
                    )
                    if self._ledger_fh.tell() == 0:
                        header = {
                            "kind": LEDGER_KIND,
                            "version": LEDGER_VERSION,
                            "config": asdict(self.config),
                        }
                        self._ledger_fh.write(
                            json.dumps(header, sort_keys=True) + "\n"
                        )
                self._ledger_fh.write(json.dumps(rec, sort_keys=True) + "\n")
                self._ledger_fh.flush()
            except OSError as e:
                klog.errorf("autoscaler ledger write failed: %s", e)

    # --------------------------------------------------------- loop/debug

    def start(self) -> None:
        if self._thread is not None or not self.config.enabled:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.config.interval_s):
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 — loop survives
                    klog.errorf("autoscaler step failed: %s", e)

        self._thread = threading.Thread(
            target=loop, name="autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        fh = self._ledger_fh
        if fh is not None:
            self._ledger_fh = None
            try:
                fh.close()
            except OSError:
                pass

    def summary(self) -> dict:
        with self._lock:
            last = self._history[-1] if self._history else None
            return {
                "enabled": self.config.enabled,
                "dry_run": self.config.dry_run,
                "running": self._thread is not None,
                "seq": self._seq,
                "managed": len(self._managed),
                "managed_nodes": sorted(self._managed)[:16],
                "cost_node_s": round(self._cost_node_s, 6),
                "fleet_peak": self._fleet_peak,
                "fleet_min": (0 if self._fleet_min == 1 << 30
                              else self._fleet_min),
                "counts": dict(self._counts),
                "direction_changes_in_window": len(self._changes),
                "last_direction": self._last_direction,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "last": last,
                "ledger_path": self.ledger_path,
            }

    def debug_payload(self, limit: int = 32) -> dict:
        out = self.summary()
        with self._lock:
            out["recent"] = list(self._history)[-max(1, int(limit)):]
        return out


# ----------------------------------------------------------------- replay


def replay_actuations(path: str) -> dict:
    """Offline re-verification of an actuation ledger (`bench.py
    --replay` on a .jsonl actuation file): re-run the PURE decide() over
    every recorded (plan, state) under the recorded config and demand
    the decision falls out bit-identical (canonical-JSON comparison).
    Returns {"records", "verified", "mismatches": [...]}."""
    header: Optional[dict] = None
    records = 0
    mismatches: List[dict] = []
    cfg = AutoscalerConfig()
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if header is None and obj.get("kind") == LEDGER_KIND:
                header = obj
                known = {
                    k: v for k, v in (obj.get("config") or {}).items()
                    if k in AutoscalerConfig.__dataclass_fields__
                }
                cfg = AutoscalerConfig(**known)
                continue
            records += 1
            got = AutoscalerController.decide(
                obj.get("plan"), obj.get("state") or {}, cfg
            )
            want = obj.get("decision")
            if json.dumps(got, sort_keys=True) != json.dumps(
                want, sort_keys=True
            ):
                mismatches.append(
                    {"seq": obj.get("seq"), "want": want, "got": got}
                )
    return {
        "kind": LEDGER_KIND,
        "records": records,
        "verified": not mismatches,
        "mismatches": mismatches[:8],
    }


def sniff_actuation_ledger(path: str) -> bool:
    """True when `path` looks like an actuation JSONL (text line starting
    with '{') rather than the binary decision-ledger stream."""
    try:
        with open(path, "rb") as fh:
            head = fh.read(1)
        return head == b"{"
    except OSError:
        return False


# ------------------------------------------------------- process default
# No factory: the controller is only present when explicitly wired, so
# get_default() may legitimately return None (runtime/defaults.py
# ProcessDefault — the shared install/default discipline).

from kubernetes_tpu.runtime.defaults import ProcessDefault

_DEFAULT = ProcessDefault("autoscaler")


def get_default() -> Optional[AutoscalerController]:
    """The process's wired AutoscalerController (None until set): the
    seam /debug/autoscaler + POST /debug/capacity/enact read through."""
    return _DEFAULT.get()


def set_default(ctrl: Optional[AutoscalerController]) -> None:
    _DEFAULT.set(ctrl)
