"""Hot-path performance observatory: host/device time attribution,
transfer accounting, and on-demand XLA profiler capture (ISSUE 11).

The r05 phase counters said dispatch was ~20 ms while fetch+commit burned
~370 ms per 10k pods — but nothing attributed that wall time between host
Python, the wire, and actual device execution, which is exactly the
measurement the device-resident megacycle work (ROADMAP item 2) and the
learned-scoring loop (item 4) need.  This module is that instrument:

  * **Per-cycle cost model.**  The scheduler feeds `on_cycle` one record
    per committed cycle, split by the ready-fence timestamps around the
    existing AsyncFetch/dispatch seams (codec/transfer.py):

      - `host_enqueue`     encode + extender fan-out + launch enqueue
                           (scheduling-thread Python until the dispatch
                           returned with the device still computing)
      - `device_execute`   dispatch -> computation-ready, stamped by the
                           block_until_ready fence on the fetch worker
      - `d2h_materialize`  the residual host copy after ready (with the
                           async copy prefetch this is usually ~0)
      - `host_stall`       the scheduling thread's residual wait at the
                           ready fence (phase_seconds' fetch_block)
      - `host_commit`      state commit + bind/event tail + ledger +
                           telemetry (the full host tail)

    host_enqueue + host_stall + host_commit partitions the cycle's wall
    clock (the reconciliation tests/test_perfobs.py pins); the device
    pair OVERLAPS the host phases — that overlap working is the async
    result path doing its job.  Per (phase, executable width) the
    observatory maintains an EWMA matrix — the generalization of PR 8's
    launch EWMA to the whole cycle — exported as
    scheduler_perf_phase_ewma_seconds{phase,width} and at /debug/perf.

  * **Transfer accounting.**  codec/transfer.py notes bytes/calls at
    every wire seam (snapshot upload, dirty-row scatter, batch
    replicate, fetch) from array nbytes with no device sync; the
    scheduler snapshots the totals per cycle and hands the delta here,
    so every sample (and every cycle span) carries what the wire moved.

  * **On-demand profiler capture.**  `ProfilerCapture` wraps
    jax.profiler start/stop in a throttled, bounded window into a
    configurable directory — `GET /debug/profile?seconds=N` on the
    health server and the apiserver.  Where the backend lacks profiler
    support the capture degrades to a graceful no-op.  The PR 5
    `device_annotation` labels (ktpu.fetch / ktpu.snapshot_upload / …)
    make the captured device timeline phase-legible.

`OBSERVATORY`/`get_default`/`set_default` follow the flightrecorder
RECORDER pattern: a Scheduler installs its observatory as the process
default so /debug/perf serves it without extra wiring.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from kubernetes_tpu.utils import klog
from kubernetes_tpu.utils import metrics as m

# the cost-model phases, in report order.  host_* phases partition the
# cycle's scheduling-thread wall clock; device_execute/d2h_materialize
# are measured on the fetch worker and OVERLAP the host phases.
PHASES = (
    "host_enqueue",
    "device_execute",
    "d2h_materialize",
    "host_stall",
    "host_commit",
)
HOST_PHASES = ("host_enqueue", "host_stall", "host_commit")
DEVICE_PHASES = ("device_execute", "d2h_materialize")


class ProfilerCapture:
    """Throttled, bounded jax.profiler capture window.

    One capture at a time; `min_interval_s` between stop and the next
    start (an operator mashing refresh on /debug/profile must not turn
    the profiler into a load generator); `max_seconds` caps the window
    whatever the query asks.  Backends without profiler support (or a
    jax build without the profiler extra) degrade to a graceful no-op:
    start() reports supported=False instead of raising."""

    def __init__(
        self,
        profile_dir: Optional[str] = None,
        max_seconds: float = 60.0,
        min_interval_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.profile_dir = (
            profile_dir
            or os.environ.get("KTPU_PROFILE_DIR")
            or "/tmp/ktpu_profile"
        )
        self.max_seconds = float(max_seconds)
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._active_dir: Optional[str] = None
        self._active_until = 0.0
        self._last_stop: Optional[float] = None
        self.captures_total = 0
        self.last: Optional[dict] = None  # last start/stop outcome

    def start(self, seconds: float) -> dict:
        """Begin a bounded capture; a daemon timer stops it after
        `seconds` (clamped to [0.05, max_seconds]).  Returns a jsonable
        status — started / throttled / in-progress / unsupported —
        never raises (this is a debug endpoint body)."""
        seconds = max(0.05, min(float(seconds), self.max_seconds))
        now = self._clock()
        with self._lock:
            if self._active_dir is not None:
                return {
                    "started": False,
                    "reason": "capture already in progress",
                    "dir": self._active_dir,
                    "retry_after_s": round(
                        max(0.0, self._active_until - now), 2
                    ),
                }
            if (
                self._last_stop is not None
                and now - self._last_stop < self.min_interval_s
            ):
                return {
                    "started": False,
                    "reason": "throttled",
                    "retry_after_s": round(
                        self.min_interval_s - (now - self._last_stop), 2
                    ),
                }
            out_dir = os.path.join(
                self.profile_dir,
                time.strftime("%Y%m%d-%H%M%S") + f"-{self.captures_total}",
            )
            # reserve the slot BEFORE the (possibly slow — profiler
            # server init measures ~10s on some sandboxes) start call,
            # so a concurrent start sees in-progress and status readers
            # never block behind it
            self._active_dir = out_dir
            self._active_until = now + seconds
        try:
            import jax

            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
        except Exception as e:  # noqa: BLE001 — no-op where the
            # backend/build lacks profiler support
            with self._lock:
                self._active_dir = None
                self.last = {
                    "started": False, "supported": False, "error": str(e),
                }
                return dict(self.last)
        with self._lock:
            self.last = {
                "started": True, "seconds": seconds, "dir": out_dir,
            }
        t = threading.Timer(seconds, self._stop)
        t.daemon = True
        t.start()
        klog.infof(
            "profiler capture started: %.2fs into %s", seconds, out_dir
        )
        return dict(self.last)

    def _stop(self) -> None:
        with self._lock:
            if self._active_dir is None:
                return
            out_dir, self._active_dir = self._active_dir, None
            self._last_stop = self._clock()
        try:
            import jax

            jax.profiler.stop_trace()
            outcome = {"stopped": True, "dir": out_dir}
        except Exception as e:  # noqa: BLE001 — a failed stop must
            # not wedge the capture state machine
            outcome = {"stopped": False, "error": str(e)}
        with self._lock:
            if outcome.get("stopped"):
                self.captures_total += 1
            self.last = outcome
        klog.infof("profiler capture finished: %s", out_dir)

    def status(self) -> dict:
        with self._lock:
            return {
                "active": self._active_dir is not None,
                "dir": self._active_dir or self.profile_dir,
                "captures_total": self.captures_total,
                "max_seconds": self.max_seconds,
                "min_interval_s": self.min_interval_s,
                "last": dict(self.last) if self.last else None,
            }


class PerfObservatory:
    """Per-scheduler cost-model aggregation point.

    The scheduling thread calls `on_cycle` once per committed cycle
    (runtime/scheduler.py stamps the call's cost into
    scheduler_perfobs_seconds_total — the <2% budget perf_smoke pins);
    readers (/debug/perf, heartbeat, bench) come from other threads and
    take the lock only around ring/summary state."""

    def __init__(
        self,
        ring_capacity: int = 256,
        ewma_alpha: float = 0.2,
        profile_dir: Optional[str] = None,
    ):
        self._lock = threading.Lock()
        self._alpha = float(ewma_alpha)
        # phase -> {width -> ewma seconds}: the phase x executable-width
        # cost matrix (widths are the engine's padded pow2 shapes)
        self._ewma: Dict[str, Dict[int, float]] = {p: {} for p in PHASES}
        self._totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._wall_total = 0.0
        self.cycles_total = 0
        self.degraded_total = 0
        self._ring: deque = deque(maxlen=max(1, int(ring_capacity)))
        # heartbeat watermarks: totals at the last heartbeat_window()
        self._hb_host = 0.0
        self._hb_dev = 0.0
        self._hb_xfer: Dict[str, dict] = {}
        self.profiler = ProfilerCapture(profile_dir=profile_dir)

    # ------------------------------------------------------ hot-path API

    def on_cycle(
        self,
        width: int,
        tier: str,
        degraded: bool,
        enqueue_s: float,
        execute_s: float,
        materialize_s: float,
        stall_s: float,
        commit_s: float,
        wall_s: float,
        transfers: Optional[dict] = None,
        trace_id: str = "",
        mega: Optional[Tuple[int, int]] = None,
    ) -> None:
        """Fold one committed cycle into the cost model.  `transfers` is
        the cycle's codec.transfer.transfer_delta — what the wire moved
        between this cycle's dispatch and its commit tail.  `mega` =
        (k, K) marks sub-batch k of a K-deep megacycle launch (ISSUE
        12): its device/enqueue/wall figures are the 1/K share of the
        one shared launch, reconstructed by the scheduler so the phase
        totals still reconcile across the megacycle path."""
        split = {
            "host_enqueue": float(enqueue_s),
            "device_execute": float(execute_s),
            "d2h_materialize": float(materialize_s),
            "host_stall": float(stall_s),
            "host_commit": float(commit_s),
        }
        width = int(width)
        sample = {
            "cycle_wall_s": round(float(wall_s), 6),
            "width": width,
            "tier": tier,
            "degraded": bool(degraded),
            "split_s": {k: round(v, 6) for k, v in split.items()},
            # the wall clock the host split does NOT account for: ~0 on
            # the synchronous path; under pipeline_commit it is the
            # overlap window (the cycle's tail ran while the next batch
            # dispatched), which is the pipeline working, not a leak
            "unaccounted_s": round(
                float(wall_s) - sum(split[p] for p in HOST_PHASES), 6
            ),
            "transfers": transfers or {},
            "trace_id": trace_id,
            **({"mega": [int(mega[0]), int(mega[1])]}
               if mega is not None else {}),
        }
        with self._lock:
            for phase, v in split.items():
                self._totals[phase] += v
                row = self._ewma[phase]
                prev = row.get(width)
                row[width] = (
                    v if prev is None else prev + self._alpha * (v - prev)
                )
            self._wall_total += float(wall_s)
            self.cycles_total += 1
            if degraded:
                self.degraded_total += 1
            self._ring.append(sample)
        for phase, v in split.items():
            m.PERF_PHASE_EWMA.set(
                self._ewma[phase][width], phase=phase, width=str(width)
            )

    # ----------------------------------------------------------- readers

    def host_device_split(self) -> Dict[str, float]:
        """Cumulative attribution: scheduling-thread host seconds vs
        device-side seconds (the overlapping execute+materialize
        window), plus total cycle wall."""
        with self._lock:
            host = sum(self._totals[p] for p in HOST_PHASES)
            dev = sum(self._totals[p] for p in DEVICE_PHASES)
            return {
                "host_s": round(host, 6),
                "device_s": round(dev, 6),
                "wall_s": round(self._wall_total, 6),
            }

    def heartbeat_window(self) -> Tuple[float, float, str]:
        """(host_ms, dev_ms, top transfer seam) since the LAST call —
        the heartbeat satellite.  The top seam is the direction/seam
        that moved the most bytes in the window ("none" when the wire
        was quiet)."""
        from kubernetes_tpu.codec.transfer import transfer_totals

        xfer = transfer_totals()
        with self._lock:
            host = sum(self._totals[p] for p in HOST_PHASES)
            dev = sum(self._totals[p] for p in DEVICE_PHASES)
            host_ms = (host - self._hb_host) * 1000.0
            dev_ms = (dev - self._hb_dev) * 1000.0
            self._hb_host, self._hb_dev = host, dev
            prev, self._hb_xfer = self._hb_xfer, xfer
        top, top_bytes = "none", 0
        for key, cur in xfer.items():
            delta = cur["bytes"] - prev.get(key, {}).get("bytes", 0)
            if delta > top_bytes:
                top, top_bytes = key, delta
        if top != "none":
            top = f"{top}:{top_bytes}B"
        return host_ms, dev_ms, top

    def summary(self) -> dict:
        from kubernetes_tpu.codec.transfer import transfer_totals

        with self._lock:
            totals = {p: round(v, 6) for p, v in self._totals.items()}
            cycles = self.cycles_total
            degraded = self.degraded_total
            wall = self._wall_total
        host = sum(totals[p] for p in HOST_PHASES)
        dev = sum(totals[p] for p in DEVICE_PHASES)
        return {
            "cycles": cycles,
            "degraded_cycles": degraded,
            "wall_s": round(wall, 6),
            "host_s": round(host, 6),
            "device_s": round(dev, 6),
            # the reconciliation figure the acceptance test pins: on the
            # synchronous path the host split accounts for ~all of wall
            "unaccounted_s": round(wall - host, 6),
            "totals_s": totals,
            "transfers": transfer_totals(),
        }

    def ewma_matrix(self) -> Dict[str, Dict[str, float]]:
        """{phase: {width: ewma seconds}} — the phase x executable-width
        cost matrix (json keys are strings)."""
        with self._lock:
            return {
                p: {str(w): round(s, 6) for w, s in sorted(row.items())}
                for p, row in self._ewma.items()
            }

    def debug_payload(self, limit: Optional[int] = None) -> dict:
        """GET /debug/perf body: summary + EWMA matrix + transfer totals
        + profiler status + the newest `limit` per-cycle samples (the
        shared debug_body halves the limit until the body fits the 4MB
        cap, like its siblings)."""
        with self._lock:
            samples = list(self._ring)
        if limit is not None and limit >= 0:
            samples = samples[-limit:] if limit else []
        return {
            "summary": self.summary(),
            "ewma_s": self.ewma_matrix(),
            "profiler": self.profiler.status(),
            "samples": samples,
        }


def profile_request(query: str = "") -> dict:
    """GET /debug/profile handler body (health server + apiserver):
    ?seconds=N starts a bounded capture through the default
    observatory's ProfilerCapture; malformed/missing seconds default to
    2.  Never raises — the body reports the outcome."""
    from urllib.parse import parse_qs

    try:
        raw = parse_qs(query).get("seconds", ["2"])[0]
        seconds = float(raw)
    except (ValueError, TypeError):
        seconds = 2.0
    return get_default().profiler.start(seconds)


# process-wide default: the observatory /debug/perf serves when none
# was wired explicitly; a Scheduler installs its own here at
# construction.  Replica 0 wins the default, siblings register
# alongside (runtime/defaults.py ProcessDefault)
from kubernetes_tpu.runtime.defaults import ProcessDefault  # noqa: E402

_DEFAULT = ProcessDefault("perfobs", PerfObservatory)


def get_default() -> PerfObservatory:
    return _DEFAULT.get()


def set_default(obs: PerfObservatory, replica: int = 0) -> None:
    _DEFAULT.set(obs, replica)


def replica_instances() -> dict:
    """{replica id: PerfObservatory} of every install this process saw."""
    return _DEFAULT.replicas()


def __getattr__(name):  # legacy alias: perfobs.OBSERVATORY
    if name == "OBSERVATORY":
        return _DEFAULT.get()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
