"""Volume binder: assume/bind PVs alongside pod placement.

Reference: pkg/scheduler/volumebinder + the scheduling flow's
assumeVolumes/bindVolumes steps (scheduler.go:344-378): once a node is
picked, unbound WaitForFirstConsumer claims are bound to a compatible PV (or
left for the dynamic provisioner), atomically with the pod's assume; a bind
failure rolls everything back (ForgetPod + volume rollback).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.codec.encoder import SnapshotEncoder


class VolumeBinder:
    def __init__(self, encoder: SnapshotEncoder):
        self.encoder = encoder

    def assume_pod_volumes(self, pod: Pod, node_name: str) -> Tuple[bool, List]:
        """Bind the pod's unbound claims to PVs compatible with node_name.
        Returns (all_bound, assumptions) — assumptions feed revert()."""
        enc = self.encoder
        row = enc.node_rows.get(node_name)
        if row is None:
            return False, []
        assumptions = []
        for v in pod.spec.volumes:
            claim = v.get("persistentVolumeClaim")
            if not claim:
                continue
            pvc = enc.pvcs.get((pod.namespace, claim.get("claimName", "")))
            if pvc is None:
                self.revert(assumptions)
                return False, []
            if pvc.volume_name:
                continue  # already bound
            chosen = None
            for pv in enc._candidate_pvs(pvc):
                rows = set(enc._rows_matching_pv_topology(pv))
                zrows = enc._rows_matching_pv_zone(pv)
                if zrows is not None:
                    rows &= set(zrows)
                if row in rows:
                    chosen = pv
                    break
            if chosen is None:
                sc = enc.storage_classes.get(pvc.storage_class)
                if sc is not None and sc.provisioner:
                    continue  # dynamic provisioning on the chosen node
                self.revert(assumptions)
                return False, []
            old_pvc = pvc
            old_phase, old_ref = chosen.phase, chosen.claim_ref
            pvc.volume_name = chosen.name
            chosen.phase = "Bound"
            chosen.claim_ref = f"{pvc.namespace}/{pvc.name}"
            enc.generation += 1
            assumptions.append((old_pvc, chosen, old_phase, old_ref))
        return True, assumptions

    def revert(self, assumptions: List) -> None:
        for pvc, pv, old_phase, old_ref in assumptions:
            pvc.volume_name = ""
            pv.phase = old_phase
            pv.claim_ref = old_ref
            self.encoder.generation += 1
