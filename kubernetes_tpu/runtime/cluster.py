"""LocalCluster: the in-process control-plane blackboard.

The reference's architecture routes ALL component communication through the
API server + watch (SURVEY.md section 1: "blackboard architecture") — storage
(etcd3 store + watch cache, staging/.../storage/etcd3/store.go, cacher.go),
REST registry, and client-go informers (reflector -> DeltaFIFO -> shared
informer).  For the standalone framework the same seam is one in-process
object store with revisioned watch fan-out:

  * every object carries a monotonically-increasing resourceVersion
    (etcd3's mod_revision analog), bumped on each write;
  * optimistic concurrency: update(obj, expect_rv=...) fails on conflict the
    way etcd3 compare-and-swap does (GuaranteedUpdate);
  * watchers get (event_type, kind, obj) callbacks after each commit —
    the informer seam, minus the network;
  * `wire_scheduler` reproduces the scheduler's informer wiring
    (pkg/scheduler/eventhandlers.go:319-378): assigned pods -> cache,
    unassigned pods -> queue, node/service events -> cache +
    MoveAllToActiveQueue.

A real multi-process deployment swaps this for an apiserver client; the
extender sidecar's /sync endpoints speak the same three verbs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.runtime.events import EventRecorder

ADDED, MODIFIED, DELETED = "ADDED", "MODIFIED", "DELETED"

# stamped onto a pod whose binding a cluster-lifecycle event revoked
# (NodeLifecycleController eviction in displace mode, a drain wave, a
# zone outage — ISSUE 18).  wire_scheduler routes annotated unassigned
# pods through the shed-exempt displaced requeue path
# (PriorityQueue.readd_displaced + InvariantChecker.note_displaced)
# instead of the sheddable arrival path; the annotation value names the
# displacing event and is cleared by the next bind's informer echo
# being irrelevant (binds don't strip it — the value records history).
DISPLACED_BY_ANNOTATION = "kubernetes-tpu.io/displaced-by"


class ConflictError(Exception):
    """resourceVersion mismatch (etcd3 txn failure analog)."""


@dataclass
class _Stored:
    obj: object
    rv: int


class LocalCluster:
    KINDS = ("nodes", "pods", "services", "leases", "replicasets",
             "poddisruptionbudgets", "endpoints", "deployments", "jobs",
             "namespaces", "limitranges", "resourcequotas",
             "priorityclasses", "customresourcedefinitions", "apiservices",
             "daemonsets", "statefulsets", "cronjobs",
             "horizontalpodautoscalers",
             "secrets", "serviceaccounts", "roles", "rolebindings",
             "clusterroles", "clusterrolebindings",
             "persistentvolumes", "persistentvolumeclaims",
             "storageclasses", "replicationcontrollers",
             "certificatesigningrequests", "configmaps",
             "mutatingwebhookconfigurations",
             "validatingwebhookconfigurations")

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._rv = 0
        # per-instance kind registry: CRDs add kinds at runtime
        # (apiextensions-apiserver analog)
        self.kinds: List[str] = list(self.KINDS)
        self._store: Dict[str, Dict[Tuple[str, str], _Stored]] = {
            k: {} for k in self.kinds
        }
        self._watchers: List[Callable[[str, str, object], None]] = []
        # the events API analog: components record through here
        # (tools/record; queryable via cluster.events.events(...))
        self.events = EventRecorder()
        # node name -> exec handler registered by that node's kubelet
        # (the kubelet :10250 /exec endpoint's in-cluster analog; the
        # apiserver's pods/exec subresource dispatches through it —
        # ref pkg/registry/core/pod/rest/subresources.go ExecREST)
        self.node_exec: Dict[str, Callable] = {}

    # ------------------------------------------------------------ storage

    @staticmethod
    def _key(kind: str, obj) -> Tuple[str, str]:
        if kind == "nodes":
            return ("", obj.name)
        if isinstance(obj, dict):  # services / leases
            return (obj["namespace"], obj["name"])
        return (obj.namespace, obj.name)

    def _notify(self, event: str, kind: str, obj,
                rv: Optional[int] = None) -> None:
        # event_rv: the revision this event committed at, readable by
        # watchers DURING the synchronous fan-out only (they run inside
        # the store lock).  Keeps the 3-arg watcher signature while
        # letting the REST watch stream attach exact resourceVersions
        # without re-deriving them per watcher.
        self.event_rv = rv
        for w in list(self._watchers):
            w(event, kind, obj)

    def watch(self, fn: Callable[[str, str, object], None],
              bookmark: bool = False) -> None:
        """Subscribe; immediately replays the current state as ADDED events
        (the reflector LIST+WATCH contract).  With bookmark=True the replay
        ends with a ("BOOKMARK", "", None) event delivered under the SAME
        lock — no concurrent write can slip between the replay and the
        bookmark, so a reflector can swap in the replayed state atomically."""
        with self._lock:
            self._watchers.append(fn)
            for kind in self.kinds:
                for s in self._store[kind].values():
                    self.event_rv = s.rv
                    fn(ADDED, kind, s.obj)
            if bookmark:
                self.event_rv = None
                fn("BOOKMARK", "", None)

    def unwatch(self, fn: Callable[[str, str, object], None]) -> None:
        """Drop a subscription (watch-stream teardown)."""
        with self._lock:
            try:
                self._watchers.remove(fn)
            except ValueError:
                pass

    def register_kind(self, kind: str) -> None:
        """Add a storage bucket for a custom resource kind at runtime (the
        CRD establishment step; apiextensions-apiserver customresource
        storage).  Idempotent."""
        with self._lock:
            if kind not in self._store:
                self.kinds.append(kind)
                self._store[kind] = {}

    def unregister_kind(self, kind: str) -> None:
        """Drop a dynamic kind's bucket (CRD un-establishment).  Built-in
        kinds cannot be unregistered."""
        with self._lock:
            if kind in self._store and kind not in self.KINDS:
                self.kinds.remove(kind)
                del self._store[kind]

    def has_kind(self, kind: str) -> bool:
        return kind in self._store

    def create(self, kind: str, obj) -> int:
        with self._lock:
            key = self._key(kind, obj)
            if key in self._store[kind]:
                raise ConflictError(f"{kind} {key} exists")
            self._rv += 1
            self._store[kind][key] = _Stored(obj, self._rv)
            self._notify(ADDED, kind, obj, rv=self._rv)
            return self._rv

    @staticmethod
    def _finalizers(obj) -> list:
        if isinstance(obj, dict):
            meta = obj.get("metadata") or {}
            return list(meta.get("finalizers") or obj.get("finalizers") or ())
        meta = getattr(obj, "metadata", None)
        return list(getattr(meta, "finalizers", ()) or ())

    @staticmethod
    def _deleting(obj) -> bool:
        if isinstance(obj, dict):
            meta = obj.get("metadata") or {}
            return bool(meta.get("deletionTimestamp")
                        or obj.get("deletionTimestamp"))
        meta = getattr(obj, "metadata", None)
        return getattr(meta, "deletion_timestamp", None) is not None

    @staticmethod
    def _deletion_ts(obj):
        if isinstance(obj, dict):
            meta = obj.get("metadata") or {}
            return meta.get("deletionTimestamp") or obj.get("deletionTimestamp")
        meta = getattr(obj, "metadata", None)
        return getattr(meta, "deletion_timestamp", None)

    @classmethod
    def _carry_deletion_ts(cls, obj, stored):
        """deletionTimestamp is immutable through update (apimachinery
        ValidateObjectMetaUpdate: it can be set only by the delete path):
        carry the STORED object's value onto the incoming body, whatever
        the client sent — otherwise any writer with update permission
        could hard-delete (set it + omit finalizers) or resurrect (clear
        it) an object, bypassing finalizer protection."""
        ts = cls._deletion_ts(stored)
        if cls._deletion_ts(obj) == ts:
            return obj
        if isinstance(obj, dict):
            obj = dict(obj)
            meta = dict(obj.get("metadata") or {})
            if ts is None:
                meta.pop("deletionTimestamp", None)
                obj.pop("deletionTimestamp", None)
            else:
                meta["deletionTimestamp"] = ts
                if "deletionTimestamp" in obj:
                    obj["deletionTimestamp"] = ts
            if meta or "metadata" in obj:
                obj["metadata"] = meta
            return obj
        import dataclasses as _dc

        meta = getattr(obj, "metadata", None)
        if meta is not None and hasattr(meta, "deletion_timestamp"):
            return _dc.replace(
                obj, metadata=_dc.replace(meta, deletion_timestamp=ts))
        return obj

    def update(self, kind: str, obj, expect_rv: Optional[int] = None) -> int:
        with self._lock:
            key = self._key(kind, obj)
            cur = self._store[kind].get(key)
            if cur is None:
                raise ConflictError(f"{kind} {key} missing")
            if expect_rv is not None and cur.rv != expect_rv:
                raise ConflictError(f"{kind} {key} rv {cur.rv} != {expect_rv}")
            obj = self._carry_deletion_ts(obj, cur.obj)
            if self._deleting(obj) and not self._finalizers(obj):
                # the last finalizer was removed from a terminating object:
                # complete the deferred deletion (apimachinery
                # registry/generic/registry/store.go deleteWithoutFinalizers)
                del self._store[kind][key]
                self._rv += 1
                self._notify(DELETED, kind, obj, rv=self._rv)
                return self._rv
            self._rv += 1
            self._store[kind][key] = _Stored(obj, self._rv)
            self._notify(MODIFIED, kind, obj, rv=self._rv)
            return self._rv

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            key = (namespace if kind != "nodes" else "", name)
            cur = self._store[kind].get(key)
            if cur is None:
                return
            if self._finalizers(cur.obj):
                # finalizer-gated: mark terminating instead of removing
                # (the protection controllers remove their finalizer when
                # the object is safe to drop, which completes the delete)
                if not self._deleting(cur.obj):
                    import time as _time

                    obj = cur.obj
                    if isinstance(obj, dict):
                        obj = dict(obj)
                        if "metadata" in obj:
                            obj["metadata"] = dict(obj["metadata"] or {})
                            obj["metadata"]["deletionTimestamp"] = _time.time()
                        obj["deletionTimestamp"] = _time.time()
                    else:
                        import dataclasses as _dc

                        obj = _dc.replace(
                            obj, metadata=_dc.replace(
                                obj.metadata,
                                deletion_timestamp=_time.time()))
                    self._rv += 1
                    self._store[kind][key] = _Stored(obj, self._rv)
                    self._notify(MODIFIED, kind, obj, rv=self._rv)
                return
            self._store[kind].pop(key, None)
            self._rv += 1
            self._notify(DELETED, kind, cur.obj, rv=self._rv)

    def apply_event(self, event: str, kind: str, obj,
                    rv: Optional[int] = None) -> None:
        """Reflector ingestion: upsert/delete mirroring a REMOTE store.

        Unlike create/update, an explicit ``rv`` (the remote's
        resourceVersion, carried on the watch stream) is preserved so a
        client doing get_with_rv on the mirror and PUTting expect_rv back
        to the remote round-trips the REMOTE's CAS — the mirror's own
        counter would be meaningless there."""
        with self._lock:
            key = self._key(kind, obj)
            if event == DELETED:
                cur = self._store[kind].pop(key, None)
                if cur is not None:
                    self._rv += 1
                    self._notify(DELETED, kind, cur.obj, rv=self._rv)
                return
            existed = key in self._store[kind]
            if rv is None:
                self._rv += 1
                rv = self._rv
            else:
                self._rv = max(self._rv, rv)
            self._store[kind][key] = _Stored(obj, rv)
            self._notify(MODIFIED if existed else ADDED, kind, obj, rv=rv)

    def get(self, kind: str, namespace: str, name: str):
        with self._lock:
            key = (namespace if kind != "nodes" else "", name)
            s = self._store[kind].get(key)
            return s.obj if s else None

    def get_with_rv(self, kind: str, namespace: str, name: str):
        """(obj, rv) pair for compare-and-swap callers (leader election)."""
        with self._lock:
            key = (namespace if kind != "nodes" else "", name)
            s = self._store[kind].get(key)
            return (s.obj, s.rv) if s else (None, 0)

    def list(self, kind: str) -> List[object]:
        with self._lock:
            return [s.obj for s in self._store[kind].values()]

    # ------------------------------------------------------------- helpers

    def add_node(self, node: Node) -> None:
        self.create("nodes", node)

    def add_pod(self, pod: Pod) -> None:
        self.create("pods", pod)

    def add_service(self, namespace: str, name: str, selector: Dict[str, str]) -> None:
        self.create(
            "services", {"namespace": namespace, "name": name, "selector": selector}
        )

    def unbind(self, pod: Pod) -> bool:
        """Clear spec.nodeName (gang-rollback inverse of bind; the reference
        has no unbind verb — coscheduling plugins DELETE and recreate, but a
        store-level clear keeps the pod's identity/queue position)."""
        import dataclasses

        with self._lock:
            cur = self.get("pods", pod.namespace, pod.name)
            if cur is None or not cur.spec.node_name:
                return False
            self.update(
                "pods",
                dataclasses.replace(
                    cur, spec=dataclasses.replace(cur.spec, node_name="")
                ),
            )
            return True

    def displace_pod(self, pod: Pod, reason: str) -> bool:
        """Revoke a pod's binding for a cluster-lifecycle event: clear
        spec.nodeName AND stamp the displaced-by annotation, one store
        write (ISSUE 18).  Unlike delete, the pod keeps its identity —
        wire_scheduler's MODIFIED unassigned branch sees the annotation
        and re-admits it through the shed-exempt displaced requeue path,
        so a mass eviction is a mass reschedule, never pod loss.
        Returns False when the pod is gone or already unbound."""
        import dataclasses

        with self._lock:
            cur = self.get("pods", pod.namespace, pod.name)
            if cur is None or not cur.spec.node_name:
                return False
            self.update(
                "pods",
                dataclasses.replace(
                    cur,
                    metadata=dataclasses.replace(
                        cur.metadata,
                        annotations={
                            **cur.metadata.annotations,
                            DISPLACED_BY_ANNOTATION: reason,
                        },
                    ),
                    spec=dataclasses.replace(cur.spec, node_name=""),
                ),
            )
            return True

    def bind(self, pod: Pod, node_name: str, trace_id: str = "") -> bool:
        """The Binding-subresource analog (registry sets spec.nodeName,
        SURVEY section 3.3): CAS on the stored pod.  A non-empty trace_id
        (the scheduling cycle's, from the bind request's traceparent
        header or the in-process trace context) is stamped onto the bound
        pod as an annotation — the join key that makes one scheduling
        decision traceable from cycle span to stored object."""
        import dataclasses

        with self._lock:
            cur = self.get("pods", pod.namespace, pod.name)
            if cur is None:
                return False
            if cur.spec.node_name:
                return False  # already bound
            meta = cur.metadata
            if trace_id:
                meta = dataclasses.replace(
                    meta,
                    annotations={
                        **meta.annotations,
                        "kubernetes-tpu.io/trace-id": trace_id,
                    },
                )
            bound = dataclasses.replace(
                cur, metadata=meta,
                spec=dataclasses.replace(cur.spec, node_name=node_name),
            )
            self.update("pods", bound)
            return True


def wire_scheduler_defaults(cluster: LocalCluster, scheduler) -> None:
    """The non-event half of AddAllEventHandlers wiring: point the
    scheduler's defaulted collaborators (recorder, PDB lister, unbinder,
    victim deleter) at the store.  Shared by the direct-watch wiring
    below and the informer-based wiring (client/informer.py)."""
    if getattr(scheduler, "_recorder_defaulted", False):
        scheduler.recorder = cluster.events
    if getattr(scheduler, "_pdb_defaulted", False):
        # PDB-aware preemption reads live budgets from the store
        # (the disruption controller maintains disruptionsAllowed)
        scheduler.pdb_lister = lambda: cluster.list("poddisruptionbudgets")
    if getattr(scheduler, "unbinder", None) is None:
        # gang all-or-nothing rollback undoes real binds through the store
        scheduler.unbinder = lambda pod: cluster.unbind(pod)
    if getattr(scheduler, "_victim_deleter_defaulted", False):
        # preemption victims must leave the STORE (the DELETE the reference
        # POSTs, scheduler.go:319-326) so controllers replace them and PDB
        # budgets are debited; the cache-only default is for storeless use
        scheduler.victim_deleter = (
            lambda v: cluster.delete("pods", v.namespace, v.name)
        )


def wire_scheduler(cluster: LocalCluster, scheduler) -> None:
    """AddAllEventHandlers analog (pkg/scheduler/eventhandlers.go:319-378):
    route store events into the scheduler's cache and queue; the scheduler's
    event recorder becomes the cluster's (one audit trail)."""
    cache = scheduler.cache
    queue = scheduler.queue
    wire_scheduler_defaults(cluster, scheduler)
    # responsibleForPod: only pods naming THIS scheduler enter its
    # queue; assigned pods feed the cache regardless (everyone's
    # placements consume resources)
    from kubernetes_tpu.runtime.scheduler import responsible_for

    def responsible(pod) -> bool:
        return responsible_for(pod, scheduler)

    def on_event(event: str, kind: str, obj) -> None:
        if kind == "nodes":
            if event == ADDED:
                cache.add_node(obj)
            elif event == MODIFIED:
                cache.update_node(obj)
            else:
                cache.remove_node(obj.name)
            # node changes can make unschedulable pods feasible
            queue.move_all_to_active()
        elif kind == "pods":
            # the reference's pod informer uses the non-terminated field
            # selector (status.phase != Succeeded/Failed): completed pods
            # leave the scheduler's world and release their resources
            if obj.status.phase in ("Succeeded", "Failed"):
                if event != DELETED:
                    cache.remove_pod(obj)
                    queue.delete(obj)
                    queue.move_all_to_active()
                return
            assigned = bool(obj.spec.node_name)
            if event == ADDED:
                if assigned:
                    cache.add_pod(obj)
                    queue.move_all_to_active()
                elif responsible(obj):
                    queue.add(obj)
            elif event == MODIFIED:
                if assigned:
                    # another scheduler (or this one) bound it: confirm in
                    # the cache AND drop it from the queue — otherwise a
                    # second scheduler sharing the store double-binds
                    # (eventhandlers.go moves pods between the unscheduled
                    # and scheduled informers on assignment)
                    cache.add_pod(obj)
                    queue.delete(obj)
                else:
                    # assigned -> unassigned (gang-rollback unbind) must
                    # DECHARGE the cache — confirm-on-bind popped the pod
                    # from the assumed map, so forget_pod alone is a no-op;
                    # remove_pod tolerates pods the cache never held
                    cache.remove_pod(obj)
                    # spec update while pending: re-queue the fresh copy
                    queue.delete(obj)
                    if responsible(obj):
                        reason = obj.metadata.annotations.get(
                            DISPLACED_BY_ANNOTATION
                        )
                        if reason and hasattr(queue, "readd_displaced"):
                            # lifecycle displacement (ISSUE 18): close the
                            # checker's bound mark FIRST (the pod is not a
                            # popped-and-unresolved entry, it was running),
                            # then re-admit shed-exempt + shed-protected
                            inv = getattr(scheduler, "invariants", None)
                            if inv is not None:
                                inv.note_displaced(obj)
                            from kubernetes_tpu.utils import metrics as _m

                            _m.PODS_DISPLACED.inc(reason=reason)
                            queue.readd_displaced(obj)
                            # the freed node capacity may revive parked
                            # unschedulable pods, same as a delete would
                            queue.move_all_to_active()
                        else:
                            queue.add(obj)
            else:
                if assigned:
                    cache.remove_pod(obj)
                    queue.move_all_to_active()
                else:
                    queue.delete(obj)
        elif kind == "services":
            if event == ADDED:
                cache.encoder.add_spread_selector(
                    obj["namespace"], obj["selector"]
                )
                queue.move_all_to_active()
        elif kind == "persistentvolumes":
            if event == DELETED:
                cache.encoder.remove_pv(obj.name)
            else:
                cache.encoder.add_pv(obj)
            queue.move_all_to_active()  # PV events unblock volume-bound pods
        elif kind == "persistentvolumeclaims":
            if event == DELETED:
                cache.encoder.remove_pvc(obj.namespace, obj.name)
            else:
                cache.encoder.add_pvc(obj)
            queue.move_all_to_active()
        elif kind == "storageclasses":
            if event == DELETED:
                # a dead provisioner must stop admitting WFFC pods through
                # CheckVolumeBinding's dynamic-provisioning branch
                cache.encoder.remove_storage_class(obj.name)
            else:
                cache.encoder.add_storage_class(obj)
                queue.move_all_to_active()

    cluster.watch(on_event)


def make_cluster_binder(cluster: LocalCluster):
    """Binder callback for Scheduler: POST .../binding analog.  Carries
    the calling thread's trace context (the scheduler sets it around the
    commit tail) so embedded single-process planes stamp the same
    trace-id annotation the HTTP Binding path does."""
    from kubernetes_tpu.utils.trace import current_trace_id

    def binder(pod: Pod, node_name: str) -> bool:
        return cluster.bind(pod, node_name, trace_id=current_trace_id())

    return binder
