"""Certificates: the TLS-bootstrap flow distilled to its auth outcome.

Reference: the kubelet TLS bootstrap — a machine holding only a
bootstrap token submits a CertificateSigningRequest for the identity
``system:node:<name>`` (certificates.k8s.io/v1beta1); the
kube-controller-manager's csrapproving controller auto-approves
node-client CSRs from bootstrap identities
(pkg/controller/certificates/approver/sarapprove.go) and the csrsigning
controller signs them (pkg/controller/certificates/signer/signer.go),
returning the credential in ``status.certificate``; the kubelet then
drops the bootstrap token and authenticates as its node identity, which
RBAC (system:nodes) and NodeRestriction scope per-object.

Two credential forms, matching the server's two authn paths:

  * bearer mode (default): the "signed certificate" is a minted node
    auth-token Secret (``kubernetes-tpu/auth-token`` with user
    ``system:node:<name>``, the form TokenAuthenticator resolves); the
    token rides ``status.certificate`` where the reference puts the PEM;
  * PKI mode (signer constructed with a ``CertificateAuthority``, the
    TLS serving stack of utils/pki.py): a CSR whose ``spec.request``
    carries a REAL PEM CSR gets a REAL signed client certificate in
    ``status.certificate`` (signer.go), subject policy enforced by the
    approver: CN must be the requested node identity, O must be
    system:nodes.  The apiserver's x509 authn then accepts the cert
    directly.
"""

from __future__ import annotations

import secrets as _secrets

from kubernetes_tpu.runtime.cluster import DELETED, ConflictError, LocalCluster
from kubernetes_tpu.runtime.controllers import Reconciler

NODE_CLIENT_SIGNER = "kubernetes.io/kube-apiserver-client-kubelet"


class CSRApproverSigner(Reconciler):
    """csrapproving + csrsigning collapsed into one reconciler: approve
    node-client CSRs from bootstrap/admin identities, mint the node
    credential, surface it in status.certificate."""

    WATCH_KINDS = ("certificatesigningrequests",)

    def __init__(self, cluster: LocalCluster, ca=None, informers=None):
        #: utils.pki.CertificateAuthority for PKI mode, or None (bearer)
        self.ca = ca
        super().__init__(cluster, informers=informers)

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "certificatesigningrequests" and event != DELETED:
            self.queue.add(obj.get("name", ""))

    @staticmethod
    def _requested_node(csr: dict) -> str:
        """The node identity a CSR requests (spec.username in the
        reference's x509 CN form system:node:<name>)."""
        username = (csr.get("spec") or {}).get("username", "")
        if username.startswith("system:node:"):
            return username[len("system:node:"):]
        return ""

    def sync(self, name: str) -> None:
        csr = self.cluster.get("certificatesigningrequests", "", name)
        if csr is None:
            return
        status = csr.get("status") or {}
        conds = {c.get("type") for c in status.get("conditions") or []}
        if status.get("certificate") or "Denied" in conds:
            return  # terminal: signed or denied (re-writing the same
            # denial would re-trigger this controller forever)
        spec = csr.get("spec") or {}
        node = self._requested_node(csr)
        requestor = spec.get("requestorUsername", "")
        groups = spec.get("requestorGroups") or []
        # approval policy (sarapprove.go): the node-client signer NAMED
        # EXPLICITLY (signerName is required in the reference; a
        # default-allow here would sign unrelated signers' CSRs), a node
        # identity requested, and a requestor entitled to bootstrap —
        # system:bootstrappers (kubeadm join) or system:masters
        ok = (
            spec.get("signerName", "") == NODE_CLIENT_SIGNER
            and node
            and ("system:bootstrappers" in groups
                 or "system:masters" in groups
                 or requestor.startswith("system:bootstrap:"))
        )
        out = dict(csr)
        if not ok:
            out["status"] = {**status, "conditions": [
                {"type": "Denied",
                 "reason": "SignerValidationFailure",
                 "message": "not a node-client CSR from a bootstrap "
                            "identity"},
            ]}
            self.cluster.update("certificatesigningrequests", out)
            return
        if self.ca is not None and spec.get("request"):
            # PKI mode: sign the real CSR (signer.go), with the approver's
            # subject policy — the CSR may only claim the node identity it
            # requested (CN) and the nodes group (O); anything else is a
            # privilege escalation and is Denied
            from cryptography import x509 as _x509
            from cryptography.x509.oid import NameOID as _NameOID

            csr_pem = spec["request"].encode()
            try:
                req = _x509.load_pem_x509_csr(csr_pem)
                cn = next((str(a.value) for a in req.subject
                           if a.oid == _NameOID.COMMON_NAME), "")
                orgs = [str(a.value) for a in req.subject
                        if a.oid == _NameOID.ORGANIZATION_NAME]
                if cn != f"system:node:{node}" or orgs != ["system:nodes"]:
                    raise ValueError(
                        f"subject CN={cn!r} O={orgs!r} does not match the "
                        f"requested node identity")
                cert_pem = self.ca.sign_csr(csr_pem, client=True)
            except Exception as e:
                out["status"] = {**status, "conditions": [
                    {"type": "Denied",
                     "reason": "SubjectValidationFailure",
                     "message": str(e)[:300]},
                ]}
                self.cluster.update("certificatesigningrequests", out)
                return
            out["status"] = {
                "conditions": [{"type": "Approved",
                                "reason": "AutoApproved",
                                "message": "node client cert approved"}],
                "certificate": cert_pem.decode(),
            }
            self.cluster.update("certificatesigningrequests", out)
            self.cluster.events.eventf(
                "CertificateSigningRequest", "", name, "Normal", "Issued",
                "node client certificate issued for system:node:%s", node,
            )
            return
        # bearer mode: mint a FRESH node credential, ROTATING any existing
        # one.  Never reuse-and-return the stored token: that would hand a
        # joined node's LIVE credential to any bootstrap-token holder who
        # asks (in the reference a re-sign issues a new cert and cannot
        # disclose the old key).  Rotation kicks a stale holder off; the
        # legitimate node re-CSRs on its next join.
        secret_name = f"node-token-{node}"
        token = _secrets.token_hex(16)
        secret = {
            "namespace": "kube-system", "name": secret_name,
            "kind": "Secret", "apiVersion": "v1",
            "type": "kubernetes-tpu/auth-token",
            "data": {"token": token,
                     "user": f"system:node:{node}",
                     "groups": ["system:nodes"]},
        }
        try:
            self.cluster.create("secrets", secret)
        except ConflictError:
            self.cluster.update("secrets", secret)
        out["status"] = {
            "conditions": [{"type": "Approved",
                            "reason": "AutoApproved",
                            "message": "node client cert approved"}],
            # the credential rides where the reference puts the PEM
            "certificate": token,
        }
        self.cluster.update("certificatesigningrequests", out)
        self.cluster.events.eventf(
            "CertificateSigningRequest", "", name, "Normal", "Issued",
            "node credential issued for system:node:%s", node,
        )
