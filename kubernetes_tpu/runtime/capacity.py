"""Device-resident capacity planner (ISSUE 15): class-compressed what-if
binpack of the live backlog over a candidate node-shape catalog.

BASELINE's fifth config — "cluster-autoscaler what-if binpack: 50k
pending pods x 10k candidate node shapes" — asked a question nothing in
the repo answered: *given the live cluster and its pending backlog, what
should the fleet look like?*  This module is the answer end to end:

  * **Snapshot.**  Every `capacityIntervalCycles` committed cycles the
    planner snapshots the cycle's host cluster refs (allocatable /
    requested / valid — immutable by the encoder's cow contract) plus
    the pending+unschedulable backlog's request vectors (one bounded
    read-only queue walk), QUANTIZES both to per-resource power-of-two
    quanta so every value is an exact integer below 2**24 (the
    models/binpack.py count-kernel exactness contract; requests round
    UP, capacities round DOWN — the conservative direction), and
    CLASS-COMPRESSES the backlog: real backlogs are controller-stamped,
    so 50k request vectors collapse into a few hundred distinct
    (vector -> count) classes.

  * **Two-stage solve, one amortized side-launch.**  Stage 1 packs the
    compressed backlog into the EXISTING headroom (per-node free rows
    as per-bin capacities — models/binpack.binpack_ffd_counts); only
    the overflow goes to stage 2, the class-compressed what-if sweep
    over the shape catalog (binpack_shapes_compressed — C scan steps
    instead of P, the ISSUE 15 speedup).  Both stages dispatch
    back-to-back as ONE chained async side-launch behind the scheduling
    loop and materialize one interval later — the TelemetryHub
    amortization, so a scheduling cycle never blocks on the solve.
    With a device mesh the shape axis shards exactly like
    models/binpack.what_if_sharded (padded zero-capacity lanes report
    ok=False and are filtered).

  * **Recommendation.**  "add 37 x shape-C nodes" (the cheapest shape
    that fits the whole overflow, runners-up included), or — when the
    headroom already absorbs everything — "nodes n12,n47 drainable"
    (valid, pod-free nodes stage 1 left untouched).  Served at
    GET /debug/capacity on both servers, exported as the
    scheduler_capacity_* metric families, and banked by
    bench.py --autoscale.

Placements are bit-identical with the planner on or off (it only READS
immutable snapshot refs and the queue's backlog — pinned by
tests/test_capacity.py), and the hook's scheduling-thread cost is
stamped into scheduler_capacity_seconds_total (the <2%-of-cycle budget
perf_smoke pins, the telemetry/quality discipline).  `CAPACITY` /
`get_default` / `set_default` follow the flightrecorder RECORDER
pattern.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.codec.schema import (
    RES_EPHEMERAL,
    RES_MEMORY,
    RES_MILLICPU,
    RES_PODS,
)
from kubernetes_tpu.models.binpack import INT_EXACT_LIMIT, compress_classes
from kubernetes_tpu.utils import metrics as m

# a small general-purpose default catalog (GCE-flavored names) so
# enabling the planner without a nodeShapeCatalog still recommends
# something sensible; production deployments pass their own
DEFAULT_SHAPE_CATALOG: Tuple[dict, ...] = (
    {"name": "c2-standard-8", "cpu": "8", "memory": "32Gi"},
    {"name": "c2-standard-16", "cpu": "16", "memory": "64Gi"},
    {"name": "c2-standard-30", "cpu": "30", "memory": "120Gi"},
    {"name": "m1-highmem-16", "cpu": "16", "memory": "128Gi"},
)

# catalog entry keys that are NOT resource quantities
_META_KEYS = frozenset({"name", "pods"})

# default allocatable-pods slots per catalog node (the kubelet default)
DEFAULT_SHAPE_PODS = 110.0


def catalog_vectors(
    catalog,
    r: int,
    res_col: Optional[Callable[[str], Optional[int]]] = None,
) -> Tuple[List[str], np.ndarray]:
    """Shape-catalog entries ({name, cpu, memory, ephemeral-storage?,
    pods?, <extended>...}) -> (names, capacities f32[S, r]) in the
    snapshot encoder's resource-column units (cpu in milli, bytes for
    memory/ephemeral).  `res_col` maps extended resource names to
    columns READ-ONLY (unknown names are skipped — a shape advertising
    a resource no pod ever requested cannot matter to the pack)."""
    from kubernetes_tpu.api.resource import parse_quantity

    names: List[str] = []
    caps = np.zeros((len(catalog), r), np.float32)
    for i, entry in enumerate(catalog):
        names.append(str(entry.get("name", f"shape-{i}")))
        caps[i, RES_PODS] = float(entry.get("pods", DEFAULT_SHAPE_PODS))
        for key, val in entry.items():
            if key in _META_KEYS:
                continue
            if key == "cpu":
                caps[i, RES_MILLICPU] = float(parse_quantity(val).milli)
            elif key == "memory":
                caps[i, RES_MEMORY] = float(parse_quantity(val))
            elif key == "ephemeral-storage":
                caps[i, RES_EPHEMERAL] = float(parse_quantity(val))
            else:
                col = res_col(key) if res_col is not None else None
                if col is not None and 0 <= col < r:
                    caps[i, col] = float(parse_quantity(val))
    return names, caps


def quantize_columns(*arrays) -> np.ndarray:
    """Per-resource power-of-two quanta making every value in `arrays`
    fit the count kernel's integer-exactness contract (< 2**24 after
    division).  Power-of-two quanta divide exactly in binary floats, so
    quantization introduces no rounding beyond the ceil/floor the
    caller chooses."""
    r = arrays[0].shape[-1]
    maxv = np.zeros(r, np.float64)
    for a in arrays:
        if a.size:
            maxv = np.maximum(maxv, a.reshape(-1, r).max(axis=0))
    quanta = np.ones(r, np.float64)
    over = maxv >= INT_EXACT_LIMIT
    if over.any():
        quanta[over] = 2.0 ** np.ceil(
            np.log2(maxv[over] / (INT_EXACT_LIMIT - 1.0))
        )
    return quanta


_STAGE1 = None


def _stage1_kernel():
    """ONE jitted stage-1 (pack into existing headroom) kernel for the
    process, re-traced per (N, C) shape like every engine executable:
    order classes by the shared FFD key against the fleet's largest
    free shape, count-pack into the per-node free rows, and return the
    class-indexed leftovers + which nodes the pack touched."""
    global _STAGE1
    if _STAGE1 is None:
        import jax
        import jax.numpy as jnp

        from kubernetes_tpu.models.binpack import (
            binpack_ffd_counts,
            ffd_order,
        )

        def stage1(free, classes, counts):
            ref = jnp.maximum(jnp.max(free, axis=0), 1.0)
            order = ffd_order(classes, ref)
            _, loads, placed_c = binpack_ffd_counts(
                classes, counts, free, max_bins=free.shape[0], order=order
            )
            placed = jnp.zeros_like(counts).at[order].set(placed_c)
            real = jnp.any(classes > 0, axis=-1)
            leftover = jnp.where(real, counts - placed, 0)
            touched = jnp.any(loads > 0, axis=-1)
            return leftover, jnp.sum(jnp.where(real, placed, 0)), touched

        _STAGE1 = jax.jit(stage1)
    return _STAGE1


class CapacityPlanner:
    """Per-scheduler capacity-planning aggregation point.

    The scheduling thread calls `on_cycle` once per committed cycle
    (runtime/scheduler.py stamps the call's cost into
    scheduler_capacity_seconds_total); readers (/debug/capacity, bench)
    come from other threads and take the lock only around ring/summary
    state.  The backlog and snapshot are read lazily — only on a due
    interval cycle — so off-interval cycles cost two integer bumps."""

    def __init__(
        self,
        catalog=None,
        interval_cycles: int = 256,
        ring_capacity: int = 128,
        max_bins: int = 1024,
        backlog_cap: int = 65536,
        mesh=None,  # a Mesh, or a zero-arg callable returning the CURRENT
        #             mesh (the elastic ladder rebuilds at runtime; a
        #             getter keeps the shape axis sharding over whatever
        #             mesh is serving cycles right now)
        clock: Callable[[], float] = time.monotonic,
    ):
        self.catalog = list(catalog) if catalog else list(
            DEFAULT_SHAPE_CATALOG
        )
        self.interval_cycles = max(1, int(interval_cycles))
        self.max_bins = max(1, int(max_bins))
        self.backlog_cap = max(1, int(backlog_cap))
        self.mesh = mesh
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(ring_capacity)))
        self.cycles_total = 0
        self.solves_total = 0
        self._cycles_since = self.interval_cycles  # first cycle is due
        # in-flight solve: (cycle, device outs tuple, meta dict) —
        # dispatched on one due cycle, materialized on the next (the
        # telemetry hub's amortization pattern)
        self._pending: Optional[Tuple[int, tuple, dict]] = None
        self.recommendation: Optional[dict] = None
        # the shape whose recommended-nodes gauge child is currently
        # exported: cleared before the next solve's winner lands, so
        # /metrics never shows two "winning" shapes at once (or a
        # stale one after the overflow drains)
        self._reco_shape: Optional[str] = None
        # shape vectors are rebuilt when the snapshot's R width moves
        # (extended-resource growth) — keyed on (r, id-ish catalog len)
        self._caps_cache: Dict[int, Tuple[List[str], np.ndarray]] = {}

    # ------------------------------------------------------ hot-path API

    def on_cycle(
        self,
        cycle: int,
        backlog: Callable[[int], np.ndarray],
        snapshot: Optional[tuple],
        node_names: Optional[Callable[[], Dict[int, str]]] = None,
        res_col: Optional[Callable[[str], Optional[int]]] = None,
    ) -> None:
        """Fold one committed cycle: amortized materialize-then-dispatch.

        `backlog` is a CALLABLE returning the pending+unschedulable
        request matrix f32[P, R] — or the pre-grouped form
        (vectors f32[G, R], counts i[G]), which skips materializing a
        per-pod matrix entirely (the scheduler's walk already groups
        by request content) — invoked only on due cycles: the queue
        walk must not run 256x more often than the solve;
        `snapshot` the cycle's host (allocatable, requested, valid)
        refs; `node_names` resolves node rows to names for the
        drainable report; `res_col` the encoder's read-only extended-
        resource column lookup for catalog vectors.  The cadence
        counter resets only on an actual dispatch, so a due cycle that
        cannot sample (no snapshot yet) leaves the interval due."""
        self.cycles_total += 1
        self._cycles_since += 1
        if self._cycles_since < self.interval_cycles:
            return
        self._materialize_pending()
        if snapshot is None:
            return
        try:
            reqs = backlog(self.backlog_cap)
        except Exception:  # noqa: BLE001 — a failed backlog walk costs
            # one sample, never the cycle (the telemetry discipline)
            return
        if self._dispatch(cycle, reqs, snapshot, node_names, res_col):
            self._cycles_since = 0

    # ------------------------------------------------------ solve launch

    def _shape_caps(self, r: int, res_col) -> Tuple[List[str], np.ndarray]:
        hit = self._caps_cache.get(r)
        if hit is None:
            hit = catalog_vectors(self.catalog, r, res_col=res_col)
            self._caps_cache[r] = hit
        return hit

    def _dispatch(self, cycle: int, reqs, snapshot, node_names,
                  res_col) -> bool:
        """Quantize + compress + launch the two-stage solve; the result
        materializes one interval from now.  Returns whether a launch
        actually dispatched."""
        import jax

        alloc, used, valid = (np.asarray(x) for x in snapshot)
        # the backlog arrives per-pod ([P, R]) or pre-grouped
        # ((vectors [G, R], counts [G])); normalize to rows + weights
        if isinstance(reqs, tuple):
            reqs, req_counts = reqs
            req_counts = np.asarray(req_counts, np.int64)
        else:
            req_counts = None
        reqs = np.asarray(reqs, np.float32)
        if reqs.ndim != 2 or reqs.shape[1] != alloc.shape[1]:
            reqs = np.zeros((0, alloc.shape[1]), np.float32)
            req_counts = None
        names, caps = self._shape_caps(alloc.shape[1], res_col)
        if not len(names):
            return False
        free = np.where(
            valid[:, None],
            np.maximum(alloc.astype(np.float64) - used.astype(np.float64),
                       0.0),
            0.0,
        )
        # per-resource power-of-two quanta -> exact-integer arithmetic
        # in the count kernel (requests ceil, capacities floor: the
        # conservative direction — a recommendation may buy one node
        # too many, never one too few)
        quanta = quantize_columns(free, caps.astype(np.float64),
                                  reqs.astype(np.float64))
        free_q = np.floor(free / quanta).astype(np.float32)
        caps_q = np.floor(caps.astype(np.float64) / quanta).astype(
            np.float32
        )
        reqs_q = np.ceil(reqs.astype(np.float64) / quanta).astype(
            np.float32
        )
        classes, counts = compress_classes(
            reqs_q, pad_to_pow2=True, weights=req_counts
        )
        backlog_pods = int(counts.sum())
        n_classes = int(np.sum(np.any(classes > 0, axis=-1)))
        meta = {
            "backlog_pods": backlog_pods,
            "classes": max(n_classes, 1 if backlog_pods else 0),
            "shapes": len(names),
            "shape_names": names,
            "quanta": [float(q) for q in quanta],
            "node_names": node_names,
            "valid": valid,
            "pod_free": used[:, RES_PODS] <= 0,
        }
        try:
            from kubernetes_tpu.models.binpack import (
                binpack_shapes_compressed,
            )

            mesh = self.mesh() if callable(self.mesh) else self.mesh
            if mesh is not None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                axis = mesh.axis_names[0]
                n_dev = mesh.devices.size
                s = caps_q.shape[0]
                pad = (-s) % n_dev
                shp = np.zeros((s + pad, caps_q.shape[1]), np.float32)
                shp[:s] = caps_q
                repl = NamedSharding(mesh, P(None, None))
                with mesh:
                    free_d = jax.device_put(
                        free_q.astype(np.float32), repl
                    )
                    cls_d = jax.device_put(classes, repl)
                    cnt_d = jax.device_put(
                        counts, NamedSharding(mesh, P(None))
                    )
                    leftover, absorbed, touched = _stage1_kernel()(
                        free_d, cls_d, cnt_d
                    )
                    bins, ok = binpack_shapes_compressed(
                        cls_d, leftover,
                        jax.device_put(
                            shp, NamedSharding(mesh, P(axis, None))
                        ),
                        max_bins=self.max_bins,
                    )
                meta["padded_shapes"] = int(pad)
            else:
                leftover, absorbed, touched = _stage1_kernel()(
                    free_q.astype(np.float32), classes, counts
                )
                bins, ok = binpack_shapes_compressed(
                    classes, leftover, caps_q, max_bins=self.max_bins
                )
        except Exception:  # noqa: BLE001 — a faulted side launch costs
            # one sample, never the cycle (the telemetry discipline)
            return False
        with self._lock:  # /debug readers race the swap
            self._pending = (
                cycle, (leftover, absorbed, touched, bins, ok), meta,
            )
        return True

    # ------------------------------------------------------ materialize

    def _materialize_pending(self) -> Optional[dict]:
        with self._lock:  # one consumer wins (scheduling thread vs
            # HTTP readers via debug_payload/finalize)
            pending, self._pending = self._pending, None
        if pending is None:
            return None
        cycle, outs, meta = pending
        try:
            leftover, absorbed, touched, bins, ok = (
                np.asarray(x) for x in outs
            )
        except Exception:  # noqa: BLE001 — one lost sample, not a cycle
            return None
        names: List[str] = meta["shape_names"]
        s = len(names)
        bins, ok = bins[:s], ok[:s]
        overflow = int(leftover.sum())
        fits = np.flatnonzero(ok & (bins > 0)) if overflow else (
            np.empty(0, np.int64)
        )
        scale_up = None
        runners_up: List[dict] = []
        if overflow and len(fits):
            ranked = fits[np.argsort(bins[fits], kind="stable")]
            best = int(ranked[0])
            scale_up = {
                "shape": names[best],
                "count": int(bins[best]),
                "shape_index": best,
            }
            runners_up = [
                {"shape": names[int(i)], "count": int(bins[int(i)])}
                for i in ranked[1:4]
            ]
        # drainable: valid, pod-free nodes the headroom pack left
        # untouched — removable without moving anything
        drain_rows = np.flatnonzero(
            meta["valid"] & meta["pod_free"] & ~touched[: len(meta["valid"])]
        )
        drain_names: List[str] = []
        resolve = meta.get("node_names")
        if resolve is not None and len(drain_rows):
            try:
                by_row = resolve()
                drain_names = [
                    by_row[int(r)] for r in drain_rows[:16]
                    if int(r) in by_row
                ]
            except Exception:  # noqa: BLE001 — names are advisory
                drain_names = []
        backlog_pods = meta["backlog_pods"]
        n_classes = meta["classes"]
        sample = {
            "time": time.time(),
            "cycle": int(cycle),
            "backlog_pods": backlog_pods,
            "classes": n_classes,
            "compression_x": round(backlog_pods / max(n_classes, 1), 1),
            "absorbed_existing": int(absorbed),
            "overflow_pods": overflow,
            "shapes_evaluated": s,
            "shapes_fitting": int(len(fits)),
            "scale_up": scale_up,
            "runners_up": runners_up,
            "drainable": {
                "count": int(len(drain_rows)),
                "nodes": drain_names,
            },
            "quanta": meta["quanta"],
        }
        with self._lock:
            self.recommendation = sample
            self._ring.append(sample)
            self.solves_total += 1
        m.CAPACITY_SOLVES.inc()
        m.CAPACITY_BACKLOG.set(float(backlog_pods), kind="pods")
        m.CAPACITY_BACKLOG.set(float(n_classes), kind="classes")
        m.CAPACITY_OVERFLOW.set(float(overflow))
        m.CAPACITY_ABSORBED.set(float(absorbed))
        m.CAPACITY_DRAINABLE.set(float(len(drain_rows)))
        new_shape = scale_up["shape"] if scale_up is not None else None
        if self._reco_shape is not None and self._reco_shape != new_shape:
            m.CAPACITY_RECOMMENDED.remove(shape=self._reco_shape)
        if scale_up is not None:
            m.CAPACITY_RECOMMENDED.set(
                float(scale_up["count"]), shape=new_shape
            )
        self._reco_shape = new_shape
        return sample

    def finalize(self) -> None:
        """Materialize any in-flight solve (bench/test exit — the
        amortization would otherwise leave the last sample in flight
        forever on a drained queue)."""
        self._materialize_pending()

    # ----------------------------------------------------------- readers

    def summary(self) -> dict:
        with self._lock:
            reco = dict(self.recommendation) if self.recommendation else None
            return {
                "cycles": self.cycles_total,
                "solves": self.solves_total,
                "interval_cycles": self.interval_cycles,
                "catalog_shapes": len(self.catalog),
                "max_bins": self.max_bins,
                "backlog_cap": self.backlog_cap,
                "sharded": (
                    (self.mesh() if callable(self.mesh) else self.mesh)
                    is not None
                ),
                "recommendation": reco,
            }

    def debug_payload(self, limit: Optional[int] = None) -> dict:
        """GET /debug/capacity body: summary + the newest `limit` solve
        samples (the shared debug_body halves the limit until the body
        fits the 4MB cap, like its siblings)."""
        self._materialize_pending()
        with self._lock:
            samples = list(self._ring)
        if limit is not None and limit >= 0:
            samples = samples[-limit:] if limit else []
        return {"summary": self.summary(), "samples": samples}


# process-wide default: the planner /debug/capacity serves when none
# was wired explicitly; a Scheduler with capacity_planner enabled
# installs its own here.  Replica 0 wins the default, siblings register
# alongside (runtime/defaults.py ProcessDefault)
from kubernetes_tpu.runtime.defaults import ProcessDefault  # noqa: E402

_DEFAULT = ProcessDefault("capacity", CapacityPlanner)


def get_default() -> CapacityPlanner:
    return _DEFAULT.get()


def set_default(planner: CapacityPlanner, replica: int = 0) -> None:
    _DEFAULT.set(planner, replica)


def replica_instances() -> dict:
    """{replica id: CapacityPlanner} of every install this process saw."""
    return _DEFAULT.replicas()


def __getattr__(name):  # legacy alias: capacity.CAPACITY
    if name == "CAPACITY":
        return _DEFAULT.get()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
