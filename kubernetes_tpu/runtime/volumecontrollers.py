"""Volume + serviceaccount controllers (the server-side reconcilers).

Reference:
  * pkg/controller/volume/persistentvolume/pv_controller.go (+
    pv_controller_base.go, index.go findBestMatchForClaim): claim<->volume
    binding — syncUnboundClaim matches an Available PV by capacity /
    access modes / storage class (smallest-that-fits), sets
    pv.spec.claimRef + both phases Bound; syncVolume releases PVs whose
    claim vanished and applies the reclaim policy (Retain -> Released,
    Delete -> delete the PV); dynamic provisioning creates a PV for
    claims whose class names a provisioner (WaitForFirstConsumer waits
    for the scheduler's node pick, read from the pod that uses the
    claim).
  * pkg/controller/volume/attachdetach/attach_detach_controller.go:
    desired state = pods assigned to nodes x their PV-backed volumes;
    reconciler attaches/detaches, surfacing node.status.volumesAttached.
  * pkg/controller/serviceaccount/serviceaccounts_controller.go: every
    active namespace gets a "default" ServiceAccount.
  * pkg/controller/serviceaccount/tokens_controller.go: every SA gets a
    token Secret (type kubernetes.io/service-account-token) — which this
    framework's TokenAuthenticator then accepts as
    system:serviceaccount:<ns>:<name>.
"""

from __future__ import annotations

import dataclasses
import secrets as _secrets
import threading
from typing import List, Optional, Tuple

from kubernetes_tpu.api.storage import (
    IMMEDIATE,
    WAIT_FOR_FIRST_CONSUMER,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from kubernetes_tpu.api.types import ObjectMeta
from kubernetes_tpu.runtime.cluster import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    LocalCluster,
)
from kubernetes_tpu.runtime.controllers import Reconciler


def _access_modes_satisfied(pv: PersistentVolume,
                            pvc: PersistentVolumeClaim) -> bool:
    """Every requested mode must be offered (CheckAccessModes,
    index.go:290-302)."""
    return set(pvc.access_modes) <= set(pv.access_modes)


class PersistentVolumeController(Reconciler):
    """Claim<->volume binding + reclaim + dynamic provisioning."""

    WATCH_KINDS = ("persistentvolumeclaims", "persistentvolumes", "pods")

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "persistentvolumeclaims":
            self.queue.add(("claim", obj.namespace, obj.name))
        elif kind == "persistentvolumes":
            self.queue.add(("volume", "", obj.name))
        elif kind == "pods" and obj.spec.node_name:
            # a scheduled pod may unblock WaitForFirstConsumer provisioning
            for v in obj.spec.volumes:
                claim = (v.get("persistentVolumeClaim") or {})
                if claim.get("claimName"):
                    self.queue.add(
                        ("claim", obj.namespace, claim["claimName"]))

    # ------------------------------------------------------------- claims

    def _find_best_match(self, pvc: PersistentVolumeClaim,
                         node_name: str = "") -> Optional[PersistentVolume]:
        """Smallest Available PV satisfying class/modes/capacity
        (findBestMatchForClaim); with node_name (the WFFC selected node),
        topology-pinned PVs must admit that node."""
        node = (self.cluster.get("nodes", "", node_name)
                if node_name else None)
        best = None
        for pv in self.cluster.list("persistentvolumes"):
            if pv.phase != "Available" or pv.claim_ref:
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if not _access_modes_satisfied(pv, pvc):
                continue
            if node is not None and pv.node_affinity is not None:
                from kubernetes_tpu.cpuref.reference import (
                    match_node_selector_term,
                )

                if not any(match_node_selector_term(t, node)
                           for t in pv.node_affinity.terms):
                    continue
            if pvc.request is not None:
                if pv.capacity is None or float(pv.capacity) < float(pvc.request):
                    continue
            if best is None or (
                pv.capacity is not None and best.capacity is not None
                and float(pv.capacity) < float(best.capacity)
            ):
                best = pv
        return best

    def _selected_node(self, pvc: PersistentVolumeClaim) -> str:
        """WaitForFirstConsumer: the node the scheduler picked, read from
        a pod that uses this claim (the selected-node annotation analog)."""
        for p in self.cluster.list("pods"):
            if p.namespace != pvc.namespace or not p.spec.node_name:
                continue
            for v in p.spec.volumes:
                if (v.get("persistentVolumeClaim") or {}).get(
                        "claimName") == pvc.name:
                    return p.spec.node_name
        return ""

    def _provision(self, pvc: PersistentVolumeClaim, sc: StorageClass,
                   node_name: str) -> PersistentVolume:
        """Dynamic provisioning: mint a PV sized to the claim; WFFC pins
        it to the selected node via nodeAffinity (provisioned volumes
        reclaim Delete)."""
        from kubernetes_tpu.api.types import (
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
        )

        na = None
        if node_name:
            na = NodeSelector((NodeSelectorTerm((
                NodeSelectorRequirement("kubernetes.io/hostname", "In",
                                        (node_name,)),
            )),))
        return PersistentVolume(
            metadata=ObjectMeta(
                name=f"pvc-{pvc.namespace}-{pvc.name}-"
                     f"{_secrets.token_hex(4)}"),
            capacity=pvc.request,
            access_modes=pvc.access_modes or ("ReadWriteOnce",),
            storage_class=pvc.storage_class,
            node_affinity=na,
            source_kind="csi",
            csi_driver=sc.provisioner,
            source_id=_secrets.token_hex(8),
            reclaim_policy="Delete",
        )

    def _sync_claim(self, ns: str, name: str) -> None:
        pvc = self.cluster.get("persistentvolumeclaims", ns, name)
        if pvc is None:
            # claim deleted: release its PV (syncVolume's release half
            # handles reclaim when the volume event fires)
            for pv in self.cluster.list("persistentvolumes"):
                if pv.claim_ref == f"{ns}/{name}":
                    self.queue.add(("volume", "", pv.name))
            return
        if pvc.volume_name:
            # user-pre-bound claim (spec.volumeName): the PV side must be
            # bound too or the volume stays Available and a second claim
            # can steal it (syncUnboundClaim's volumeName!=nil arm)
            pv = self.cluster.get("persistentvolumes", "", pvc.volume_name)
            if pv is None:
                return  # named volume doesn't exist yet: stays Pending
            ours = f"{pvc.namespace}/{pvc.name}"
            if pv.claim_ref and pv.claim_ref != ours:
                return  # volume belongs to someone else: stays Pending
            self._bind(pv, pvc)
            return
        # pre-bound by PV side? (a PV claiming this PVC).  A Released
        # volume keeps its old claimRef for the admin — a NEW claim with
        # the same ns/name must NOT silently inherit it (and its data);
        # the reference compares claimRef UID for the same reason.
        for pv in self.cluster.list("persistentvolumes"):
            if pv.claim_ref == f"{ns}/{name}" and pv.phase != "Released":
                self._bind(pv, pvc)
                return
        sc = None
        for s in self.cluster.list("storageclasses"):
            if s.name == pvc.storage_class:
                sc = s
                break
        node = ""
        if sc is not None and sc.binding_mode == WAIT_FOR_FIRST_CONSUMER:
            # delayed binding: NOTHING binds (static or dynamic) until the
            # scheduler picks a node — binding early to a topology-pinned
            # PV is exactly the failure WFFC exists to avoid
            # (syncUnboundClaim's shouldDelayBinding gate)
            node = self._selected_node(pvc)
            if not node:
                return
        match = self._find_best_match(pvc, node_name=node)
        if match is not None:
            self._bind(match, pvc)
            return
        if sc is None or not sc.provisioner:
            return  # stays Pending until a PV appears
        pv = self._provision(pvc, sc, node)
        pv.claim_ref = f"{ns}/{name}"  # pre-bind to the provoking claim
        try:
            self.cluster.create("persistentvolumes", pv)
        except ConflictError:
            return  # raced another worker; requeue via events
        self._bind(pv, pvc)

    def _bind(self, pv: PersistentVolume, pvc: PersistentVolumeClaim) -> None:
        """The two-object transaction (bindVolumeToClaim +
        bindClaimToVolume): PV first, claim second — a crash in between
        leaves a pre-bound PV that _sync_claim's pre-bound check heals."""
        if pv.claim_ref != f"{pvc.namespace}/{pvc.name}" or pv.phase != "Bound":
            self.cluster.update(
                "persistentvolumes",
                dataclasses.replace(
                    pv, claim_ref=f"{pvc.namespace}/{pvc.name}",
                    phase="Bound"))
        self.cluster.update(
            "persistentvolumeclaims",
            dataclasses.replace(pvc, volume_name=pv.name, phase="Bound"))

    # ------------------------------------------------------------ volumes

    def _sync_volume(self, name: str) -> None:
        pv = self.cluster.get("persistentvolumes", "", name)
        if pv is None:
            return
        if not pv.claim_ref:
            if pv.phase not in ("Available", "Released"):
                self.cluster.update(
                    "persistentvolumes",
                    dataclasses.replace(pv, phase="Available"))
            # a newly Available volume may satisfy a Pending claim: re-sync
            # matching unbound claims (pv_controller_base.go enqueues
            # claims on volume events for exactly this)
            for pvc in self.cluster.list("persistentvolumeclaims"):
                if not pvc.volume_name and pvc.storage_class == pv.storage_class:
                    self.queue.add(("claim", pvc.namespace, pvc.name))
            return
        ns, _, claim_name = pv.claim_ref.partition("/")
        pvc = self.cluster.get("persistentvolumeclaims", ns, claim_name)
        if pvc is not None:
            if pvc.volume_name == "":
                # statically pre-bound PV arriving after its claim: finish
                # the binding from the claim side (syncVolume enqueues the
                # claim for exactly this case)
                self.queue.add(("claim", ns, claim_name))
                return
            if pvc.volume_name == pv.name:
                return  # live binding
            # the claim bound to a DIFFERENT volume: this never-used PV
            # goes back to Available, not to reclaim (syncVolume unbinds)
            self.cluster.update(
                "persistentvolumes",
                dataclasses.replace(pv, claim_ref="", phase="Available"))
            return
        # bound claim is gone: reclaim (reclaimVolume)
        if pv.reclaim_policy == "Delete":
            self.cluster.delete("persistentvolumes", "", pv.name)
        else:  # Retain: keep the data, mark Released (needs admin action)
            self.cluster.update(
                "persistentvolumes",
                dataclasses.replace(pv, phase="Released"))

    def sync(self, key) -> None:
        what, ns, name = key
        if what == "claim":
            self._sync_claim(ns, name)
        else:
            self._sync_volume(name)


class AttachDetachController(Reconciler):
    """Desired attachments from assigned pods -> node.status.volumesAttached
    (attach_detach_controller.go reconciler, collapsed: the framework has
    no real attach operation, so desired state IS actual state)."""

    WATCH_KINDS = ("pods", "nodes", "persistentvolumeclaims")

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "pods":
            if obj.spec.node_name:
                self.queue.add(obj.spec.node_name)
        elif kind == "nodes" and event != DELETED:
            self.queue.add(obj.name)
        elif kind == "persistentvolumeclaims":
            # (re)bound claim changes which PV a pod's volume resolves to —
            # only nodes running pods that actually reference THIS claim
            for p in self.cluster.list("pods"):
                if p.namespace != obj.namespace or not p.spec.node_name:
                    continue
                if any((v.get("persistentVolumeClaim") or {}).get(
                        "claimName") == obj.name for v in p.spec.volumes):
                    self.queue.add(p.spec.node_name)

    def _desired_for_node(self, node_name: str) -> Tuple[str, ...]:
        attached: List[str] = []
        for p in self.cluster.list("pods"):
            if p.spec.node_name != node_name:
                continue
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            for v in p.spec.volumes:
                claim = (v.get("persistentVolumeClaim") or {})
                cn = claim.get("claimName")
                if cn:
                    pvc = self.cluster.get(
                        "persistentvolumeclaims", p.namespace, cn)
                    if pvc is not None and pvc.volume_name:
                        attached.append(pvc.volume_name)
        return tuple(sorted(set(attached)))

    def sync(self, node_name: str) -> None:
        node, rv = self.cluster.get_with_rv("nodes", "", node_name)
        if node is None:
            return
        desired = self._desired_for_node(node_name)
        if tuple(node.status.volumes_attached) == desired:
            return
        self.cluster.update(
            "nodes",
            dataclasses.replace(
                node, status=dataclasses.replace(
                    node.status, volumes_attached=desired)),
            expect_rv=rv,
        )


class TokenCleaner(Reconciler):
    """Delete expired bootstrap-token Secrets
    (pkg/controller/bootstrap/tokencleaner.go): a token whose
    ``expiration`` (epoch seconds or RFC3339) has passed stops
    authenticating by ceasing to exist."""

    WATCH_KINDS = ("secrets",)

    def _on_event(self, event: str, kind: str, obj) -> None:
        if (kind == "secrets" and event != DELETED
                and isinstance(obj, dict)
                and obj.get("type") == "bootstrap.kubernetes.io/token"):
            self.queue.add((obj.get("namespace", ""), obj.get("name", "")))

    def tick(self, now: float = None) -> int:
        """Periodic sweep (the controller also re-queues on events);
        returns deletions."""
        import time as _time

        from kubernetes_tpu.api.types import parse_time

        now = _time.time() if now is None else now
        n = 0
        for s in list(self.cluster.list("secrets")):
            if not isinstance(s, dict):
                continue
            if s.get("type") != "bootstrap.kubernetes.io/token":
                continue
            exp = parse_time((s.get("data") or {}).get("expiration"))
            if exp is not None and exp <= now:
                self.cluster.delete(
                    "secrets", s.get("namespace", ""), s.get("name", ""))
                n += 1
        return n

    def sync(self, key) -> None:
        self.tick()


class NodeIpamController(Reconciler):
    """Assign each node a pod CIDR from the cluster CIDR
    (pkg/controller/nodeipam/ipam/range_allocator.go): the cluster range
    is carved into per-node subnets of node_cidr_mask_size; a node
    keeps its assignment for life, freed slots are reused."""

    WATCH_KINDS = ("nodes",)

    def __init__(self, cluster, cluster_cidr: str = "10.244.0.0/16",
                 node_mask: int = 24, informers=None):
        import ipaddress

        self.network = ipaddress.ip_network(cluster_cidr)
        self.node_mask = node_mask
        self._subnets = list(self.network.subnets(new_prefix=node_mask))
        super().__init__(cluster, informers=informers)

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "nodes" and event != DELETED and not obj.spec.pod_cidr:
            self.queue.add(obj.name)

    def sync(self, name: str) -> None:
        node, rv = self.cluster.get_with_rv("nodes", "", name)
        if node is None or node.spec.pod_cidr:
            return
        used = {n.spec.pod_cidr for n in self.cluster.list("nodes")
                if n.spec.pod_cidr}
        for subnet in self._subnets:
            cidr = str(subnet)
            if cidr not in used:
                self.cluster.update(
                    "nodes",
                    dataclasses.replace(
                        node, spec=dataclasses.replace(
                            node.spec, pod_cidr=cidr)),
                    expect_rv=rv,
                )
                return
        raise RuntimeError(
            f"cluster CIDR {self.network} exhausted "
            f"({len(self._subnets)} /{self.node_mask} ranges)")


class ServiceAccountController(Reconciler):
    """Every active namespace carries a 'default' ServiceAccount
    (serviceaccounts_controller.go)."""

    WATCH_KINDS = ("namespaces", "serviceaccounts")

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "namespaces":
            ns = obj.get("name") if isinstance(obj, dict) else obj.name
            self.queue.add(ns)
        elif kind == "serviceaccounts" and event == DELETED:
            self.queue.add(obj.get("namespace", "default"))

    def sync(self, ns: str) -> None:
        nso = self.cluster.get("namespaces", "", ns)
        if nso is None:
            return
        phase = (nso.get("status") or {}).get("phase", "Active") \
            if isinstance(nso, dict) else "Active"
        if phase == "Terminating":
            return
        if self.cluster.get("serviceaccounts", ns, "default") is None:
            try:
                self.cluster.create("serviceaccounts", {
                    "namespace": ns, "name": "default",
                    "kind": "ServiceAccount", "apiVersion": "v1",
                    "metadata": {"namespace": ns, "name": "default"},
                })
            except ConflictError:
                pass


class TokenController(Reconciler):
    """Every ServiceAccount gets a token Secret; deleting the SA reaps its
    secrets (tokens_controller.go).  The minted secret is exactly what
    TokenAuthenticator resolves to system:serviceaccount:<ns>:<name>."""

    WATCH_KINDS = ("serviceaccounts", "secrets")

    @staticmethod
    def _secret_name(sa_name: str) -> str:
        return f"{sa_name}-token"

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "serviceaccounts":
            self.queue.add((obj.get("namespace", "default"),
                            obj.get("name", "")))
        elif kind == "secrets" and event == DELETED:
            if obj.get("type") == "kubernetes.io/service-account-token":
                sa = (obj.get("data") or {}).get("serviceAccountName", "")
                if sa:
                    self.queue.add((obj.get("namespace", "default"), sa))

    def sync(self, key) -> None:
        ns, name = key
        sa = self.cluster.get("serviceaccounts", ns, name)
        secret_name = self._secret_name(name)
        if sa is None:
            # SA deleted: reap its token secrets
            cur = self.cluster.get("secrets", ns, secret_name)
            if cur is not None:
                self.cluster.delete("secrets", ns, secret_name)
            return
        if self.cluster.get("secrets", ns, secret_name) is not None:
            return
        try:
            self.cluster.create("secrets", {
                "namespace": ns, "name": secret_name,
                "kind": "Secret", "apiVersion": "v1",
                "type": "kubernetes.io/service-account-token",
                "metadata": {"namespace": ns, "name": secret_name},
                "annotations": {
                    "kubernetes.io/service-account.name": name,
                },
                "data": {
                    "token": _secrets.token_hex(16),
                    "namespace": ns,
                    "serviceAccountName": name,
                },
            })
        except ConflictError:
            pass
