"""Metrics timeline store + online anomaly detection (ISSUE 20).

Every observability layer before this one is instant-scope: /metrics is
a point-in-time snapshot, the /debug/* rings hold the last few cycles.
A diurnal scenario or a multi-hour autoscaler run left no queryable
history of how utilization, burn rates, mesh width, or queue depth
EVOLVED — and the learned-scoring line (PAPERS.md "Learning to Score",
Gavel's policy evaluation) tunes on exactly such outcome trajectories.

`TimelineStore` closes that gap in-process and dependency-free:

- it samples EVERY registered metric family through the
  utils/metrics.py sampling protocol (`sample_families`) on a
  configurable cadence — counters stored as per-sample deltas (rates
  fall out of the timestamps), gauges as values, histograms as selected
  quantiles — into bounded per-series rings;
- typed event annotations from the existing seams (breaker/shard
  transitions, mesh rebuilds, AIMD resizes, autoscaler rounds, SLO
  burns, shed bursts, scenario chaos windows) interleave with the
  samples, so an excursion and its cause land on one timeline;
- an `AnomalyDetector` runs rule-based checks (static threshold,
  z-score vs a trailing window, least-squares slope) over configured
  series after every sweep, edge-triggered with re-arm hysteresis (a
  storm fires each rule ONCE, not once per sample) — each firing
  increments scheduler_timeline_anomalies_total{rule,series}, annotates
  the timeline, and (when wired) dumps a throttled flight-recorder
  postmortem;
- the whole store serves at GET /debug/timeline
  (?series=&window=&step=&limit=, 4MB-capped like its siblings),
  exports as a JSONL artifact (`export_jsonl` — bench --timeline-out,
  ScenarioRunner banking), and renders to a static self-contained HTML
  report (inline SVG sparklines per series with annotation lanes).

The scheduler drives `maybe_sample()` from its commit tail AND its idle
poll path (an idle scheduler still has a trajectory), under the same
<2%-of-cycle-wall budget discipline as the telemetry/perfobs/quality
hooks (scheduler_timeline_seconds_total, pinned by perf_smoke).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.utils import metrics as m
from kubernetes_tpu.utils.metrics import sample_families

# ------------------------------------------------------------ anomaly rules

# the default rule set: quiet on a healthy run by construction —
# degraded cycles and invariant violations are zero-delta unless
# something actually broke, and the z-score guard needs a long trailing
# window before it can fire at all
DEFAULT_RULES: List[dict] = [
    {"rule": "threshold", "series": "scheduler_degraded_cycles_total",
     "op": ">", "value": 0.0},
    {"rule": "threshold", "series": "scheduler_invariant_violations_total",
     "op": ">", "value": 0.0},
    {"rule": "zscore", "series": "scheduler_pending_pods",
     "window": 64, "z": 6.0, "min_samples": 16},
]

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


def _rule_name(rule: dict) -> str:
    return str(rule.get("name") or rule.get("rule", "threshold"))


class AnomalyDetector:
    """Rule-based online checks over the store's sampled series.

    Edge-triggered with re-arm hysteresis, per (rule, series): a rule
    whose condition holds fires ONCE and disarms; it re-arms only after
    observing the condition false again.  A seeded chaos storm that
    keeps a series hot for hundreds of samples therefore produces one
    anomaly, not hundreds — the exactly-once-throttled discipline the
    flight recorder applies to postmortems, applied to detection.

    `postmortem(trigger, detail)` — when wired (the scheduler passes
    its own `_postmortem`) — dumps the flight-recorder snapshot; the
    recorder's own per-trigger min-interval throttle still applies on
    top, so even rapid re-arm/re-fire cycles cannot storm snapshots.
    """

    def __init__(
        self,
        rules: Optional[List[dict]] = None,
        postmortem: Optional[Callable[[str, str], None]] = None,
    ):
        self.rules = [dict(r) for r in (rules if rules is not None
                                        else DEFAULT_RULES)]
        self.postmortem = postmortem
        self._disarmed: Dict[Tuple[str, str], bool] = {}
        self.anomalies_total = 0
        self.fired: "deque[dict]" = deque(maxlen=64)
        self._lock = threading.Lock()

    # ------------------------------------------------------------ evaluation

    def _condition(self, rule: dict, points: List[Tuple[float, float]]
                   ) -> Tuple[bool, str]:
        """(fires?, detail) for one rule over one series' point tail.
        Counters arrive as per-sample deltas (the store's encoding), so
        a threshold of >0 on a *_total family means 'it moved'."""
        kind = rule.get("rule", "threshold")
        if not points:
            return False, ""
        if kind == "threshold":
            op = _OPS.get(str(rule.get("op", ">")), _OPS[">"])
            bound = float(rule.get("value", 0.0))
            last = points[-1][1]
            return op(last, bound), (
                f"value {last:g} {rule.get('op', '>')} {bound:g}"
            )
        window = int(rule.get("window", 32))
        tail = points[-window:]
        if kind == "zscore":
            min_samples = int(rule.get("min_samples", 8))
            if len(tail) < max(2, min_samples):
                return False, ""
            base = [v for _, v in tail[:-1]]
            mean = sum(base) / len(base)
            var = sum((v - mean) ** 2 for v in base) / len(base)
            std = var ** 0.5
            if std <= 0.0:
                return False, ""
            z = abs(tail[-1][1] - mean) / std
            bound = float(rule.get("z", 4.0))
            return z >= bound, (
                f"z={z:.2f} >= {bound:g} (mean {mean:g}, std {std:g})"
            )
        if kind == "slope":
            min_samples = int(rule.get("min_samples", 4))
            if len(tail) < max(2, min_samples):
                return False, ""
            # least-squares slope in value-units per second
            n = len(tail)
            t0 = tail[0][0]
            xs = [t - t0 for t, _ in tail]
            ys = [v for _, v in tail]
            mx = sum(xs) / n
            my = sum(ys) / n
            denom = sum((x - mx) ** 2 for x in xs)
            if denom <= 0.0:
                return False, ""
            slope = sum((x - mx) * (y - my)
                        for x, y in zip(xs, ys)) / denom
            bound = float(rule.get("per_second", 1.0))
            if bound >= 0:
                return slope >= bound, f"slope {slope:g}/s >= {bound:g}/s"
            return slope <= bound, f"slope {slope:g}/s <= {bound:g}/s"
        return False, ""

    def observe(self, store: "TimelineStore", now: float) -> List[dict]:
        """Run every rule after one sampling sweep.  Returns the
        anomalies that FIRED this sweep (edge-triggered)."""
        fired: List[dict] = []
        for rule in self.rules:
            pattern = str(rule.get("series", ""))
            if not pattern:
                continue
            name = _rule_name(rule)
            for series in store.match_series(pattern):
                points = store.series_points(series)
                hot, detail = self._condition(rule, points)
                key = (name, series)
                with self._lock:
                    disarmed = self._disarmed.get(key, False)
                    if hot and not disarmed:
                        self._disarmed[key] = True
                        self.anomalies_total += 1
                    elif not hot and disarmed:
                        self._disarmed[key] = False  # recovered: re-arm
                        continue
                    else:
                        continue
                anom = {"t": now, "rule": name, "series": series,
                        "detail": detail}
                self.fired.append(anom)
                fired.append(anom)
                m.TIMELINE_ANOMALIES.inc(rule=name, series=series)
                if self.postmortem is not None:
                    try:
                        self.postmortem(
                            f"anomaly_{name}", f"{series}: {detail}"
                        )
                    except Exception:  # noqa: BLE001 — detection never raises
                        pass
        return fired

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rules": [dict(r) for r in self.rules],
                "anomalies_total": self.anomalies_total,
                "disarmed": sorted(
                    f"{r}:{s}" for (r, s), d in self._disarmed.items() if d
                ),
            }


# ------------------------------------------------------------------ store

class TimelineStore:
    """Bounded in-process time-series store over the metric registry.

    Thread-safe: `maybe_sample` runs on the scheduling thread,
    `annotate` from scheduler/autoscaler/scenario threads, readers
    (HTTP handlers, exports) from server threads.
    """

    def __init__(
        self,
        interval_s: float = 1.0,
        retention: int = 512,
        quantiles: Tuple[float, ...] = (0.5, 0.99),
        clock: Callable[[], float] = time.monotonic,
        detector: Optional[AnomalyDetector] = None,
        registry=None,
    ):
        self.interval_s = max(0.0, float(interval_s))
        self.retention = max(2, int(retention))
        self.quantiles = tuple(quantiles)
        self.clock = clock
        self.detector = detector if detector is not None else AnomalyDetector()
        self._registry = registry
        self._series: Dict[str, "deque[Tuple[float, float]]"] = {}
        self._kinds: Dict[str, str] = {}
        self._counter_base: Dict[str, float] = {}
        self._events: "deque[dict]" = deque(maxlen=self.retention)
        self._anomalies: "deque[dict]" = deque(maxlen=64)
        self._last_sample: Optional[float] = None
        self.lag_s = 0.0
        self.samples_total = 0
        self._wall_anchor = (time.time(), clock())
        self._lock = threading.Lock()

    # -------------------------------------------------------------- sampling

    def maybe_sample(self, now: Optional[float] = None) -> bool:
        """One cadence-gated sampling sweep.  Returns whether a sweep
        ran.  Lag — how far past the due time this sweep actually fired
        — is tracked as both a gauge and a store field: the scheduler's
        heartbeat surfaces it (sampling falling behind its cadence is
        itself a signal)."""
        now = self.clock() if now is None else float(now)
        with self._lock:
            if (self._last_sample is not None
                    and now - self._last_sample < self.interval_s):
                return False
            if self._last_sample is None:
                self.lag_s = 0.0
            else:
                self.lag_s = max(
                    0.0, (now - self._last_sample) - self.interval_s
                )
            self._last_sample = now
        triples = sample_families(self._registry, quantiles=self.quantiles)
        with self._lock:
            for name, kind, value in triples:
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = deque(maxlen=self.retention)
                    self._kinds[name] = kind
                if kind == "counter":
                    # per-sample delta; the first sighting establishes
                    # the baseline (a pre-existing cumulative total must
                    # not read as a spike)
                    base = self._counter_base.get(name)
                    self._counter_base[name] = value
                    point = 0.0 if base is None else value - base
                else:
                    point = value
                ring.append((now, point))
            self.samples_total += 1
            n_series = len(self._series)
        m.TIMELINE_SAMPLES.inc()
        m.TIMELINE_LAG.set(self.lag_s)
        m.TIMELINE_SERIES.set(float(n_series))
        if self.detector is not None:
            for anom in self.detector.observe(self, now):
                with self._lock:
                    self._anomalies.append(anom)
                self.annotate(
                    "anomaly", f"{anom['rule']} {anom['series']}: "
                    f"{anom['detail']}", t=now,
                )
        return True

    # ------------------------------------------------------------ annotation

    def annotate(self, kind: str, detail: str = "",
                 t: Optional[float] = None, **fields) -> dict:
        """Push one typed event annotation onto the timeline (breaker
        transition, mesh rebuild, AIMD resize, autoscaler round, SLO
        burn, shed burst, chaos window edge, ...)."""
        ev = {"t": self.clock() if t is None else float(t),
              "kind": str(kind), "detail": str(detail)}
        if fields:
            ev.update(fields)
        with self._lock:
            self._events.append(ev)
        m.TIMELINE_EVENTS.inc(kind=str(kind))
        return ev

    # --------------------------------------------------------------- readers

    def match_series(self, pattern: str) -> List[str]:
        """Series names matching `pattern`: exact, or prefix when the
        pattern ends with '*' (so a rule can cover every child of a
        labeled family: 'scheduler_queue_shed_pods_total*')."""
        with self._lock:
            names = list(self._series)
        if pattern.endswith("*"):
            prefix = pattern[:-1]
            return [n for n in names if n.startswith(prefix)]
        return [n for n in names if n == pattern]

    def series_points(self, name: str) -> List[Tuple[float, float]]:
        with self._lock:
            ring = self._series.get(name)
            return list(ring) if ring is not None else []

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def anomalies(self) -> List[dict]:
        with self._lock:
            return list(self._anomalies)

    def summary(self) -> dict:
        det = self.detector
        with self._lock:
            out = {
                "samples": self.samples_total,
                "series": len(self._series),
                "events": len(self._events),
                "lag_s": round(self.lag_s, 6),
                "interval_s": self.interval_s,
                "retention": self.retention,
            }
        out["anomalies"] = det.anomalies_total if det is not None else 0
        return out

    # ----------------------------------------------------------------- query

    def debug_payload(self, limit: Optional[int] = None,
                      query: str = "") -> dict:
        """GET /debug/timeline body.

        Query contract: `?series=a,b*` filters series (comma list,
        exact or '*'-prefix), `?window=S` keeps only the last S seconds,
        `?step=S` downsamples to one point (the newest) per S-second
        bucket, `?limit=N` bounds points per series AND events (the
        shared debug_body halves it until the body fits the 4MB cap).
        """
        from urllib.parse import parse_qs

        q = parse_qs(query or "")

        def _qfloat(key: str) -> Optional[float]:
            try:
                v = q.get(key)
                return float(v[0]) if v else None
            except (ValueError, TypeError):
                return None

        window = _qfloat("window")
        step = _qfloat("step")
        patterns = []
        for raw in q.get("series", []):
            patterns.extend(p for p in raw.split(",") if p)
        names = self.series_names()
        if patterns:
            keep = set()
            for p in patterns:
                keep.update(self.match_series(p))
            names = [n for n in names if n in keep]
        now = self.clock()
        cutoff = (now - window) if window is not None else None
        series_out: Dict[str, dict] = {}
        for name in names:
            pts = self.series_points(name)
            if cutoff is not None:
                pts = [p for p in pts if p[0] >= cutoff]
            if step is not None and step > 0 and pts:
                buckets: Dict[int, Tuple[float, float]] = {}
                for t, v in pts:  # newest point per bucket wins
                    buckets[int(t // step)] = (t, v)
                pts = [buckets[k] for k in sorted(buckets)]
            if limit is not None and limit >= 0:
                pts = pts[-limit:] if limit else []
            series_out[name] = {
                "kind": self._kinds.get(name, "gauge"),
                "points": [[round(t, 6), v] for t, v in pts],
            }
        events = self.events()
        anomalies = self.anomalies()
        if cutoff is not None:
            events = [e for e in events if e["t"] >= cutoff]
            anomalies = [a for a in anomalies if a["t"] >= cutoff]
        if limit is not None and limit >= 0:
            events = events[-limit:] if limit else []
            anomalies = anomalies[-limit:] if limit else []
        det = self.detector
        return {
            "summary": self.summary(),
            "detector": det.snapshot() if det is not None else None,
            "series": series_out,
            "events": events,
            "anomalies": anomalies,
        }

    # ---------------------------------------------------------------- export

    def export_jsonl(self, path: str) -> int:
        """Bank the whole store as a JSONL artifact: one `meta` line
        (with the wall-clock anchor so monotonic timestamps convert),
        one `series` line per series, one `event`/`anomaly` line each.
        Returns the number of lines written."""
        wall, mono = self._wall_anchor
        det = self.detector
        lines: List[dict] = [{
            "kind": "meta",
            "summary": self.summary(),
            "detector": det.snapshot() if det is not None else None,
            "wall_anchor": wall,
            "monotonic_anchor": mono,
        }]
        for name in self.series_names():
            lines.append({
                "kind": "series",
                "name": name,
                "type": self._kinds.get(name, "gauge"),
                "points": [[round(t, 6), v]
                           for t, v in self.series_points(name)],
            })
        for ev in self.events():
            # annotations carry their own typed "kind" — nest them so
            # the envelope marker survives the round trip
            lines.append({"kind": "event", "event": ev})
        for anom in self.anomalies():
            lines.append({"kind": "anomaly", **anom})
        with open(path, "w") as f:
            for line in lines:
                f.write(json.dumps(line) + "\n")
        return len(lines)


def load_jsonl(path: str) -> dict:
    """A banked JSONL artifact back into the debug_payload shape (the
    HTML renderer accepts either, so reports render live OR offline)."""
    meta: dict = {}
    series: Dict[str, dict] = {}
    events: List[dict] = []
    anomalies: List[dict] = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            rec = json.loads(raw)
            kind = rec.get("kind")
            if kind == "meta":
                meta = rec
            elif kind == "series":
                series[rec["name"]] = {
                    "kind": rec.get("type", "gauge"),
                    "points": rec.get("points", []),
                }
            elif kind == "event":
                events.append(rec.get("event", {}))
            elif kind == "anomaly":
                anomalies.append(
                    {k: v for k, v in rec.items() if k != "kind"}
                )
    return {
        "summary": meta.get("summary", {}),
        "detector": meta.get("detector"),
        "series": series,
        "events": events,
        "anomalies": anomalies,
    }


# ------------------------------------------------------------- HTML report

_HTML_HEAD = """<!doctype html>
<html><head><meta charset="utf-8"><title>{title}</title><style>
body {{ font: 13px/1.4 system-ui, sans-serif; margin: 24px;
       background: #fafafa; color: #222; }}
h1 {{ font-size: 18px; }} h2 {{ font-size: 13px; margin: 18px 0 2px;
      font-weight: 600; }}
.meta {{ color: #666; margin-bottom: 12px; }}
.row {{ background: #fff; border: 1px solid #e2e2e2; border-radius: 4px;
        padding: 6px 10px; margin-bottom: 6px; }}
.minmax {{ color: #888; font-size: 11px; }}
svg {{ display: block; }}
.lane {{ margin: 12px 0; }}
.ev {{ display: inline-block; margin-right: 10px; font-size: 11px; }}
.dot {{ display: inline-block; width: 8px; height: 8px;
        border-radius: 50%; margin-right: 3px; }}
</style></head><body>
"""

_LANE_COLORS = [
    "#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
    "#8c564b", "#e377c2", "#17becf", "#bcbd22", "#7f7f7f",
]


def _event_color(kind: str) -> str:
    if kind == "anomaly":
        return "#d62728"
    return _LANE_COLORS[hash(kind) % len(_LANE_COLORS)]


def _svg_sparkline(points: List[List[float]], events: List[dict],
                   t0: float, t1: float, width: int = 640,
                   height: int = 48) -> str:
    """One series as an inline SVG polyline with vertical annotation
    rules at event times — no external assets, renders from file://."""
    span = max(t1 - t0, 1e-9)
    vals = [v for _, v in points]
    lo, hi = min(vals), max(vals)
    vspan = max(hi - lo, 1e-9)

    def x(t: float) -> float:
        return round((t - t0) / span * (width - 2) + 1, 2)

    def y(v: float) -> float:
        return round(height - 3 - (v - lo) / vspan * (height - 6), 2)

    parts = [f'<svg width="{width}" height="{height}" '
             f'viewBox="0 0 {width} {height}">']
    for ev in events:
        t = ev.get("t")
        if t is None or not (t0 <= t <= t1):
            continue
        color = _event_color(str(ev.get("kind", "")))
        parts.append(
            f'<line x1="{x(t)}" y1="0" x2="{x(t)}" y2="{height}" '
            f'stroke="{color}" stroke-width="1" opacity="0.45">'
            f'<title>{_esc(ev.get("kind", ""))}: '
            f'{_esc(ev.get("detail", ""))}</title></line>'
        )
    pts = " ".join(f"{x(t)},{y(v)}" for t, v in points)
    parts.append(f'<polyline points="{pts}" fill="none" '
                 f'stroke="#1f77b4" stroke-width="1.2"/>')
    parts.append("</svg>")
    return "".join(parts)


def _esc(s) -> str:
    return (str(s).replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;").replace('"', "&quot;"))


def render_html(payload: dict, title: str = "kubernetes_tpu timeline",
                max_series: int = 200) -> str:
    """debug_payload/load_jsonl dict -> one self-contained HTML page:
    a sparkline per series (flat-zero series are folded away), shared
    time axis, annotation rules through every chart, and an event/
    anomaly legend lane.  Dependency-free by design — the artifact
    must open from a CI tarball with no server behind it."""
    series = payload.get("series", {})
    events = list(payload.get("events", []))
    anomalies = payload.get("anomalies", [])
    for anom in anomalies:
        events.append({"t": anom.get("t"), "kind": "anomaly",
                       "detail": f"{anom.get('rule')} {anom.get('series')}"})
    all_t = [p[0] for s in series.values() for p in s.get("points", [])]
    all_t += [e["t"] for e in events if e.get("t") is not None]
    t0, t1 = (min(all_t), max(all_t)) if all_t else (0.0, 1.0)
    out = [_HTML_HEAD.format(title=_esc(title))]
    out.append(f"<h1>{_esc(title)}</h1>")
    summ = payload.get("summary", {})
    out.append(
        '<div class="meta">'
        f"samples={summ.get('samples', '?')} "
        f"series={len(series)} events={len(events)} "
        f"anomalies={summ.get('anomalies', len(anomalies))} "
        f"span={t1 - t0:.1f}s</div>"
    )
    if events:
        kinds = sorted({str(e.get("kind", "")) for e in events})
        lane = "".join(
            f'<span class="ev"><span class="dot" style="background:'
            f'{_event_color(k)}"></span>{_esc(k)}</span>'
            for k in kinds
        )
        out.append(f'<div class="lane">{lane}</div>')
    shown = 0
    for name in sorted(series):
        pts = series[name].get("points", [])
        if len(pts) < 2:
            continue
        vals = [v for _, v in pts]
        if min(vals) == max(vals) == 0.0:
            continue  # flat zero: noise in a 70-family registry
        if shown >= max_series:
            out.append(f"<p class='meta'>… {len(series) - shown} more "
                       "series elided (max_series)</p>")
            break
        shown += 1
        out.append(f"<h2>{_esc(name)}</h2>")
        out.append(
            '<div class="row">'
            + _svg_sparkline(pts, events, t0, t1)
            + f'<div class="minmax">min {min(vals):g} · '
            f"max {max(vals):g} · last {vals[-1]:g} · "
            f"kind {series[name].get('kind', 'gauge')}</div></div>"
        )
    if anomalies:
        out.append("<h2>anomalies</h2>")
        for anom in anomalies:
            out.append(
                f'<div class="row">t={anom.get("t", 0):.3f} '
                f"<b>{_esc(anom.get('rule'))}</b> "
                f"{_esc(anom.get('series'))}: "
                f"{_esc(anom.get('detail', ''))}</div>"
            )
    out.append("</body></html>\n")
    return "\n".join(out)


# --------------------------------------------------------- process default
# /debug/timeline serves the default store; a Scheduler with timeline
# enabled installs its own here (replica 0 wins, siblings register
# alongside — runtime/defaults.py ProcessDefault, which this store uses
# from day one instead of growing a seventh copy of the pattern)

from kubernetes_tpu.runtime.defaults import ProcessDefault  # noqa: E402

_DEFAULT = ProcessDefault("timeline", TimelineStore)


def get_default() -> TimelineStore:
    return _DEFAULT.get()


def set_default(store: TimelineStore, replica: int = 0) -> None:
    _DEFAULT.set(store, replica)


def replica_instances() -> dict:
    """{replica id: TimelineStore} of every install this process saw."""
    return _DEFAULT.replicas()
