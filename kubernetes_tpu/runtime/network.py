"""Service networking slice (SURVEY.md layer 9).

The reference's dataplane is kube-proxy programming iptables/ipvs from
Service+Endpoints watches (pkg/proxy; `syncProxyRules`
iptables/proxier.go:667).  The standalone analog keeps the same two-stage
architecture over the blackboard:

  * EndpointsController (pkg/controller/endpoint): for every Service,
    derive the Endpoints object = ready backends (assigned + Running pods
    matching the selector), written back to the store;
  * ServiceProxy (kube-proxy): watches services + endpoints and maintains a
    versioned rules table (the iptables-rules analog — rebuilt by a full
    `sync_rules` sweep, like syncProxyRules' full-table writes), exposing
    `route(ns, service)` round-robin backend selection (the ipvs/iptables
    DNAT probability-chain analog).

Backends are addressed as (pod name, node name) — the hollow world has no
pod IPs; a real deployment substitutes the CNI address at the same seam.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import labels as klabels
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.controllers import Reconciler


def _service_backends(cluster: LocalCluster, svc: dict) -> List[dict]:
    sel = klabels.selector_from_match_labels(svc.get("selector") or {})
    out = []
    for p in cluster.list("pods"):
        if (
            p.namespace == svc["namespace"]
            and p.spec.node_name
            and p.status.phase == "Running"
            and p.status.ready  # IsPodReady: probes gate endpoint membership
            and sel.matches(p.labels)
        ):
            out.append({"pod": p.name, "node": p.spec.node_name})
    out.sort(key=lambda a: a["pod"])
    return out


class EndpointsController(Reconciler):
    """pkg/controller/endpoint: Service selector + ready pods -> Endpoints
    object in the store (the objects kube-proxy consumes)."""

    def _on_event(self, event: str, kind: str, obj) -> None:
        # watch callbacks run under the store lock: enqueue markers only
        if kind == "services":
            self.queue.add((obj["namespace"], obj["name"]))
        elif kind == "pods":
            self.queue.add(("@pod", obj.namespace))

    def sync(self, key) -> None:
        if key[0] == "@pod":
            for svc in self.cluster.list("services"):
                if svc["namespace"] == key[1]:
                    self.sync((svc["namespace"], svc["name"]))
            return
        ns, name = key
        svc = self.cluster.get("services", ns, name)
        if svc is None:
            self.cluster.delete("endpoints", ns, name)
            return
        ep = {
            "namespace": ns,
            "name": name,
            "addresses": _service_backends(self.cluster, svc),
        }
        cur = self.cluster.get("endpoints", ns, name)
        if cur is None:
            self.cluster.create("endpoints", ep)
        elif cur.get("addresses") != ep["addresses"]:
            self.cluster.update("endpoints", ep)

class ServiceProxy:
    """kube-proxy analog: a full-resync rules table + round-robin routing.

    `sync_rules` is the syncProxyRules shape — recompute the WHOLE table
    from the current services+endpoints state (level-triggered; the version
    counter is the iptables-restore generation).  `route` picks the next
    backend for a service round-robin (the ipvs rr scheduler / iptables
    statistic-mode chain)."""

    def __init__(self, cluster: LocalCluster, node_name: str = "proxy-0"):
        self.cluster = cluster
        self.node_name = node_name
        self._lock = threading.Lock()
        self.rules: Dict[Tuple[str, str], List[dict]] = {}
        self.rules_version = 0
        self._rr: Dict[Tuple[str, str], int] = {}
        self._dirty = threading.Event()
        cluster.watch(self._on_event)
        self.sync_rules()

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind in ("services", "endpoints"):
            self._dirty.set()

    def sync_rules(self) -> int:
        """Full-table rebuild (iptables/proxier.go:667 syncProxyRules).
        The dirty mark clears BEFORE reading state: a commit landing during
        the sweep re-marks and forces another sweep (level-triggered)."""
        self._dirty.clear()
        table: Dict[Tuple[str, str], List[dict]] = {}
        for svc in self.cluster.list("services"):
            key = (svc["namespace"], svc["name"])
            ep = self.cluster.get("endpoints", *key)
            table[key] = list(ep.get("addresses", [])) if ep else []
        with self._lock:
            self.rules = table
            self.rules_version += 1
            return self.rules_version

    def sync_if_dirty(self) -> bool:
        if self._dirty.is_set():
            self.sync_rules()
            return True
        return False

    def route(self, namespace: str, service: str) -> Optional[dict]:
        """Next backend for the service VIP, or None (blackhole — the
        REJECT rule for an endpoint-less service)."""
        key = (namespace, service)
        with self._lock:
            backends = self.rules.get(key) or []
            if not backends:
                return None
            i = self._rr.get(key, 0) % len(backends)
            self._rr[key] = i + 1
            return backends[i]

    def run(self, stop: threading.Event, period: float = 0.05) -> threading.Thread:
        def loop():
            while not stop.is_set():
                self.sync_if_dirty()
                stop.wait(period)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t


class IPVSProxy:
    """The second dataplane mode (ipvs/proxier.go:736 syncProxyRules).

    Where the iptables proxier (ServiceProxy above) REWRITES the whole
    table per sync (iptables-restore semantics), the ipvs proxier keeps
    virtual servers + real-server sets programmed in the kernel and
    applies only the DELTA each sync — why ipvs scales to tens of
    thousands of services.  The "kernel" here is the ``programmed``
    map; every apply operation is recorded in ``ops`` (and counted per
    sync in ``last_ops``) so incrementality is observable: adding one
    endpoint to one service must cost O(1) operations, not O(cluster).

    Scheduling: round-robin (the ipvs rr scheduler, the proxier's
    default)."""

    def __init__(self, cluster: LocalCluster, node_name: str = "proxy-0"):
        self.cluster = cluster
        self.node_name = node_name
        self._lock = threading.Lock()
        # (ns, name) -> programmed real-server set; addr dicts keyed by
        # their wire identity
        self.programmed: Dict[Tuple[str, str], Dict[str, dict]] = {}
        # only the LAST sync's apply operations are retained (a daemon
        # syncing every 50ms for weeks must not accumulate history);
        # total_ops counts lifetime operations for observability
        self.ops: List[tuple] = []
        self.total_ops = 0
        self.last_ops = 0
        self.rules_version = 0
        self._rr: Dict[Tuple[str, str], int] = {}
        self._dirty = threading.Event()
        cluster.watch(self._on_event)
        self.sync_rules()

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind in ("services", "endpoints"):
            self._dirty.set()

    @staticmethod
    def _addr_id(a: dict) -> str:
        return f"{a.get('ip', a.get('pod', ''))}"

    def sync_rules(self) -> int:
        """Diff desired (services+endpoints) against programmed state and
        apply only the changes (the ipvs proxier reads kernel state and
        Add/Delete-s virtual/real servers individually)."""
        self._dirty.clear()
        desired: Dict[Tuple[str, str], Dict[str, dict]] = {}
        for svc in self.cluster.list("services"):
            key = (svc["namespace"], svc["name"])
            ep = self.cluster.get("endpoints", *key)
            addrs = list(ep.get("addresses", [])) if ep else []
            desired[key] = {self._addr_id(a): a for a in addrs}
        with self._lock:
            self.ops = []
            # removed virtual servers
            for key in list(self.programmed):
                if key not in desired:
                    for aid in self.programmed[key]:
                        self.ops.append(("del-real", key, aid))
                    self.ops.append(("del-virtual", key))
                    del self.programmed[key]
                    self._rr.pop(key, None)
            for key, want in desired.items():
                have = self.programmed.get(key)
                if have is None:
                    self.ops.append(("add-virtual", key))
                    have = self.programmed[key] = {}
                for aid in list(have):
                    if aid not in want:
                        self.ops.append(("del-real", key, aid))
                        del have[aid]
                for aid, addr in want.items():
                    if aid not in have:
                        self.ops.append(("add-real", key, aid))
                        have[aid] = addr
                    else:
                        have[aid] = addr  # refresh payload, no kernel op
            self.last_ops = len(self.ops)
            self.total_ops += self.last_ops
            self.rules_version += 1
            return self.rules_version

    def sync_if_dirty(self) -> bool:
        if self._dirty.is_set():
            self.sync_rules()
            return True
        return False

    def route(self, namespace: str, service: str) -> Optional[dict]:
        """Next real server for the virtual server, or None (an
        endpoint-less ipvs service blackholes)."""
        key = (namespace, service)
        with self._lock:
            backends = list(self.programmed.get(key, {}).values())
            if not backends:
                return None
            i = self._rr.get(key, 0) % len(backends)
            self._rr[key] = i + 1
            return backends[i]

    def run(self, stop: threading.Event,
            period: float = 0.05) -> threading.Thread:
        def loop():
            while not stop.is_set():
                self.sync_if_dirty()
                stop.wait(period)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t
