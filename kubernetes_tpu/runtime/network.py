"""Service networking slice (SURVEY.md layer 9).

The reference's dataplane is kube-proxy programming iptables/ipvs from
Service+Endpoints watches (pkg/proxy; `syncProxyRules`
iptables/proxier.go:667).  The standalone analog keeps the same two-stage
architecture over the blackboard:

  * EndpointsController (pkg/controller/endpoint): for every Service,
    derive the Endpoints object = ready backends (assigned + Running pods
    matching the selector), written back to the store;
  * ServiceProxy (kube-proxy): watches services + endpoints and maintains a
    versioned rules table (the iptables-rules analog — rebuilt by a full
    `sync_rules` sweep, like syncProxyRules' full-table writes), exposing
    `route(ns, service)` round-robin backend selection (the ipvs/iptables
    DNAT probability-chain analog).

Backends are addressed as (pod name, node name) — the hollow world has no
pod IPs; a real deployment substitutes the CNI address at the same seam.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import labels as klabels
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.controllers import Reconciler


def _service_backends(cluster: LocalCluster, svc: dict) -> List[dict]:
    sel = klabels.selector_from_match_labels(svc.get("selector") or {})
    out = []
    for p in cluster.list("pods"):
        if (
            p.namespace == svc["namespace"]
            and p.spec.node_name
            and p.status.phase == "Running"
            and p.status.ready  # IsPodReady: probes gate endpoint membership
            and sel.matches(p.labels)
        ):
            out.append({"pod": p.name, "node": p.spec.node_name})
    out.sort(key=lambda a: a["pod"])
    return out


class EndpointsController(Reconciler):
    """pkg/controller/endpoint: Service selector + ready pods -> Endpoints
    object in the store (the objects kube-proxy consumes)."""

    def _on_event(self, event: str, kind: str, obj) -> None:
        # watch callbacks run under the store lock: enqueue markers only
        if kind == "services":
            self.queue.add((obj["namespace"], obj["name"]))
        elif kind == "pods":
            self.queue.add(("@pod", obj.namespace))

    def sync(self, key) -> None:
        if key[0] == "@pod":
            for svc in self.cluster.list("services"):
                if svc["namespace"] == key[1]:
                    self.sync((svc["namespace"], svc["name"]))
            return
        ns, name = key
        svc = self.cluster.get("services", ns, name)
        if svc is None:
            self.cluster.delete("endpoints", ns, name)
            return
        ep = {
            "namespace": ns,
            "name": name,
            "addresses": _service_backends(self.cluster, svc),
        }
        cur = self.cluster.get("endpoints", ns, name)
        if cur is None:
            self.cluster.create("endpoints", ep)
        elif cur.get("addresses") != ep["addresses"]:
            self.cluster.update("endpoints", ep)

class ServiceProxy:
    """kube-proxy analog: a full-resync rules table + round-robin routing.

    `sync_rules` is the syncProxyRules shape — recompute the WHOLE table
    from the current services+endpoints state (level-triggered; the version
    counter is the iptables-restore generation).  `route` picks the next
    backend for a service round-robin (the ipvs rr scheduler / iptables
    statistic-mode chain)."""

    def __init__(self, cluster: LocalCluster, node_name: str = "proxy-0"):
        self.cluster = cluster
        self.node_name = node_name
        self._lock = threading.Lock()
        self.rules: Dict[Tuple[str, str], List[dict]] = {}
        self.rules_version = 0
        self._rr: Dict[Tuple[str, str], int] = {}
        self._dirty = threading.Event()
        cluster.watch(self._on_event)
        self.sync_rules()

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind in ("services", "endpoints"):
            self._dirty.set()

    def sync_rules(self) -> int:
        """Full-table rebuild (iptables/proxier.go:667 syncProxyRules).
        The dirty mark clears BEFORE reading state: a commit landing during
        the sweep re-marks and forces another sweep (level-triggered)."""
        self._dirty.clear()
        table: Dict[Tuple[str, str], List[dict]] = {}
        for svc in self.cluster.list("services"):
            key = (svc["namespace"], svc["name"])
            ep = self.cluster.get("endpoints", *key)
            table[key] = list(ep.get("addresses", [])) if ep else []
        with self._lock:
            self.rules = table
            self.rules_version += 1
            return self.rules_version

    def sync_if_dirty(self) -> bool:
        if self._dirty.is_set():
            self.sync_rules()
            return True
        return False

    def route(self, namespace: str, service: str) -> Optional[dict]:
        """Next backend for the service VIP, or None (blackhole — the
        REJECT rule for an endpoint-less service)."""
        key = (namespace, service)
        with self._lock:
            backends = self.rules.get(key) or []
            if not backends:
                return None
            i = self._rr.get(key, 0) % len(backends)
            self._rr[key] = i + 1
            return backends[i]

    def run(self, stop: threading.Event, period: float = 0.05) -> threading.Thread:
        def loop():
            while not stop.is_set():
                self.sync_if_dirty()
                stop.wait(period)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t
