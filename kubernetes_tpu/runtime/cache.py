"""Scheduler cache: assume/confirm/expire over the tensor encoder.

Mirrors the Cache contract (ref pkg/scheduler/internal/cache/cache.go,
interface.go:60-110): optimistic AssumePod immediately charges the pod to its
node so the next cycle sees it; the informer's AddPod confirms it; ForgetPod
rolls it back (bind failure, scheduler.go:416-426); assumed pods expire after
a TTL if never confirmed.  snapshot() is UpdateNodeInfoSnapshot: the encoder
arenas already ARE the incrementally-maintained snapshot, so this is a copy
tagged with the generation counter (interface.go:125-128).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.codec.encoder import SnapshotEncoder
from kubernetes_tpu.codec.schema import ClusterTensors


class SchedulerCache:
    def __init__(self, encoder: Optional[SnapshotEncoder] = None, assume_ttl: float = 30.0):
        self.encoder = encoder or SnapshotEncoder()
        self.assume_ttl = assume_ttl
        self._lock = threading.RLock()
        self._assumed: Dict[Tuple[str, str], Tuple[Pod, float]] = {}

    # ---- nodes ----

    def add_node(self, node: Node) -> None:
        with self._lock:
            self.encoder.add_node(node)

    def add_nodes(self, nodes) -> None:
        """Batched node ingest: one lock acquisition + one columnar encoder
        apply for a whole node list (informer initial list / failover
        re-sync — the cold-start wall; see encoder.add_nodes)."""
        if not nodes:
            return
        with self._lock:
            self.encoder.add_nodes(nodes)

    def update_node(self, node: Node) -> None:
        with self._lock:
            self.encoder.update_node(node)

    def update_nodes(self, nodes) -> None:
        """Batched upsert (informer re-list): new nodes bulk-encode,
        unchanged nodes are skipped, changed nodes re-encode per row."""
        if not nodes:
            return
        with self._lock:
            self.encoder.update_nodes(nodes)

    def remove_node(self, name: str) -> None:
        with self._lock:
            self.encoder.remove_node(name)

    # ---- pods ----

    def assume_pod(self, pod: Pod) -> None:
        """Charge the pod to its node optimistically (cache.go AssumePod)."""
        with self._lock:
            key = (pod.namespace, pod.name)
            self.encoder.add_pod(pod)
            self._assumed[key] = (pod, time.monotonic() + self.assume_ttl)

    def assume_pods(self, pods) -> None:
        """Batched AssumePod: one lock acquisition + one encoder delta
        apply for a whole commit batch (the per-pod loop held/released the
        lock and paid the numpy small-op overhead B times; the batched
        encoder apply is state-equivalent — see encoder.add_pods)."""
        if not pods:
            return
        with self._lock:
            deadline = time.monotonic() + self.assume_ttl
            self.encoder.add_pods(pods)
            for pod in pods:
                self._assumed[(pod.namespace, pod.name)] = (pod, deadline)

    def forget_pod(self, pod: Pod) -> None:
        """Roll back an assumed pod (cache.go ForgetPod)."""
        with self._lock:
            key = (pod.namespace, pod.name)
            if key in self._assumed:
                self._assumed.pop(key)
                self.encoder.remove_pod(pod)

    def add_pod(self, pod: Pod) -> None:
        """Confirm from the watch (cache.go AddPod): replaces any assumed copy."""
        with self._lock:
            key = (pod.namespace, pod.name)
            self._assumed.pop(key, None)
            self.encoder.add_pod(pod)  # add_pod replaces an existing record

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop((pod.namespace, pod.name), None)
            self.encoder.remove_pod(pod)

    def cleanup_expired(self, now: Optional[float] = None) -> int:
        """Expire assumed-but-never-confirmed pods (cache.go cleanupAssumedPods)."""
        now = now if now is not None else time.monotonic()
        n = 0
        with self._lock:
            for key, (pod, deadline) in list(self._assumed.items()):
                if deadline <= now:
                    self._assumed.pop(key)
                    self.encoder.remove_pod(pod)
                    n += 1
        return n

    # ---- snapshot ----

    @property
    def generation(self) -> int:
        return self.encoder.generation

    def snapshot(self) -> Tuple[ClusterTensors, int]:
        with self._lock:
            return self.encoder.snapshot(), self.encoder.generation
