"""Durable storage: the etcd3 semantics analog with real persistence.

Reference: staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go (826 LoC,
revisioned KV over etcd's raft WAL + snapshots) and etcd3/watcher.go:408
(watch-from-revision, ErrCompacted -> client relist).  LocalCluster already
reproduces the revision/CAS/watch-fan-out semantics in memory;
PersistentCluster adds the durability half:

  * every committed write appends one JSON line to a write-ahead log
    (``wal.jsonl``): {"rv": N, "op": create|update|delete, "kind": K,
    "obj"|"key": ...} — the mod_revision-ordered event history;
  * ``snapshot_to_disk()`` writes the full state atomically
    (tmp + rename) and truncates the WAL — etcd's snapshot + compaction;
  * startup replays snapshot then WAL tail, tolerating a torn final line
    (crash mid-append), restoring objects AND the revision counter so
    optimistic CAS (expect_rv) stays valid across restarts;
  * ``watch_from(rv, fn)`` delivers every event after rv then follows live
    — the reflector's resume path; asking below the compacted revision
    raises CompactedError (the HTTP 410 Gone analog that forces a relist).

The event history is retained in memory from the last compaction forward
(exactly the window etcd keeps), so watch_from costs no disk reads.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, List, Optional, Tuple

from kubernetes_tpu.api.serialize import object_to_dict
from kubernetes_tpu.runtime.cluster import ADDED, DELETED, MODIFIED, LocalCluster

SNAPSHOT = "snapshot.json"
WAL = "wal.jsonl"


class CompactedError(Exception):
    """Requested revision is older than the last compaction (etcd
    ErrCompacted / HTTP 410 Gone): the watcher must relist."""


def _decode(kind: str, d: dict):
    """Wire dict -> stored object, via the scheme (api/scheme.py), which
    handles dynamic '<plural>.<group>' kinds as wire dicts and raises
    loudly for unknown builtin kinds (a corrupt WAL entry must fail
    recovery, not load as a dict)."""
    from kubernetes_tpu.api import scheme

    return scheme.decode(kind, d)


class PersistentCluster(LocalCluster):
    """LocalCluster + WAL/snapshot durability.  Drop-in: every LocalCluster
    consumer (apiserver, scheduler wiring, controllers) works unchanged."""

    def __init__(self, data_dir: str, fsync: bool = False) -> None:
        super().__init__()
        self.dir = data_dir
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)
        self._events: List[Tuple[int, str, str, object]] = []  # (rv, ev, kind, obj)
        self._compacted_rv = 0
        self._wal_f = None
        self._replaying = True
        self._load()
        self._replaying = False
        self._wal_f = open(os.path.join(data_dir, WAL), "a")

    # ------------------------------------------------------------- recovery

    def _load(self) -> None:
        snap_path = os.path.join(self.dir, SNAPSHOT)
        if os.path.exists(snap_path):
            with open(snap_path) as f:
                snap = json.load(f)
            self._compacted_rv = self._rv = int(snap["rv"])
            for entry in snap["objects"]:
                kind, rv, d = entry["kind"], int(entry["rv"]), entry["obj"]
                self.register_kind(kind)  # dynamic kinds re-establish first
                obj = _decode(kind, d)
                key = self._key(kind, obj)
                from kubernetes_tpu.runtime.cluster import _Stored

                self._store[kind][key] = _Stored(obj, rv)
        wal_path = os.path.join(self.dir, WAL)
        if os.path.exists(wal_path):
            good_end = 0  # byte offset after the last parseable line
            torn = False
            with open(wal_path, "rb") as f:
                for raw in f:
                    line = raw.strip()
                    if not line:
                        good_end += len(raw)
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        torn = True
                        break  # torn final append (crash mid-write)
                    self._apply_entry(e)
                    good_end += len(raw)
            if torn:
                # Discard the torn tail ON DISK, not just in replay: the
                # file reopens in append mode, so leaving the half-line
                # would glue the NEXT record onto it and destroy the
                # first post-recovery write (e.g. an actuator's rollback
                # uncordon after a crash mid-scale-down).
                with open(wal_path, "r+b") as f:
                    f.truncate(good_end)

    def _apply_entry(self, e: dict) -> None:
        rv, op, kind = int(e["rv"]), e["op"], e["kind"]
        if rv <= self._compacted_rv:
            # stale tail from before the snapshot (crash between snapshot
            # write and WAL truncate): snapshot state already includes every
            # entry at or below its revision — replaying ANY of them
            # (deletes included) would rewind later state
            return
        from kubernetes_tpu.runtime.cluster import _Stored

        self.register_kind(kind)
        # Rebuild the in-memory event history alongside state, so a
        # post-restart watch_from(rv) inside the (compacted_rv, head] window
        # replays the WAL tail instead of silently delivering nothing (the
        # etcd watcher resume contract: deliver or ErrCompacted, never skip).
        if op == "delete":
            ns, name = e["key"]
            prev = self._store[kind].pop((ns, name), None)
            if prev is not None:
                self._events.append((rv, DELETED, kind, prev.obj))
            else:
                # pre-delete payload unavailable (entry references an object
                # the snapshot+WAL never materialized); a faithful replay is
                # impossible, so compact past it: resumes below rv get 410
                # and relist rather than a silently dropped event
                self._compacted_rv = rv
        else:
            obj = _decode(kind, e["obj"])
            self._store[kind][self._key(kind, obj)] = _Stored(obj, rv)
            self._events.append((rv, ADDED if op == "create" else MODIFIED, kind, obj))
        self._rv = max(self._rv, rv)

    # ------------------------------------------------------------ wal hooks

    def _append(self, rv: int, op: str, kind: str, obj=None, key=None) -> None:
        if self._replaying:
            return
        entry = {"rv": rv, "op": op, "kind": kind}
        if op == "delete":
            entry["key"] = list(key)
        else:
            entry["obj"] = object_to_dict(kind, obj)
        self._wal_f.write(json.dumps(entry) + "\n")
        self._wal_f.flush()
        if self.fsync:
            os.fsync(self._wal_f.fileno())
        ev = {"create": ADDED, "update": MODIFIED, "delete": DELETED}[op]
        self._events.append((rv, ev, kind, obj))

    def create(self, kind: str, obj) -> int:
        with self._lock:
            rv = super().create(kind, obj)
            self._append(rv, "create", kind, obj=obj)
            return rv

    def update(self, kind: str, obj, expect_rv: Optional[int] = None) -> int:
        with self._lock:
            key = self._key(kind, obj)
            rv = super().update(kind, obj, expect_rv=expect_rv)
            if key not in self._store[kind]:
                # removing the LAST finalizer from a terminating object
                # completes the deferred deletion (cluster.py update):
                # the durable record must be the delete, not an update a
                # replay would resurrect
                self._append(rv, "delete", kind, obj=obj, key=key)
            else:
                self._append(rv, "update", kind, obj=obj)
            return rv

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            key = (namespace if kind != "nodes" else "", name)
            cur = self._store[kind].get(key)
            super().delete(kind, namespace, name)
            if cur is None:
                return
            after = self._store[kind].get(key)
            if after is not None:
                if after is cur:
                    # retried DELETE of an already-terminating object:
                    # the store changed nothing — logging anything would
                    # stamp a foreign rv into the WAL/event history and
                    # break post-restart CAS
                    return
                # finalizer-gated: the store only MARKED the object
                # terminating — persist that mutation, NOT a delete a
                # replay would apply eagerly
                self._append(self._rv, "update", kind, obj=after.obj)
            else:
                # WAL records the key; the in-memory event history keeps
                # the full object so watch_from replays the same payload
                # live watchers saw
                self._append(self._rv, "delete", kind, obj=cur.obj, key=key)

    # --------------------------------------------------- snapshot / compact

    def snapshot_to_disk(self) -> int:
        """Write full state atomically, truncate the WAL, compact the event
        history.  Returns the snapshot revision."""
        with self._lock:
            objects = []
            for kind in self.kinds:
                for s in self._store[kind].values():
                    objects.append({
                        "kind": kind,
                        "rv": s.rv,
                        "obj": object_to_dict(kind, s.obj),
                    })
            snap = {"rv": self._rv, "objects": objects}
            tmp = os.path.join(self.dir, SNAPSHOT + ".tmp")
            with open(tmp, "w") as f:
                json.dump(snap, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.dir, SNAPSHOT))
            # truncate the WAL: everything <= rv now lives in the snapshot
            self._wal_f.close()
            self._wal_f = open(os.path.join(self.dir, WAL), "w")
            self._compacted_rv = self._rv
            self._events.clear()
            return self._rv

    # ------------------------------------------------------------ watch_from

    def watch_from(self, rv: int, fn: Callable[[str, str, object], None]) -> None:
        """Deliver every event with revision > rv, then follow live (the
        etcd3 watcher resume contract).  rv below the compaction point
        raises CompactedError — relist via watch() instead."""
        with self._lock:
            if rv < self._compacted_rv:
                raise CompactedError(
                    f"revision {rv} compacted (compacted_rv="
                    f"{self._compacted_rv}); relist required"
                )
            for erv, ev, kind, obj in self._events:
                if erv > rv:
                    fn(ev, kind, obj)
            self._watchers.append(fn)

    def close(self) -> None:
        if self._wal_f is not None:
            self._wal_f.close()
            self._wal_f = None
