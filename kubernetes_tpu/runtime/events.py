"""Event recording: the client-go tools/record analog.

The reference emits Kubernetes Events as the user-visible audit trail —
"Scheduled" on success (scheduler.go:268), "FailedScheduling" on fit errors
(:433), "Preempted" per victim (:325) — via an EventRecorder that aggregates
repeats (correlator semantics: same (object, reason, message) increments a
count instead of appending).  This recorder keeps a bounded in-memory log
queryable by object, the standalone analog of the events API.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


@dataclass
class Event:
    kind: str           # involved object kind ("Pod", "Node")
    namespace: str
    name: str
    type: str           # Normal | Warning
    reason: str         # Scheduled | FailedScheduling | Preempted | ...
    message: str
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)
    # tracing join key (utils/trace.py): the scheduling-cycle trace id
    # that produced this event, "" when the emitter carried no context —
    # what makes one decision joinable across cycle span / bind / event
    trace_id: str = ""


class EventRecorder:
    """Thread-safe aggregating recorder (tools/record EventAggregator): a
    repeat of (object, type, reason, message) bumps count/last_timestamp."""

    def __init__(self, max_events: int = 10000):
        self._lock = threading.Lock()
        self._by_key: Dict[Tuple, Event] = {}
        self._order: List[Tuple] = []
        self._max = max_events

    def _record_locked(self, key: Tuple, now: float,
                       trace_id: str = "") -> Event:
        """Aggregate-or-append one event; the caller holds self._lock.
        `key` is (kind, namespace, name, type_, reason, msg) — the Event
        constructor's field order.  trace_id is NOT part of the
        aggregation key (a repeat from a later cycle still aggregates);
        the LATEST non-empty id wins, pointing at the freshest cycle."""
        ev = self._by_key.get(key)
        if ev is not None:
            ev.count += 1
            ev.last_timestamp = now
            if trace_id:
                ev.trace_id = trace_id
            return ev
        ev = Event(*key, trace_id=trace_id)
        self._by_key[key] = ev
        self._order.append(key)
        while len(self._order) > self._max:
            old = self._order.pop(0)
            self._by_key.pop(old, None)
        return ev

    def eventf(
        self,
        kind: str,
        namespace: str,
        name: str,
        type_: str,
        reason: str,
        message_fmt: str,
        *args,
        trace_id: str = "",
    ) -> Event:
        msg = message_fmt % args if args else message_fmt
        with self._lock:
            return self._record_locked(
                (kind, namespace, name, type_, reason, msg), time.time(),
                trace_id=trace_id,
            )

    def eventf_batch(self, entries) -> None:
        """Record many pre-formatted events under ONE lock acquisition (the
        batched commit path emits a whole cycle's audit trail at once).
        entries: iterable of (kind, namespace, name, type_, reason, msg)
        or 7-tuples with a trailing trace_id, msg already formatted.
        Aggregation semantics identical to per-event eventf calls in the
        same order."""
        now = time.time()
        with self._lock:
            for entry in entries:
                entry = tuple(entry)
                trace_id = ""
                if len(entry) == 7:
                    entry, trace_id = entry[:6], entry[6]
                self._record_locked(entry, now, trace_id=trace_id)

    def events(
        self,
        namespace: Optional[str] = None,
        name: Optional[str] = None,
        reason: Optional[str] = None,
    ) -> List[Event]:
        with self._lock:
            out = [self._by_key[k] for k in self._order if k in self._by_key]
        if namespace is not None:
            out = [e for e in out if e.namespace == namespace]
        if name is not None:
            out = [e for e in out if e.name == name]
        if reason is not None:
            out = [e for e in out if e.reason == reason]
        return out
