"""Process-default singleton registry (ISSUE 20 satellite).

Every observability layer grew the same copy-pasted tail: a module
global serving `/debug/*` when nothing was wired explicitly, a
`set_default(obj, replica=0)` install where replica 0 wins the global,
and a `replica_instances()` roll-up for `/debug/replicas` (the ISSUE 14
per-replica discipline).  Six modules reimplemented it — flightrecorder
RECORDER, telemetry HUB, perfobs OBSERVATORY, quality QUALITY, capacity
CAPACITY, ledger LEDGER — each with its own replicas dict and its own
replica-0-wins rule.  `ProcessDefault` is that pattern once: the owning
module keeps its public `get_default`/`set_default`/`replica_instances`
signatures (callers never see this class) and delegates the state here.

The timeline store (runtime/timeline.py) registers through this helper
from day one instead of growing a seventh copy.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional


class ProcessDefault:
    """One process-wide default instance + the per-replica install
    registry behind it.

    - `get()` returns the current default, lazily constructing it via
      `factory` when none was installed (modules whose default may
      legitimately be absent — the autoscaler — pass no factory and get
      None back).
    - `set(obj, replica=0)` registers `obj` under its replica id;
      replica 0 wins the process default (single-scheduler behavior
      unchanged, sibling replicas register alongside for the
      /debug/replicas aggregate).
    - `replicas()` returns {replica id: instance}, sorted.
    """

    def __init__(self, name: str,
                 factory: Optional[Callable[[], Any]] = None) -> None:
        self.name = name
        self._factory = factory
        self._lock = threading.Lock()
        self._default: Any = None
        self._replicas: Dict[int, Any] = {}

    def get(self) -> Any:
        with self._lock:
            if self._default is None and self._factory is not None:
                self._default = self._factory()
            return self._default

    def set(self, obj: Any, replica: int = 0) -> None:
        with self._lock:
            self._replicas[int(replica)] = obj
            if int(replica) == 0:
                self._default = obj

    def replicas(self) -> Dict[int, Any]:
        """{replica id: instance} of every install this process saw."""
        with self._lock:
            return dict(sorted(self._replicas.items()))
