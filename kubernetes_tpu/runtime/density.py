"""Sustained-density harness: the reference's 30k-pod density config
measured against a LIVE control plane.

Reference: test/integration/scheduler_perf/scheduler_test.go:90-96 (the
{nodes: 1000, pods: 30000} config) and :133-178 (per-interval sampling of
scheduled-pod counts against the 30 pods/s enforced minimum and
100 pods/s warning bar, scheduler_test.go:34-38); test/e2e/scalability/density.go runs the same shape with
churn against real masters.

Unlike bench.py's raw-engine burst, this drives the FULL runtime path:
store -> watch wiring -> scheduler cache/queue -> batched engine ->
assume + bind through the Binding callback -> committed pods visible to
the next cycle, with pods arriving in waves and a churn fraction deleted
and replaced while scheduling runs.  Per-interval throughput is bucketed
from bind-commit timestamps, exactly what the reference samples.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.cluster import (
    LocalCluster,
    make_cluster_binder,
    wire_scheduler,
)
from kubernetes_tpu.runtime.queue import PodBackoff, PriorityQueue
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig


def run_sustained_density(
    nodes: int = 1000,
    pods: int = 30000,
    batch: int = 1024,
    interval_s: float = 5.0,
    churn_fraction: float = 0.1,
    engine: str = "speculative",
    wave: Optional[int] = None,
    arrival_rate: Optional[float] = None,
) -> dict:
    """Schedule `pods` pods through a live control plane on `nodes` hollow
    nodes, pods arriving in waves with churn, and return the bench JSON
    shape with per-interval pods/s in detail.intervals.

    arrival_rate (pods/s) switches from deep-queue waves to PACED
    arrival — pod i becomes pending at t0 + i/rate, the reference
    density harness's controlled create rate.  Below the saturation
    throughput this measures the true per-pod queue-add -> bind-commit
    latency distribution (detail.latency_ms), the pair the e2e SLO
    names: p50 = p90 = p99 <= 5s (density.go:56,988-990)."""
    from kubernetes_tpu.api.factory import make_node, make_pod
    from kubernetes_tpu.utils import metrics as m

    if arrival_rate is not None and arrival_rate <= 0:
        raise ValueError(f"arrival_rate must be > 0, got {arrival_rate}")

    zone = "failure-domain.beta.kubernetes.io/zone"
    cluster = LocalCluster()
    cache = SchedulerCache()
    queue = PriorityQueue(backoff=PodBackoff(initial=0.01, max_duration=0.1))
    sched = Scheduler(
        cache=cache, queue=queue, binder=make_cluster_binder(cluster),
        config=SchedulerConfig(
            batch_size=batch, engine=engine, disable_preemption=True),
    )
    wire_scheduler(cluster, sched)

    t_setup0 = time.monotonic()
    for i in range(nodes):
        cluster.add_node(make_node(
            f"node-{i}", cpu="32", mem="256Gi", pods=110,
            labels={zone: f"zone-{i % 8}", "tier": "a" if i % 3 else "b"},
        ))
    setup_s = time.monotonic() - t_setup0

    n_deploy = 20

    def pending_pod(i: int):
        d = i % n_deploy
        return make_pod(
            f"pod-{i}", cpu="100m", mem="256Mi",
            labels={"app": f"dep-{d}"},
            node_selector={"tier": "a"} if d % 4 == 0 else None,
            owner=("ReplicaSet", f"rs-{d}"),
        )

    wave = wave or max(batch * 2, 2048)
    bind_times: list = []
    created = 0
    churned = 0
    next_id = pods  # replacement pods get fresh ids past the base range

    # per-pod queue-add -> bind-commit latency rides the runtime's own
    # e2e histogram (scheduler._record_scheduled); a fresh instance
    # isolates this run's distribution
    lat_hist = m.Histogram("density_e2e", "")
    orig_hist = m.E2E_LATENCY
    m.E2E_LATENCY = lat_hist

    # first cycle = jit compile + first placements: measured separately
    # (the reference's harness likewise excludes master setup from the
    # sampled window); its binds stamp at t0 so every pod still counts
    warm_n = min(wave, pods) if arrival_rate is None else min(batch, pods)
    while created < warm_n:
        cluster.add_pod(pending_pod(created))
        created += 1
    try:
        t_c0 = time.monotonic()
        first_placed = sched.run_once(timeout=0.05)
        compile_s = time.monotonic() - t_c0
        t0 = time.monotonic()
        bind_times.extend([t0] * first_placed)
        if arrival_rate is not None:
            # the compile cycle's queue-wait samples would dominate the
            # distribution: restart the histogram for the PACED window
            lat_hist = m.Histogram("density_e2e", "")
            m.E2E_LATENCY = lat_hist

        while True:
            if arrival_rate is None:
                # deep-queue waves: keep the queue fed (saturation)
                while created < pods and len(queue) < wave:
                    n = min(wave, pods - created)
                    for i in range(created, created + n):
                        cluster.add_pod(pending_pod(i))
                    created += n
            else:
                # paced arrival: pod i due at t0 + (i - warm)/rate
                due = warm_n + int((time.monotonic() - t0) * arrival_rate)
                while created < min(due, pods):
                    cluster.add_pod(pending_pod(created))
                    created += 1
            results_before = len(sched.results)
            placed = sched.run_once(timeout=0.05)
            now = time.monotonic()
            bind_times.extend([now] * placed)
            # churn: delete a slice of scheduled pods and replace them
            # with fresh pending ones (runners.go's delete/create
            # strategies) — bounded by the configured fraction
            if placed and churned < int(pods * churn_fraction):
                kill = min(max(1, placed // 10),
                           int(pods * churn_fraction) - churned)
                # slice by results-list growth, not the placed count:
                # run_once returns PLACED pods while results records every
                # attempt (and gang cycles append in gang order)
                victims = [r.pod for r in sched.results[results_before:]
                           if r.node is not None][:kill]
                for v in victims:
                    cluster.delete("pods", v.namespace, v.name)
                    cluster.add_pod(pending_pod(next_id))
                    next_id += 1
                    churned += 1
            if created >= pods and len(queue) == 0:
                break
            if now - t0 > 3600:  # hard safety stop
                break
        dt = time.monotonic() - t0
    finally:
        m.E2E_LATENCY = orig_hist  # restore the global histogram

    total_bound = len(bind_times)
    rel = np.asarray(bind_times) - t0
    n_buckets = max(1, int(np.ceil(dt / interval_s)))
    hist, _ = np.histogram(rel, bins=n_buckets, range=(0.0, n_buckets * interval_s))
    intervals = [round(float(c) / interval_s, 1) for c in hist]
    # drop the final partial bucket from the min (the run ends mid-bucket)
    sustained = intervals[:-1] if len(intervals) > 1 else intervals
    rate = total_bound / dt if dt > 0 else 0.0
    detail = {
        "nodes": nodes,
        "pods_created": created + churned,
        "pods_bound": total_bound,
        "churned": churned,
        "batch": batch,
        "engine": engine,
        "seconds": round(dt, 3),
        "setup_seconds": round(setup_s, 3),
        "first_cycle_seconds": round(compile_s, 3),
        "interval_s": interval_s,
        "intervals": intervals,
        "min_interval_rate": min(sustained) if sustained else 0.0,
        "unschedulable": sum(
            1 for r in sched.results if r.node is None),
        # queue-add -> bind-commit percentiles from the runtime's own e2e
        # histogram (bucket upper bounds); under paced arrival this is
        # the e2e SLO pair: p50 = p90 = p99 <= 5s (density.go:988-990)
        "latency_ms": {
            p: (round(q * 1000, 1) if np.isfinite(q) else "gt_32s")
            for p, q in (("p50", lat_hist.quantile(0.5)),
                         ("p90", lat_hist.quantile(0.9)),
                         ("p99", lat_hist.quantile(0.99)))
        },
        "arrival_rate": arrival_rate,
    }
    return {
        "metric": "sustained_density_pods_per_sec_1k_nodes",
        "value": round(rate, 1),
        "unit": "pods/s",
        # the reference enforces 30 pods/s and warns under 100
        # (scheduler_test.go:34-38); vs_baseline = ratio to the floor
        "vs_baseline": round(rate / 30.0, 2),
        "vs_warning_bar": round(rate / 100.0, 2),
        "detail": detail,
    }
