"""Chaos harness: fault injection with invariants held across the fault.

The reference shape (test/e2e/chaosmonkey/chaosmonkey.go:17-60): register
tests, run a Disruption concurrently, assert behavior across it.  Here a
`Chaosmonkey` carries (setup, during, teardown) hooks per registered test
and drives them around a disruption callable; `Disruptions` bundles the
faults this cluster model can inject (node lease expiry, random pod kills,
leader kill) so suites compose them.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from kubernetes_tpu.runtime.cluster import LocalCluster


@dataclass
class ChaosTest:
    """chaosmonkey.Test analog: observe before, during, and after."""

    name: str
    setup: Callable[[], None] = lambda: None
    during: Callable[[], None] = lambda: None      # polled while disrupting
    teardown: Callable[[], None] = lambda: None    # asserts recovery


class Chaosmonkey:
    def __init__(self, disruption: Callable[[], None]):
        self.disruption = disruption
        self.tests: List[ChaosTest] = []

    def register(self, test: ChaosTest) -> None:
        self.tests.append(test)

    def do(self, during_interval: float = 0.05) -> None:
        """Setup all -> run the disruption while polling every `during`
        hook -> teardown all.  Exceptions propagate (the test fails)."""
        for t in self.tests:
            t.setup()
        stop = threading.Event()

        def poller():
            while not stop.is_set():
                for t in self.tests:
                    t.during()
                stop.wait(during_interval)

        th = threading.Thread(target=poller, daemon=True)
        th.start()
        try:
            self.disruption()
        finally:
            stop.set()
            th.join(timeout=5.0)
        for t in self.tests:
            t.teardown()


class Disruptions:
    """Fault injectors over the LocalCluster world."""

    def __init__(self, cluster: LocalCluster, rng: Optional[random.Random] = None):
        self.cluster = cluster
        self.rng = rng or random.Random(0)

    def kill_random_pods(self, n: int, namespace: str = "default") -> List[str]:
        """Delete n random pods (the pod-kill monkey); owning controllers
        are expected to replace them."""
        pods = [
            p for p in self.cluster.list("pods")
            if p.namespace == namespace
            and p.status.phase not in ("Succeeded", "Failed")
        ]
        victims = self.rng.sample(pods, min(n, len(pods)))
        for p in victims:
            self.cluster.delete("pods", p.namespace, p.name)
        return [p.name for p in victims]

    def expire_node_lease(self, node_name: str, lifecycle, now: float) -> None:
        """Silence a node's heartbeat and run the monitor at `now` (the
        node-failure monkey); pods there get evicted."""
        lifecycle.monitor(now=now)

    def kill_leader(self, elector) -> None:
        """Stop the current leader WITHOUT releasing its lease (a crash,
        not a graceful shutdown): the standby must wait out the TTL."""
        elector.stop(release=False)
