"""Chaos harness: fault injection with invariants held across the fault.

The reference shape (test/e2e/chaosmonkey/chaosmonkey.go:17-60): register
tests, run a Disruption concurrently, assert behavior across it.  Here a
`Chaosmonkey` carries (setup, during, teardown) hooks per registered test
and drives them around a disruption callable; `Disruptions` bundles the
faults this cluster model can inject — the reference's cluster-layer
monkeys (node lease expiry, random pod kills, leader kill) PLUS the
device-layer faults the reference never had (codec/faults.py FaultInjector:
transient XLA errors, device-lost, slow device, corrupted fetch) — so
suites compose cluster and accelerator failure in one storm.
"""

from __future__ import annotations

import dataclasses
import math
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from kubernetes_tpu.codec import faults as device_faults
from kubernetes_tpu.runtime.cluster import LocalCluster


@dataclass
class ChaosTest:
    """chaosmonkey.Test analog: observe before, during, and after."""

    name: str
    setup: Callable[[], None] = lambda: None
    during: Callable[[], None] = lambda: None      # polled while disrupting
    teardown: Callable[[], None] = lambda: None    # asserts recovery


class Chaosmonkey:
    def __init__(self, disruption: Callable[[], None]):
        self.disruption = disruption
        self.tests: List[ChaosTest] = []

    def register(self, test: ChaosTest) -> None:
        self.tests.append(test)

    def do(self, during_interval: float = 0.05) -> None:
        """Setup all -> run the disruption while polling every `during`
        hook -> teardown all.  Exceptions propagate (the test fails): a
        `during` hook raising on the poller thread stops the polling,
        still runs every teardown, then re-raises the FIRST captured
        exception — previously it died silently with the thread and the
        invariant violation went unreported."""
        for t in self.tests:
            t.setup()
        stop = threading.Event()
        poll_errors: List[BaseException] = []

        def poller():
            while not stop.is_set():
                for t in self.tests:
                    try:
                        t.during()
                    except BaseException as e:  # noqa: BLE001
                        poll_errors.append(e)
                        stop.set()
                        return
                stop.wait(during_interval)

        th = threading.Thread(target=poller, daemon=True)
        th.start()
        try:
            self.disruption()
        finally:
            stop.set()
            th.join(timeout=5.0)
        for t in self.tests:
            t.teardown()
        if poll_errors:
            raise poll_errors[0]


class Disruptions:
    """Fault injectors over the LocalCluster world + the device datapath.

    Determinism contract (ISSUE 18): every random choice any primitive
    makes — victim sampling in kill_random_pods, the drain ORDER when
    rolling_drain is given no explicit node list, the zone pick when
    zone_outage is given none, the device FaultInjector's seed — draws
    from the ONE instance `rng` (`random.Random(seed)`; default seed 0).
    Two Disruptions built with the same seed against the same cluster
    state make identical choices in identical order, so a failing chaos
    scenario reproduces from its logged seed alone.  Primitives take no
    other entropy: wall-clock pacing affects WHEN faults land, never
    WHICH — pass explicit node lists / zones / `now` timestamps to pin
    the remaining degrees of freedom for bit-exact replay."""

    def __init__(self, cluster: LocalCluster, rng: Optional[random.Random] = None):
        self.cluster = cluster
        self.rng = rng or random.Random(0)
        self._fault_remover: Optional[Callable[[], None]] = None
        self._armed_sites: set = set()  # sites THIS Disruptions armed

    def kill_random_pods(self, n: int, namespace: str = "default") -> List[str]:
        """Delete n random pods (the pod-kill monkey); owning controllers
        are expected to replace them."""
        pods = [
            p for p in self.cluster.list("pods")
            if p.namespace == namespace
            and p.status.phase not in ("Succeeded", "Failed")
        ]
        victims = self.rng.sample(pods, min(n, len(pods)))
        for p in victims:
            self.cluster.delete("pods", p.namespace, p.name)
        return [p.name for p in victims]

    def expire_node_lease(self, node_name: str, lifecycle, now: float) -> None:
        """Silence a node's heartbeat and run the monitor at `now` (the
        node-failure monkey); pods there get evicted."""
        lifecycle.monitor(now=now)

    def kill_leader(self, elector) -> None:
        """Stop the current leader WITHOUT releasing its lease (a crash,
        not a graceful shutdown): the standby must wait out the TTL."""
        elector.stop(release=False)

    def overload_storm(
        self,
        make_pod: Callable[[int], object],
        count: int,
        duration_s: float = 0.0,
    ) -> List[str]:
        """Burst create traffic at k× capacity (the overload monkey):
        pour `count` pods into the cluster's write path — as fast as the
        store accepts when duration_s == 0, evenly paced across the
        window otherwise (offered rate = count / duration_s, so a caller
        that measured saturated throughput T drives a 2× storm with
        count = 2*T*duration_s).  make_pod(i) -> Pod; the scheduler's
        bounded queue, shedding, and adaptive batching are the system
        under test.  Returns the created pod names."""
        interval = duration_s / count if duration_s > 0 and count else 0.0
        t0 = time.monotonic()
        names: List[str] = []
        # pace in small chunks against the WALL clock: per-create sleeps
        # would let create cost silently lower the offered rate, and
        # sub-ms sleeps degrade into a GIL-hogging spin that starves the
        # scheduler under test
        chunk = 8
        for i in range(count):
            pod = make_pod(i)
            self.cluster.add_pod(pod)
            names.append(pod.name)
            if interval and (i % chunk) == chunk - 1:
                lag = t0 + (i + 1) * interval - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
        return names

    # --------------------------------------------- cluster-lifecycle chaos
    #
    # ISSUE 18: the correlated cluster-level events the ladder never
    # faced.  All three drive the REAL seams — cordon + the PDB/429
    # eviction path (controllers.try_evict), the NodeLifecycleController
    # taint/eviction monitor, the bounded queue's AIMD pressure — so a
    # scenario exercises mass requeue and recovery end to end, with the
    # invariant checker as the pass/fail oracle.

    def rolling_drain(
        self,
        nodes: Optional[List[str]] = None,
        wave_size: int = 2,
        mode: str = "displace",
        retry_rounds: int = 8,
        retry_after_s: float = 0.05,
    ) -> dict:
        """Rolling node drain (the upgrade monkey): cordon + evict in
        waves of `wave_size` through the PDB-respecting eviction seam
        (controllers.try_evict — the pods/eviction subresource's 429 +
        Retry-After semantics).  `nodes` None drains EVERY node in an
        rng-shuffled order (seeded: same seed, same order); an explicit
        list drains exactly those, in that order.

        A PDB-blocked eviction is retried up to `retry_rounds` times,
        each round paced by the refusal's Retry-After hint (capped at
        `retry_after_s` so tests stay fast) — bounded progress, never a
        spin.  Pods still blocked after the rounds are SKIPPED: the wave
        records them, emits a DrainBlocked Warning event on the node,
        and moves on.  mode "displace" (default) revokes bindings in
        place so the same pods re-enter the queue shed-exempt;
        mode "delete" is the reference kubectl-drain behavior.

        Returns {"order", "waves", "evicted", "blocked_retries",
        "skipped"} — skipped non-empty means PDBs held the line.  The
        wave loop itself lives in controllers.drain_waves (ISSUE 19):
        this monkey and the autoscaler's scale-down actuation share one
        implementation so the two drain paths cannot drift."""
        from kubernetes_tpu.runtime.controllers import drain_waves

        if nodes is None:
            nodes = sorted(n.name for n in self.cluster.list("nodes"))
            self.rng.shuffle(nodes)
        return drain_waves(
            self.cluster,
            nodes,
            wave_size=wave_size,
            mode=mode,
            retry_rounds=retry_rounds,
            retry_after_s=retry_after_s,
            reason="drain",
        )

    def _cordon(self, node_name: str) -> None:
        """kubectl cordon (delegates to controllers.cordon_node)."""
        from kubernetes_tpu.runtime.controllers import cordon_node

        cordon_node(self.cluster, node_name)

    def uncordon(self, node_name: str) -> None:
        """Undo a drain's cordon (the post-upgrade return to service)."""
        from kubernetes_tpu.runtime.controllers import uncordon_node

        uncordon_node(self.cluster, node_name)

    # ------------------------------------------- misbehaving-actuator chaos
    #
    # ISSUE 19: faults aimed at the autoscaler's actuation loop itself —
    # a drain that can never finish, a cloud API that dies mid-batch, a
    # plan that flip-flops every read.  The controller's rollback
    # deadline, partial-batch deregistration, and cooldown hysteresis
    # are the systems under test; the invariant checker's node-lifecycle
    # rule is the oracle.

    STUCK_DRAIN_PDB = "chaos-stuck-drain"

    def stuck_drain(self, namespace: str = "default",
                    name: str = STUCK_DRAIN_PDB) -> str:
        """Make every drain in `namespace` stick forever: install a
        match-all PodDisruptionBudget with zero disruptions allowed, so
        each eviction gets the 429 + Retry-After refusal on every retry
        round.  A scale-down hitting this must roll back (uncordon the
        victims) once its drain deadline expires — pods are stranded by
        policy, not by load.  Returns the PDB name for teardown."""
        from kubernetes_tpu.api.types import ObjectMeta, PodDisruptionBudget

        self.cluster.create(
            "poddisruptionbudgets",
            PodDisruptionBudget(
                metadata=ObjectMeta(name=name, namespace=namespace),
                selector={"matchLabels": {}},  # match-all in namespace
                disruptions_allowed=0,
            ),
        )
        return name

    def clear_stuck_drain(self, namespace: str = "default",
                          name: str = STUCK_DRAIN_PDB) -> None:
        """Lift the stuck-drain veto (drains proceed again)."""
        self.cluster.delete("poddisruptionbudgets", namespace, name)

    def plan_oscillation(self, autoscaler, shape: str = "c2-standard-8",
                         count: int = 2, drain: int = 2) -> Callable[[], dict]:
        """Swap the autoscaler's plan source for one that flip-flops
        between "add `count` × `shape`" and "drain `drain` managed
        nodes" on EVERY read, each with a fresh cycle stamp (so
        staleness can't mask the oscillation).  The cooldown window must
        bound the fleet to ≤ max_direction_changes direction changes per
        window — the flap counter, not the fleet size, should absorb the
        noise.  Returns the installed source (for inspection)."""
        state = {"i": 0}

        def source() -> dict:
            state["i"] += 1
            managed = autoscaler.managed_nodes()
            if state["i"] % 2:
                return {
                    "cycle": state["i"],
                    "backlog_pods": count,
                    "overflow_pods": count,
                    "scale_up": {"shape": shape, "count": count},
                    "drainable": {"count": 0, "nodes": []},
                }
            return {
                "cycle": state["i"],
                "backlog_pods": 0,
                "overflow_pods": 0,
                "scale_up": None,
                "drainable": {
                    "count": min(drain, len(managed)),
                    "nodes": managed[:drain],
                },
            }

        autoscaler.set_plan_source(source)
        return source

    def actuation_fault(self, autoscaler, after: int = 0,
                        count: int = 1) -> None:
        """Arm a mid-batch registration failure (the cloud API's 5xx
        halfway through a scale-up): registrations #after+1..#after+count
        raise, and the controller must deregister the partial batch."""
        autoscaler.arm_register_fault(after=after, count=count)

    def zone_outage(
        self,
        zone: Optional[str] = None,
        lifecycle=None,
        now: Optional[float] = None,
    ) -> dict:
        """Correlated node loss (the zone-failure monkey): every node
        labeled with `zone` (failure-domain zone key) goes silent at
        once — their leases are backdated past the lifecycle grace and
        the monitor runs, so the whole zone is tainted unreachable and
        its pods mass-evicted through the controller's real path.
        `zone` None picks one rng-uniform from the zones present
        (seeded: same seed, same zone).  `lifecycle` defaults to a
        displace-mode NodeLifecycleController so the displaced pods
        re-enter the queue for mass rescheduling; pass your own to keep
        one controller across the scenario.  Returns {"zone", "nodes",
        "evicted"} (the controller's eviction delta)."""
        from kubernetes_tpu.api.factory import ZONE_KEY
        from kubernetes_tpu.runtime.controllers import (
            NodeLifecycleController,
            renew_node_lease,
        )

        if lifecycle is None:
            lifecycle = NodeLifecycleController(
                self.cluster, grace_period=1.0, eviction_mode="displace"
            )
        if zone is None:
            zones = sorted({
                n.labels.get(ZONE_KEY)
                for n in self.cluster.list("nodes")
                if n.labels.get(ZONE_KEY)
            })
            if not zones:
                return {"zone": None, "nodes": [], "evicted": []}
            zone = self.rng.choice(zones)
        now = time.monotonic() if now is None else now
        dead = [
            n.name for n in self.cluster.list("nodes")
            if n.labels.get(ZONE_KEY) == zone
        ]
        stale = now - lifecycle.grace - 1.0
        for name in dead:
            # upsert a STALE lease: covers both a heartbeating node going
            # silent and a never-heartbeated node (no lease = invisible to
            # the monitor, which would mask the outage)
            renew_node_lease(self.cluster, name, now=stale)
        before = len(lifecycle.evictions)
        lifecycle.monitor(now=now)
        return {
            "zone": zone,
            "nodes": dead,
            "evicted": list(lifecycle.evictions[before:]),
        }

    def diurnal_load(
        self,
        make_pod: Callable[[int], object],
        period_s: float,
        amplitude: float,
        base_rate: float,
        cycles: float = 1.0,
        slices_per_period: int = 32,
    ) -> List[str]:
        """Diurnal load swing (the day/night monkey): offered create
        rate r(t) = base_rate * (1 + amplitude * sin(2*pi*t/period_s)),
        poured through the cluster write path for `cycles` periods —
        the swing drives AIMD batch sizing up the peak and back down the
        trough, and gives the capacity planner a breathing backlog.  Pod
        COUNT per slice is a pure function of the arguments (floor-
        accumulated, no rng), so two runs offer identical pod sequences;
        the wall clock only paces delivery, exactly like overload_storm.
        amplitude in [0, 1); base_rate in pods/s.  Returns the created
        pod names."""
        amplitude = max(0.0, min(float(amplitude), 0.999))
        n_slices = max(1, int(slices_per_period * cycles))
        dt = period_s / slices_per_period
        names: List[str] = []
        t0 = time.monotonic()
        offered = 0.0
        created = 0
        for s in range(n_slices):
            t_mid = (s + 0.5) * dt
            rate = base_rate * (
                1.0 + amplitude * math.sin(2.0 * math.pi * t_mid / period_s)
            )
            offered += max(rate, 0.0) * dt
            want = int(math.floor(offered)) - created
            for _ in range(want):
                pod = make_pod(created)
                self.cluster.add_pod(pod)
                names.append(pod.name)
                created += 1
            lag = t0 + (s + 1) * dt - time.monotonic()
            if lag > 0:
                time.sleep(lag)
        return names

    # ------------------------------------------------- device-layer faults
    #
    # The accelerator failure domain (codec/faults.py): each method arms
    # one site of the process-wide FaultInjector, installing a seeded one
    # on first use.  Sites: "dispatch" (engine launch), "fence"
    # (ready-fence / AsyncFetch.result), "fetch" (D2H materialization),
    # "snapshot_update" (H2D delta upload).  The scheduler's classified
    # retry / breaker / CPU-degradation machinery is the system under
    # test; clear_device_faults() ends the storm.

    def _injector(self) -> device_faults.FaultInjector:
        inj = device_faults.current_injector()
        if inj is None:
            inj = device_faults.FaultInjector(seed=self.rng.randrange(2 ** 31))
            self._fault_remover = device_faults.install_injector(inj)
        return inj

    def _arm(self, site: str, **kw) -> device_faults.FaultInjector:
        self._armed_sites.add(site)
        return self._injector().arm(site, **kw)

    def device_transient(
        self, site: str = device_faults.SITE_FENCE,
        count: Optional[int] = 1, p: float = 1.0,
    ) -> device_faults.FaultInjector:
        """Transient XLA runtime errors (UNAVAILABLE-family): the retry/
        backoff monkey."""
        return self._arm(
            site, kind=device_faults.FAULT_TRANSIENT, count=count, p=p
        )

    def device_lost(
        self, site: str = device_faults.SITE_FENCE,
        count: Optional[int] = None,
    ) -> device_faults.FaultInjector:
        """Persistent device-lost: the breaker-tripping monkey (count=None
        keeps the device dead until clear_device_faults)."""
        return self._arm(
            site, kind=device_faults.FAULT_PERSISTENT, count=count
        )

    def slow_device(
        self, site: str = device_faults.SITE_FENCE,
        latency_s: float = 0.05, count: Optional[int] = None,
    ) -> device_faults.FaultInjector:
        """Injected device latency (no error): exercises overlap/backoff
        accounting without touching the breaker."""
        return self._arm(
            site, kind=device_faults.FAULT_SLOW, count=count,
            latency_s=latency_s,
        )

    def shard_lost(
        self,
        device_index: int,
        count: Optional[int] = None,
        sites: tuple = (
            device_faults.SITE_DISPATCH,
            device_faults.SITE_FENCE,
            device_faults.SITE_SCATTER,
        ),
    ) -> device_faults.FaultInjector:
        """ONE mesh device goes dark (the elastic-ladder monkey): every
        dispatch/fence/scatter that involves `device_index` (jax device
        .id) raises a persistent fault ATTRIBUTED to that device, while
        computations on the surviving devices pass — so the scheduler
        under test must shrink the mesh, not demote it wholesale.  The
        half-open probe of exactly that device keeps failing until
        clear_shard_lost()/clear_device_faults() ends the outage
        (count=None keeps the shard dead until then).  Repeated calls
        ACCUMULATE targets — shard_lost(3) then shard_lost(0) keeps both
        devices dark, the double-loss rung of the ladder matrix."""
        inj = self._injector()
        for site in sites:
            self._armed_sites.add(site)
            inj.arm_devices(
                site, {int(device_index)},
                kind=device_faults.FAULT_PERSISTENT, count=count,
            )
        return inj

    def clear_shard_lost(self, device_index: Optional[int] = None) -> None:
        """End a shard_lost outage — for one device (`device_index`) or
        all of them (None).  Only device-targeted arms are touched
        (untargeted arms from other primitives stay), so the scheduler's
        next lost-shard probe succeeds and the mesh climbs back."""
        inj = device_faults.current_injector()
        if inj is None:
            return
        for site in list(self._armed_sites):
            inj.clear_devices(
                site,
                None if device_index is None else {int(device_index)},
            )
            if not inj.is_armed(site):
                self._armed_sites.discard(site)

    def corrupted_fetch(self, count: Optional[int] = 1) -> device_faults.FaultInjector:
        """Structurally-corrupt D2H results: winner rows scrambled out of
        range so the scheduler's fetch validation must catch them."""
        return self._arm(
            device_faults.SITE_FETCH, kind=device_faults.FAULT_CORRUPT,
            count=count,
        )

    def clear_device_faults(self) -> None:
        """Disarm the sites THIS Disruptions armed (a shared process-wide
        injector may carry another owner's arms — leave those alone);
        uninstall the injector only if this Disruptions installed it."""
        inj = device_faults.current_injector()
        if inj is not None:
            for site in self._armed_sites:
                inj.disarm(site)
        self._armed_sites.clear()
        if self._fault_remover is not None:
            self._fault_remover()
            self._fault_remover = None
