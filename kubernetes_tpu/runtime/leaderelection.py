"""Leader election over the blackboard's CAS lease.

Mirrors client-go/tools/leaderelection/leaderelection.go (384 LoC): a
LeaderElectionRecord in a resource lock, acquired/renewed by compare-and-swap
on the store's resourceVersion (the etcd3 txn analog), with
LeaseDuration / RenewDeadline / RetryPeriod semantics.  The scheduler wires
it the way cmd/kube-scheduler/app/server.go:248-262 does: only the elected
instance runs the scheduling loop; on lost leadership it stops, and a
standby's elector acquires the expired lease and starts its own loop —
active/standby replication for the control plane.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubernetes_tpu.runtime.cluster import ConflictError, LocalCluster


@dataclass
class LeaderElectionConfig:
    """leaderelection.go LeaderElectionConfig; durations in seconds
    (defaults mirror component-base LeaderElectionConfiguration: 15/10/2)."""

    lease_name: str = "kube-scheduler"
    namespace: str = "kube-system"
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0


class LeaderElector:
    """Run acquire/renew against the cluster's "leases" kind.

    on_started_leading fires (in the elector thread) when the lease is
    acquired; on_stopped_leading when renewal fails past RenewDeadline or
    stop() is called while leading."""

    def __init__(
        self,
        cluster: LocalCluster,
        identity: str,
        config: Optional[LeaderElectionConfig] = None,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.cluster = cluster
        self.identity = identity
        self.config = config or LeaderElectionConfig()
        self.on_started_leading = on_started_leading or (lambda: None)
        self.on_stopped_leading = on_stopped_leading or (lambda: None)
        self.is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_renew = 0.0

    # ------------------------------------------------------------- lease CAS

    def _try_acquire_or_renew(self) -> bool:
        """tryAcquireOrRenew (leaderelection.go:322-378): create the record,
        or CAS-update it when expired or already ours."""
        cfg = self.config
        now = time.monotonic()
        cur, rv = self.cluster.get_with_rv("leases", cfg.namespace, cfg.lease_name)
        if cur is None:
            rec = {
                "namespace": cfg.namespace,
                "name": cfg.lease_name,
                "holder": self.identity,
                "lease_duration": cfg.lease_duration,
                "acquire_time": now,
                "renew_time": now,
            }
            try:
                self.cluster.create("leases", rec)
                return True
            except ConflictError:
                return False
        held_by_other = cur["holder"] != self.identity
        expired = now >= cur["renew_time"] + cur["lease_duration"]
        if held_by_other and not expired:
            return False
        rec = dict(cur)
        rec["holder"] = self.identity
        rec["lease_duration"] = cfg.lease_duration
        rec["renew_time"] = now
        if held_by_other:
            rec["acquire_time"] = now
        try:
            self.cluster.update("leases", rec, expect_rv=rv)
            return True
        except ConflictError:
            return False

    # ------------------------------------------------------------- run loop

    def _loop(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                self._last_renew = time.monotonic()
                if not self.is_leader:
                    self.is_leader = True
                    self.on_started_leading()
            elif self.is_leader and (
                time.monotonic() - self._last_renew >= cfg.renew_deadline
            ):
                # failed to renew within the deadline: step down
                self.is_leader = False
                self.on_stopped_leading()
            self._stop.wait(cfg.retry_period)
        if self.is_leader:
            self.is_leader = False
            self.on_stopped_leading()

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        """Stop the elector; `release` zeroes the renew time so a standby
        acquires immediately (ReleaseOnCancel semantics)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if release and self.cluster is not None:
            cfg = self.config
            cur, rv = self.cluster.get_with_rv(
                "leases", cfg.namespace, cfg.lease_name
            )
            if cur is not None and cur["holder"] == self.identity:
                rec = dict(cur)
                rec["renew_time"] = -cur["lease_duration"]
                try:
                    self.cluster.update("leases", rec, expect_rv=rv)
                except ConflictError:
                    pass

    def healthy(self) -> bool:
        """Lease-renewal watchdog for /healthz (server.go:196-197)."""
        if not self.is_leader:
            return True
        return time.monotonic() - self._last_renew < self.config.renew_deadline


def run_scheduler_elected(
    cluster: LocalCluster,
    scheduler,
    identity: str,
    config: Optional[LeaderElectionConfig] = None,
) -> LeaderElector:
    """server.go:248-262 wiring: OnStartedLeading runs the scheduling loop in
    a thread; OnStoppedLeading stops it.  Returns the started elector."""
    state = {"thread": None}

    def started():
        t = threading.Thread(target=scheduler.run, daemon=True)
        state["thread"] = t
        t.start()

    def stopped():
        scheduler.stop()
        t = state.get("thread")
        if t is not None:
            t.join(timeout=5.0)

    return LeaderElector(
        cluster, identity, config,
        on_started_leading=started, on_stopped_leading=stopped,
    ).start()
