"""Placement-quality observatory: margins, regret, packing-drift (ISSUE 13).

PR 11's perf observatory measures *speed* and PR 8's telemetry hub
measures *state*; this module measures *decision quality* — the third
axis nothing watched: how confident each placement was, what the
runner-up nodes were, how dense the packing is against a greedy
counterfactual, and whether any of it is drifting.  Three pieces:

  * **In-launch top-k.**  The engines' `quality_topk` static flag
    (ops/select.select_topk; models/batched.py / speculative.py /
    megacycle.py) makes every launch ALSO return, per pod, the K best
    feasible node rows with the WINNER PINNED at column 0, their total
    scores, and the feasible-candidate count — read off the exact
    (mask, score, winner) state the placement used, so placements are
    bit-identical flag-on/off (pinned by tests/test_quality.py, both
    engines, megacycle, single-chip and sharded).  The scheduler
    materializes the pytree at the same commit fence as PR 7's
    attribution, so quality costs one extra D2H copy, never a second
    sync.

  * **Per-decision records.**  `on_cycle` folds each committed cycle
    into margin (top-1 minus runner-up, normalized), feasible-count,
    and — riding PR 7's attribution seam when the sequential engine is
    active — per-plugin score components for the winner vs the
    runner-up.  Every `interval_cycles` committed cycles the cycle's
    pod requests are binpacked first-fit-decreasing into the
    PRE-CYCLE free capacity (models/binpack.py, per-bin capacities) as
    a dispatch-now/materialize-next-interval side launch — the
    telemetry hub's amortization pattern, so the scheduling thread
    never blocks on the counterfactual — and the **regret ratio**
    (nodes the live placements touched / nodes FFD needed) lands in
    `scheduler_placement_regret`.

  * **Packing-drift detection.**  A dual-window EWMA step detector per
    series (margin, utilization_cpu, fragmentation — the latter two
    joined from PR 8's analytics samples): a fast EWMA stepping away
    from the slow one past the threshold fires
    `scheduler_quality_drift_alerts_total{series=}` once (hysteresis:
    re-arms when the windows reconverge) plus a throttled
    `quality_drift` flight-recorder postmortem through the scheduler's
    existing SLO postmortem seam.

Served at `GET /debug/quality` on both servers (?limit= + the shared
4MB cap), summarized on the heartbeat line (`margin=`/`regret=`), and
banked by `bench.py` as the `quality` stage with top-level
`placement_margin_p50` / `regret_ratio` gate rows.  `QUALITY` /
`get_default` / `set_default` follow the flightrecorder RECORDER
pattern.  This is the reward/attribution surface ROADMAP item 4's
learned-scoring loop trains against: margins say how decisive the
current weights are, regret is the packing-quality objective, and the
ledger's top-k blocks make both replayable offline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.utils import metrics as m

# drift-detector series fed by the scheduler's quality hook
DRIFT_SERIES = ("margin", "utilization_cpu", "fragmentation")


def _p50(values) -> float:
    if not values:
        return 0.0
    return float(np.percentile(np.asarray(values, np.float64), 50))


def normalized_margin(top1, top2):
    """THE margin formula — (top-1 − runner-up) / max(1, |top-1|) —
    shared by the live observatory, its ring examples, and the ledger's
    offline replay recompute (runtime/ledger.py), so the three surfaces
    stay bit-comparable by construction."""
    top1 = np.asarray(top1, np.float32)
    top2 = np.asarray(top2, np.float32)
    return (top1 - top2) / np.maximum(np.abs(top1), 1.0)


class StepDetector:
    """Dual-window EWMA step detector for one quality series.

    A fast EWMA tracks the recent level, a slow EWMA the baseline; a
    relative deviation past `threshold` is a step (drift), fired ONCE
    per excursion (hysteresis: the alert re-arms when the deviation
    falls below threshold/2).  `min_samples` suppresses the warm-up
    where both windows are still converging on the workload's level.
    Deviation is |fast - slow| / max(|slow|, floor) — the floor keeps
    near-zero baselines (an idle cluster's fragmentation) from reading
    every wiggle as a 100x step."""

    __slots__ = ("name", "fast_alpha", "slow_alpha", "threshold",
                 "min_samples", "floor", "fast", "slow", "n", "active",
                 "alerts")

    def __init__(self, name: str, fast_alpha: float = 0.3,
                 slow_alpha: float = 0.03, threshold: float = 0.25,
                 min_samples: int = 32, floor: float = 0.05):
        self.name = name
        self.fast_alpha = float(fast_alpha)
        self.slow_alpha = float(slow_alpha)
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.floor = float(floor)
        self.fast: Optional[float] = None
        self.slow: Optional[float] = None
        self.n = 0
        self.active = False
        self.alerts = 0

    def deviation(self) -> float:
        if self.fast is None or self.slow is None:
            return 0.0
        return abs(self.fast - self.slow) / max(abs(self.slow), self.floor)

    def update(self, v: float) -> bool:
        """Fold one sample; True when a drift alert NEWLY fires."""
        v = float(v)
        if self.fast is None:
            self.fast = self.slow = v
        else:
            self.fast += self.fast_alpha * (v - self.fast)
            self.slow += self.slow_alpha * (v - self.slow)
        self.n += 1
        if self.n < self.min_samples:
            return False
        dev = self.deviation()
        if dev > self.threshold and not self.active:
            self.active = True
            self.alerts += 1
            return True
        if dev < self.threshold / 2:
            self.active = False
        return False

    def snapshot(self) -> dict:
        return {
            "fast": round(self.fast, 6) if self.fast is not None else None,
            "slow": round(self.slow, 6) if self.slow is not None else None,
            "deviation": round(self.deviation(), 4),
            "threshold": self.threshold,
            "active": self.active,
            "alerts": self.alerts,
            "samples": self.n,
        }


def _ffd_counterfactual(alloc, used, valid, reqs):
    """The regret side launch: FFD the cycle's PLACED pod requests into
    each node's PRE-CYCLE free capacity (per-bin capacities — a full
    node is a zero row no pod fits; the caller zero-masks pods the live
    run did NOT place, so both sides of the ratio pack the SAME pod set
    — comparing a constraint-filtered live placement against a
    constraint-blind FFD of a bigger set would let regret read < 1).
    FFD order is dominant share of the largest free shape, descending —
    the autoscaler estimator's rule.  Returns (nodes FFD touched, pods
    FFD placed, real pods) as i32 scalars; jitted per (N, B) shape like
    every engine executable."""
    import jax.numpy as jnp

    from kubernetes_tpu.models.binpack import binpack_ffd

    free = jnp.where(
        valid[:, None],
        jnp.maximum(alloc.astype(jnp.float32) - used.astype(jnp.float32),
                    0.0),
        0.0,
    )
    reqs = reqs.astype(jnp.float32)
    cap_ref = jnp.maximum(jnp.max(free, axis=0), 1e-30)
    key = jnp.max(reqs / cap_ref[None, :], axis=-1)
    order = jnp.argsort(-key, stable=True).astype(jnp.int32)
    used_bins, _loads, placed = binpack_ffd(
        reqs, free, max_bins=free.shape[0], order=order
    )
    real = jnp.any(reqs > 0, axis=-1)
    return (
        used_bins,
        jnp.sum((placed & real[order]).astype(jnp.int32)),
        jnp.sum(real.astype(jnp.int32)),
    )


_REGRET_KERNEL = None


def _regret_kernel():
    """ONE jitted counterfactual kernel for the process (re-traced per
    (N, B) shape by jit, like every engine executable — building a
    fresh jit wrapper per sample would recompile every time)."""
    global _REGRET_KERNEL
    if _REGRET_KERNEL is None:
        import jax

        _REGRET_KERNEL = jax.jit(_ffd_counterfactual)
    return _REGRET_KERNEL


class QualityObservatory:
    """Per-scheduler placement-quality aggregation point.

    The scheduling thread calls `on_cycle` once per committed cycle
    (runtime/scheduler.py stamps the call's cost into
    scheduler_quality_seconds_total — the <2% budget perf_smoke pins);
    readers (/debug/quality, heartbeat, bench) come from other threads
    and take the lock only around ring/summary state.  Degraded CPU
    cycles carry no top-k pytree (the adapter has no quality seam) and
    contribute only to the cycle count."""

    def __init__(
        self,
        top_k: int = 3,
        interval_cycles: int = 32,
        ring_capacity: int = 256,
        margin_window: int = 4096,
        postmortem: Optional[Callable[[str, str], None]] = None,
        drift_threshold: float = 0.25,
        drift_min_samples: int = 32,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.top_k = max(0, int(top_k))
        self.interval_cycles = max(1, int(interval_cycles))
        self._postmortem = postmortem
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(ring_capacity)))
        # sliding margin/feasible reservoirs: the p50s the heartbeat,
        # summary, and bench gate read (bounded; O(window log window)
        # only on reads, never on the hot path)
        self._margins: deque = deque(maxlen=max(16, int(margin_window)))
        self._feasible: deque = deque(maxlen=max(16, int(margin_window)))
        self.cycles_total = 0
        self.decisions_total = 0
        self.margin_count = 0
        self._margin_sum = 0.0
        self._cycles_since_regret = self.interval_cycles  # first is due
        # in-flight regret counterfactual: (cycle, device outs, actual
        # facts) — dispatched on one due cycle, materialized on the next
        # (the telemetry hub's amortization pattern)
        self._pending_regret: Optional[Tuple[int, tuple, dict]] = None
        self.regret: Optional[dict] = None  # last materialized sample
        self.regret_samples = 0
        self.detectors: Dict[str, StepDetector] = {
            name: StepDetector(
                name, threshold=drift_threshold,
                min_samples=drift_min_samples,
            )
            for name in DRIFT_SERIES
        }
        self.drift_alerts_total = 0

    # ------------------------------------------------------ hot-path API

    def on_cycle(
        self,
        cycle: int,
        tier: str,
        degraded: bool,
        hosts,
        n_pods: int,
        quality=None,
        reqs=None,
        snapshot: Optional[tuple] = None,
        attrib=None,
        analytics: Optional[dict] = None,
    ) -> None:
        """Fold one committed cycle into the quality model.

        `quality` is the host-materialized ops/select.TopKQuality (None
        on degraded cycles); `reqs` the encoded batch's request matrix
        (f32[B, R] host ref); `snapshot` the cycle's PRE-dispatch host
        snapshot refs (allocatable, requested, valid — immutable by the
        encoder's cow contract); `attrib` PR 7's Attribution when the
        sequential attribution seam is active; `analytics` the
        telemetry hub's last materialized sample dict (drift input)."""
        self.cycles_total += 1
        hosts = np.asarray(hosts)[:n_pods]
        margins = np.empty(0, np.float32)
        sample: dict = {
            "time": time.time(),
            "cycle": int(cycle),
            "tier": tier,
            "degraded": bool(degraded),
            "pods": int(n_pods),
            "placed": int((hosts >= 0).sum()),
        }
        fired: List[str] = []
        if quality is not None and n_pods:
            self.decisions_total += n_pods
            tn = np.asarray(quality.top_nodes)[:n_pods]
            ts = np.asarray(quality.top_scores)[:n_pods]
            feas = np.asarray(quality.feasible)[:n_pods]
            placed = hosts >= 0
            # winner == top-1 is the engines' pinning contract; enforce
            # it here so a future engine change cannot silently report
            # margins about placements it did not make
            if placed.any() and not np.array_equal(
                tn[placed, 0], hosts[placed]
            ):
                raise AssertionError(
                    "quality top-1 diverged from committed winners"
                )
            if tn.shape[1] >= 2:
                two = placed & (tn[:, 1] >= 0)
                if two.any():
                    margins = normalized_margin(ts[two, 0], ts[two, 1])
            fcounts = feas  # 0-feasible rows ARE the unschedulable story
            # vectorized metric folds: a 2048-wide cycle must not pay
            # per-pod locked bisects (the <2% hot-path budget)
            m.PLACEMENT_MARGIN.observe_np(margins, tier=tier)
            m.FEASIBLE_NODES.observe_np(fcounts)
            margin_sum = float(margins.sum()) if margins.size else 0.0
            with self._lock:
                self._margins.extend(margins.tolist())
                self._feasible.extend(fcounts.tolist())
                self.margin_count += int(margins.size)
                self._margin_sum += margin_sum
            sample["margin_mean"] = (
                round(margin_sum / margins.size, 6)
                if margins.size else None
            )
            sample["margin_min"] = (
                round(float(margins.min()), 6) if margins.size else None
            )
            if len(fcounts):
                # cheap exact median (partition, not a full percentile)
                mid = len(fcounts) // 2
                sample["feasible_p50"] = int(
                    np.partition(fcounts, mid)[mid]
                )
            else:
                sample["feasible_p50"] = 0
            sample["examples"] = self._examples(hosts, tn, ts, attrib)
        # ---- drift detectors: per-cycle margin level + the analytics
        # series PR 8 already materializes (no extra device work here)
        if margins.size:
            fired += self._drift(
                "margin", sample["margin_mean"] or 0.0
            )
        if analytics:
            try:
                fired += self._drift(
                    "utilization_cpu",
                    float(analytics["utilization"]["cpu"]["mean"]),
                )
                fired += self._drift(
                    "fragmentation", float(analytics["fragmentation"])
                )
            except (KeyError, TypeError):
                pass
        if fired and self._postmortem is not None:
            detail = "; ".join(
                f"series {name}: fast={self.detectors[name].fast:.4f} "
                f"slow={self.detectors[name].slow:.4f} "
                f"deviation={self.detectors[name].deviation():.2f} > "
                f"{self.detectors[name].threshold}"
                for name in fired
            )
            self._postmortem("quality_drift", detail)
        # ---- amortized regret counterfactual (materialize the previous
        # interval's launch, then dispatch the next — the scheduling
        # thread never waits on the binpack compute).  The cadence
        # counter resets ONLY on an actual dispatch: a due cycle that
        # cannot sample (degraded, no snapshot — e.g. megacycle windows
        # k>0 — or an empty batch) leaves the interval due, so the next
        # eligible cycle samples instead of the cadence silently
        # starving when the due slot keeps landing on ineligible cycles
        self._cycles_since_regret += 1
        if self._cycles_since_regret >= self.interval_cycles:
            self._materialize_regret()
            if (
                quality is not None and reqs is not None
                and snapshot is not None and n_pods
                and self._dispatch_regret(cycle, hosts, reqs, snapshot)
            ):
                self._cycles_since_regret = 0
        with self._lock:
            self._ring.append(sample)

    def _examples(self, hosts, tn, ts, attrib) -> List[dict]:
        """Up to 4 per-decision examples for the ring sample: winner vs
        runner-up, margin, and — when PR 7's attribution rode the same
        launch — the weighted per-plugin score components of both rows.
        Candidates are pre-filtered VECTORIZED: a wide cycle with no
        runner-ups anywhere (nodeSelector-pinned fleets, 1-wide top-k)
        must not pay a per-pod Python walk on the scheduling thread."""
        from kubernetes_tpu.codec.schema import SCORE_COMPONENTS

        if tn.shape[1] < 2:
            return []
        idxs = np.flatnonzero((hosts >= 0) & (tn[:, 1] >= 0))[:4]
        out: List[dict] = []
        for i in idxs:
            ex = {
                "pod_index": int(i),
                "winner": int(tn[i, 0]),
                "runner_up": int(tn[i, 1]),
                "margin": round(
                    float(normalized_margin(ts[i, 0], ts[i, 1])), 6,
                ),
            }
            if attrib is not None:
                # attribution's own top-k is score-ordered, not winner-
                # pinned: match rows by node id before naming components
                atn = np.asarray(attrib.top_nodes)[i]
                comp = np.asarray(attrib.top_components)[i]

                def _components(node):
                    rows = np.flatnonzero(atn == node)
                    if not len(rows):
                        return None
                    c = comp[rows[0]]
                    return {
                        SCORE_COMPONENTS[j]: round(float(c[j]), 4)
                        for j in range(len(SCORE_COMPONENTS))
                        if abs(float(c[j])) > 1e-9
                    }

                w, r = _components(tn[i, 0]), _components(tn[i, 1])
                if w is not None:
                    ex["winner_components"] = w
                if r is not None:
                    ex["runner_up_components"] = r
            out.append(ex)
        return out

    def _drift(self, name: str, value: float) -> List[str]:
        det = self.detectors[name]
        if det.update(value):
            self.drift_alerts_total += 1
            m.QUALITY_DRIFT_ALERTS.inc(series=name)
            return [name]
        return []

    # ------------------------------------------------------------ regret

    def _dispatch_regret(self, cycle: int, hosts, reqs, snapshot) -> bool:
        """Launch the FFD counterfactual for THIS cycle — the pods the
        live run PLACED (unplaced rows zero-masked so both sides pack
        the same set) vs the pre-cycle free capacity; the result
        materializes one interval from now.  Returns whether a launch
        actually dispatched (the cadence counter resets only then)."""
        placed_mask = hosts >= 0
        if not placed_mask.any():
            return False
        alloc, used, valid = snapshot
        reqs = np.asarray(reqs, np.float32)
        masked = np.zeros_like(reqs)
        n = len(hosts)
        masked[:n][placed_mask] = reqs[:n][placed_mask]
        try:
            outs = _regret_kernel()(
                np.asarray(alloc), np.asarray(used),
                np.asarray(valid), masked,
            )
        except Exception:  # noqa: BLE001 — a faulted side launch costs
            # one sample, never the cycle (the telemetry discipline)
            return False
        actual = {
            "nodes": int(len(set(int(h) for h in hosts if h >= 0))),
            "placed": int(placed_mask.sum()),
        }
        with self._lock:  # /debug/quality readers race the swap below
            self._pending_regret = (cycle, tuple(outs), actual)
        return True

    def _materialize_regret(self) -> Optional[dict]:
        with self._lock:  # one consumer wins: the scheduling thread and
            # HTTP readers (debug_payload/finalize) both materialize —
            # an unlocked swap could drop a freshly dispatched sample or
            # double-count one into the regret counters
            pending, self._pending_regret = self._pending_regret, None
        if pending is None:
            return None
        cycle, outs, actual = pending
        try:
            ffd_nodes, ffd_placed, real = (int(np.asarray(x)) for x in outs)
        except Exception:  # noqa: BLE001 — one lost sample, not a cycle
            return None
        ratio = actual["nodes"] / max(ffd_nodes, 1)
        sample = {
            "cycle": cycle,
            "ratio": round(ratio, 4),
            "actual_nodes": actual["nodes"],
            "actual_placed": actual["placed"],
            "ffd_nodes": ffd_nodes,
            "ffd_placed": ffd_placed,
            "pods": real,
        }
        with self._lock:
            self.regret = sample
            self.regret_samples += 1
        m.PLACEMENT_REGRET.set(ratio)
        m.QUALITY_REGRET_SAMPLES.inc()
        return sample

    def finalize(self) -> None:
        """Materialize any in-flight regret launch (bench/test exit —
        the amortization would otherwise leave the last sample in
        flight forever on a drained queue)."""
        self._materialize_regret()

    # ----------------------------------------------------------- readers

    def margin_p50(self) -> float:
        with self._lock:
            vals = list(self._margins)
        return _p50(vals)

    def heartbeat_fields(self) -> Tuple[float, float]:
        """(sliding margin p50, last regret ratio) — the two heartbeat
        satellites (0.0 while nothing was measured yet)."""
        with self._lock:
            regret = self.regret["ratio"] if self.regret else 0.0
        return self.margin_p50(), float(regret)

    def summary(self) -> dict:
        with self._lock:
            margins = list(self._margins)
            feas = list(self._feasible)
            regret = dict(self.regret) if self.regret else None
            cycles = self.cycles_total
            decisions = self.decisions_total
            count = self.margin_count
            msum = self._margin_sum
        return {
            "cycles": cycles,
            "decisions": decisions,
            "top_k": self.top_k,
            "interval_cycles": self.interval_cycles,
            "margin": {
                "p50": round(_p50(margins), 6),
                "mean": round(msum / count, 6) if count else 0.0,
                "count": count,
                "window": len(margins),
            },
            "feasible": {
                "p50": round(_p50(feas), 1),
                "min": min(feas) if feas else 0,
                "window": len(feas),
            },
            "regret": regret,
            "regret_samples": self.regret_samples,
            "drift": {
                name: det.snapshot() for name, det in self.detectors.items()
            },
            "drift_alerts_total": self.drift_alerts_total,
        }

    def debug_payload(self, limit: Optional[int] = None) -> dict:
        """GET /debug/quality body: summary + the newest `limit`
        per-cycle samples (the shared debug_body halves the limit until
        the body fits the 4MB cap, like its siblings)."""
        self._materialize_regret()
        with self._lock:
            samples = list(self._ring)
        if limit is not None and limit >= 0:
            samples = samples[-limit:] if limit else []
        return {"summary": self.summary(), "samples": samples}


# process-wide default: the observatory /debug/quality serves when
# none was wired explicitly; a Scheduler with quality enabled installs
# its own here at construction.  Replica 0 wins the default, siblings
# register alongside (runtime/defaults.py ProcessDefault)
from kubernetes_tpu.runtime.defaults import ProcessDefault  # noqa: E402

_DEFAULT = ProcessDefault("quality", QualityObservatory)


def get_default() -> QualityObservatory:
    return _DEFAULT.get()


def set_default(obs: QualityObservatory, replica: int = 0) -> None:
    _DEFAULT.set(obs, replica)


def replica_instances() -> dict:
    """{replica id: QualityObservatory} of every install this process
    saw."""
    return _DEFAULT.replicas()


def __getattr__(name):  # legacy alias: quality.QUALITY
    if name == "QUALITY":
        return _DEFAULT.get()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
