"""Admission plugins: the write-path policy chain.

Reference: plugin/pkg/admission/* (23 plugins) wired through the generic
admission chain (staging/src/k8s.io/apiserver/pkg/admission/chain.go).  A
plugin here is a callable ``(op, kind, obj_dict) -> obj_dict`` — mutating
plugins return a (possibly modified) dict, validating plugins raise
``AdmissionDenied`` — the exact contract ``APIServer._admit`` runs for
CREATE/UPDATE/DELETE before the registry strategy.

Implemented plugins (each cites its reference):

  NamespaceLifecycle        plugin/pkg/admission/namespace/lifecycle/admission.go
  EventRateLimit            plugin/pkg/admission/eventratelimit/admission.go
  LimitRanger               plugin/pkg/admission/limitranger/admission.go
  PodPreset                 plugin/pkg/admission/podpreset/admission.go
  AlwaysPullImages          plugin/pkg/admission/alwayspullimages/admission.go
  ServiceAccount            plugin/pkg/admission/serviceaccount/admission.go
  PodNodeSelector           plugin/pkg/admission/podnodeselector/admission.go
  Priority                  plugin/pkg/admission/priority/admission.go
  DefaultTolerationSeconds  plugin/pkg/admission/defaulttolerationseconds/admission.go
  TaintNodesByCondition     plugin/pkg/admission/nodetaint/admission.go
  StorageObjectInUseProtection  plugin/pkg/admission/storage/storageobjectinuseprotection/admission.go
  PersistentVolumeClaimResize   plugin/pkg/admission/storage/persistentvolumeclaimresize/admission.go
  PodSecurityPolicy         plugin/pkg/admission/security/podsecuritypolicy/admission.go
  NodeRestriction           plugin/pkg/admission/noderestriction/admission.go
  MutatingAdmissionWebhook / ValidatingAdmissionWebhook  apiserver/pkg/admission/plugin/webhook (webhooks.py)
  ResourceQuota             plugin/pkg/admission/resourcequota/admission.go

Available but (like the reference) not default-enabled:

  AlwaysAdmit / AlwaysDeny  plugin/pkg/admission/{admit,deny}
  NamespaceExists / NamespaceAutoProvision  plugin/pkg/admission/namespace/{exists,autoprovision}
  ExtendedResourceToleration  plugin/pkg/admission/extendedresourcetoleration/admission.go
  PodTolerationRestriction  plugin/pkg/admission/podtolerationrestriction
  SecurityContextDeny       plugin/pkg/admission/securitycontext/scdeny
  LimitPodHardAntiAffinityTopology  plugin/pkg/admission/antiaffinity

``default_admission_chain`` assembles them in the reference's recommended
order (mutating before validating; ResourceQuota last —
kubeapiserver/options/plugins.go).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.resource import Quantity, parse_quantity

# the server's AdmissionDenied lives in server.py; import lazily to avoid a
# cycle (server imports this module for the default chain)


class AdmissionDenied(Exception):
    """Raised by validating plugins; surfaced as HTTP 403 Forbidden."""


# immortal namespaces (lifecycle/admission.go: v1.NamespaceDefault,
# NamespaceSystem, NamespacePublic cannot be deleted)
IMMORTAL_NAMESPACES = ("default", "kube-system", "kube-public")

# built-in priority classes (scheduling.SystemCriticalPriority,
# pkg/apis/scheduling/types.go:29-41)
SYSTEM_PRIORITY_CLASSES = {
    "system-node-critical": 2000001000,
    "system-cluster-critical": 2000000000,
}

NAMESPACED_KINDS = (
    "pods", "services", "replicasets", "replicationcontrollers",
    "deployments", "jobs", "endpoints",
    "poddisruptionbudgets", "limitranges", "resourcequotas",
    "daemonsets", "statefulsets", "cronjobs",
    "horizontalpodautoscalers",
)


def _meta(obj: dict) -> dict:
    return obj.setdefault("metadata", {})


class NamespaceLifecycle:
    """Reject writes into missing/terminating namespaces and deletion of the
    immortal ones (lifecycle/admission.go:94-200)."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind == "namespaces":
            if op == "DELETE" and _meta(obj).get("name") in IMMORTAL_NAMESPACES:
                raise AdmissionDenied(
                    f"namespace {_meta(obj)['name']!r} is immortal"
                )
            return obj
        if op != "CREATE" or kind not in NAMESPACED_KINDS:
            return obj
        ns = _meta(obj).get("namespace", "default")
        if ns in IMMORTAL_NAMESPACES:
            return obj
        rec = self.cluster.get("namespaces", "", ns)
        if rec is None:
            raise AdmissionDenied(f"namespace {ns!r} not found")
        phase = ((rec.get("status") or {}).get("phase")) if isinstance(rec, dict) else ""
        if phase == "Terminating":
            raise AdmissionDenied(f"namespace {ns!r} is terminating")
        return obj


class LimitRanger:
    """Apply LimitRange defaults and enforce min/max on pod containers
    (limitranger/admission.go:287-344 mergePodResourceRequirements +
    PodValidateLimitFunc)."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return obj
        ns = _meta(obj).get("namespace", "default")
        ranges = [
            lr for lr in self.cluster.list("limitranges")
            if lr.get("namespace") == ns
        ]
        if not ranges:
            return obj
        containers = (obj.get("spec") or {}).get("containers") or []
        for lr in ranges:
            for item in (lr.get("spec") or {}).get("limits") or []:
                if item.get("type", "Container") != "Container":
                    continue
                d_req = item.get("defaultRequest") or {}
                d_lim = item.get("default") or {}
                lo = item.get("min") or {}
                hi = item.get("max") or {}
                for c in containers:
                    res = c.setdefault("resources", {})
                    req = res.setdefault("requests", {})
                    lim = res.setdefault("limits", {})
                    for k, v in d_req.items():
                        req.setdefault(k, v)
                    for k, v in d_lim.items():
                        lim.setdefault(k, v)
                        req.setdefault(k, v)  # request defaults to limit
                    for k, v in lo.items():
                        got = req.get(k)
                        if got is not None and parse_quantity(got) < parse_quantity(v):
                            raise AdmissionDenied(
                                f"minimum {k} usage per Container is {v}"
                            )
                    for k, v in hi.items():
                        got = lim.get(k) or req.get(k)
                        if got is not None and parse_quantity(v) < parse_quantity(got):
                            raise AdmissionDenied(
                                f"maximum {k} usage per Container is {v}"
                            )
        return obj


class PodNodeSelector:
    """Merge the namespace's node-selector annotation into the pod; deny on
    conflict (podnodeselector/admission.go:95-150)."""

    ANNOTATION = "scheduler.alpha.kubernetes.io/node-selector"

    def __init__(self, cluster) -> None:
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op != "CREATE":
            return obj
        ns = _meta(obj).get("namespace", "default")
        rec = self.cluster.get("namespaces", "", ns)
        if not isinstance(rec, dict):
            return obj
        ann = ((rec.get("metadata") or {}).get("annotations") or {}).get(
            self.ANNOTATION
        )
        if not ann:
            return obj
        ns_sel: Dict[str, str] = {}
        for part in ann.split(","):
            part = part.strip()
            if part:
                k, _, v = part.partition("=")
                ns_sel[k.strip()] = v.strip()
        spec = obj.setdefault("spec", {})
        sel = spec.setdefault("nodeSelector", {})
        for k, v in ns_sel.items():
            if k in sel and sel[k] != v:
                raise AdmissionDenied(
                    f"pod node label selector conflicts with namespace "
                    f"node label selector for key {k!r}"
                )
            sel[k] = v
        return obj


def _pc_field(pc: dict, field: str, default=None):
    """PriorityClass fields live at the top level on the wire (scheduling/
    v1beta1 has no spec), but accept a spec-nested form too — resolution
    must read wherever validation accepted."""
    if field in pc:
        return pc[field]
    return (pc.get("spec") or {}).get(field, default)


class Priority:
    """Resolve priorityClassName -> spec.priority
    (priority/admission.go:106-179): unknown class is denied; empty falls
    back to the globalDefault class or 0."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind == "priorityclasses" and op in ("CREATE", "UPDATE"):
            if _pc_field(obj, "value") is None:
                raise AdmissionDenied("priority class needs a value")
            return obj
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return obj
        spec = obj.setdefault("spec", {})
        if op == "UPDATE":
            # spec.priority is immutable after CREATE (ValidatePodUpdate):
            # without this, a client could PUT an arbitrary priority and
            # bypass the CREATE-time self-assignment denial below.
            meta = obj.get("metadata") or {}
            ns = obj.get("namespace", meta.get("namespace", "default"))
            pod_name = obj.get("name", meta.get("name", ""))
            cur = self.cluster.get("pods", ns, pod_name)
            cur_pri = getattr(getattr(cur, "spec", None), "priority", None)
            if cur is None or cur_pri is None:
                return obj
            provided = spec.get("priority")
            if provided is not None:
                try:
                    provided = int(provided)
                except (TypeError, ValueError):
                    raise AdmissionDenied(
                        f"spec.priority must be an integer, got {provided!r}"
                    )
                if provided != int(cur_pri):
                    raise AdmissionDenied(
                        "pod updates may not change spec.priority "
                        f"(have {cur_pri}, got {provided})"
                    )
            spec["priority"] = int(cur_pri)
            return obj
        name = spec.get("priorityClassName", "")
        provided = spec.get("priority")
        if name:
            if name in SYSTEM_PRIORITY_CLASSES:
                resolved = SYSTEM_PRIORITY_CLASSES[name]
            else:
                pc = self.cluster.get("priorityclasses", "", name)
                if pc is None:
                    raise AdmissionDenied(
                        f"no PriorityClass with name {name} was found"
                    )
                resolved = int(_pc_field(pc, "value", 0))
        else:
            resolved = 0
            for pc in self.cluster.list("priorityclasses"):
                if _pc_field(pc, "globalDefault"):
                    resolved = int(_pc_field(pc, "value", 0))
                    break
        # A client-supplied priority must match the computed value — pods
        # may not self-assign priorities (priority/admission.go:216).
        if provided is not None:
            try:
                provided = int(provided)
            except (TypeError, ValueError):
                raise AdmissionDenied(
                    f"spec.priority must be an integer, got {provided!r}"
                )
        if provided is not None and provided != resolved:
            raise AdmissionDenied(
                "the integer value of priority must not be provided in pod "
                f"spec; priority admission controller computed {resolved} "
                f"from the given PriorityClass name, got {provided}"
            )
        spec["priority"] = resolved
        return obj


class DefaultTolerationSeconds:
    """Add the 300s not-ready/unreachable NoExecute tolerations unless the
    pod already tolerates those taints
    (defaulttolerationseconds/admission.go:78-119)."""

    NOT_READY = "node.kubernetes.io/not-ready"
    UNREACHABLE = "node.kubernetes.io/unreachable"
    SECONDS = 300

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return obj
        spec = obj.setdefault("spec", {})
        tols = spec.setdefault("tolerations", [])
        have = {t.get("key") for t in tols if isinstance(t, dict)}
        wildcard = any(
            isinstance(t, dict) and not t.get("key")
            and t.get("operator") == "Exists" for t in tols
        )
        for key in (self.NOT_READY, self.UNREACHABLE):
            if wildcard or key in have:
                continue
            tols.append({
                "key": key,
                "operator": "Exists",
                "effect": "NoExecute",
                "tolerationSeconds": self.SECONDS,
            })
        return obj


class TaintNodesByCondition:
    """Taint fresh nodes not-ready:NoSchedule so nothing lands before the
    node reports Ready (nodetaint/admission.go:69-94; the nodelifecycle
    controller removes it on the first lease heartbeat).

    A registration that already carries Ready=True is not tainted: in this
    framework an API-created node with a Ready condition IS the ready
    signal (hollow kubelets register without conditions and heartbeat
    leases; plain API nodes have no kubelet to shed the taint for them)."""

    NOT_READY = "node.kubernetes.io/not-ready"

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "nodes" or op != "CREATE":
            return obj
        for cond in (obj.get("status") or {}).get("conditions") or []:
            if cond.get("type") == "Ready" and cond.get("status") == "True":
                return obj
        spec = obj.setdefault("spec", {})
        taints = spec.setdefault("taints", [])
        if not any(
            t.get("key") == self.NOT_READY and t.get("effect") == "NoSchedule"
            for t in taints if isinstance(t, dict)
        ):
            taints.append({"key": self.NOT_READY, "effect": "NoSchedule"})
        return obj


# quota resource names -> how to charge a pod for them
# (resourcequota/evaluator/core/pods.go podUsageHelper)
_QUOTA_POD_RESOURCES = (
    "pods", "cpu", "memory", "requests.cpu", "requests.memory",
    "limits.cpu", "limits.memory",
)


def _pod_charge(spec: dict, resource: str) -> Quantity:
    """How much a pod wire spec charges against a quota resource."""
    if resource == "pods":
        return parse_quantity(1)
    bucket, _, plain = resource.partition(".")
    if not plain:  # bare "cpu"/"memory" count requests (pods.go:282-297)
        bucket, plain = "requests", resource
    total = parse_quantity(0)
    for c in spec.get("containers") or []:
        res = (c.get("resources") or {}).get(bucket) or {}
        if plain in res:
            total = total + parse_quantity(res[plain])
    return total


def _pod_object_charge(pod, resource: str) -> Quantity:
    """_pod_charge for a decoded Pod object (no wire-dict rebuild)."""
    if resource == "pods":
        return parse_quantity(1)
    bucket, _, plain = resource.partition(".")
    if not plain:
        bucket, plain = "requests", resource
    total = parse_quantity(0)
    for c in pod.spec.containers:
        d = c.requests if bucket == "requests" else c.limits
        if plain in d:
            total = total + d[plain]
    return total


def quota_usage(cluster, ns: str, resources) -> Dict[str, Quantity]:
    """Live usage of the tracked quota resources: ONE pass over the pod
    list, charging every resource at once (non-terminal pods only — the
    quota controller's replenishment semantics)."""
    totals = {r: parse_quantity(0) for r in resources}
    for p in cluster.list("pods"):
        if p.namespace != ns or p.status.phase in ("Succeeded", "Failed"):
            continue
        for r in resources:
            totals[r] = totals[r] + _pod_object_charge(p, r)
    return totals


class ResourceQuota:
    """Enforce ResourceQuota hard limits on pod creation
    (resourcequota/controller.go checkRequest): live usage is recomputed
    from non-terminal pods in the namespace, matching the quota
    controller's replenishment semantics."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op != "CREATE":
            return obj
        ns = _meta(obj).get("namespace", "default")
        quotas = [
            q for q in self.cluster.list("resourcequotas")
            if q.get("namespace") == ns
        ]
        if not quotas:
            return obj
        spec = obj.get("spec") or {}
        tracked = {
            rname
            for q in quotas
            for rname in ((q.get("spec") or {}).get("hard") or {})
            if rname in _QUOTA_POD_RESOURCES
        }
        used = quota_usage(self.cluster, ns, tracked)
        for q in quotas:
            hard = (q.get("spec") or {}).get("hard") or {}
            for rname, cap in hard.items():
                if rname not in _QUOTA_POD_RESOURCES:
                    continue
                want = _pod_charge(spec, rname)
                if float(want) == 0 and rname != "pods":
                    # quota-limited resources REQUIRE a request
                    # (checkRequest: "must specify <r>")
                    raise AdmissionDenied(
                        f"failed quota: {q.get('name')}: must specify {rname}"
                    )
                if parse_quantity(cap) < used[rname] + want:
                    raise AdmissionDenied(
                        f"exceeded quota: {q.get('name')}, requested: "
                        f"{rname}={want}, used: {rname}={used[rname]}, "
                        f"limited: {rname}={cap}"
                    )
        return obj


class NodeRestriction:
    """Scope a kubelet identity to ITS OWN objects
    (plugin/pkg/admission/noderestriction/admission.go): a requester named
    ``system:node:<name>`` in group ``system:nodes`` may only

      * create/update the Node object named <name> (and its status);
      * create MIRROR pods bound to <name> (the static-pod surfacing
        path, admission.go:178-210) — never regular pods;
      * update/delete pods already bound to <name>;
      * create/update the node lease named <name>.

    RBAC grants the system:nodes GROUP broad verbs; this plugin narrows
    them per-object, which roles cannot express.  ``user_getter`` reads
    the authenticated identity the server parked for the request
    (APIServer.request_user)."""

    MIRROR_ANNOTATION = "kubernetes.io/config.mirror"

    def __init__(self, cluster, user_getter: Callable):
        self.cluster = cluster
        self.user_getter = user_getter

    def _node_name(self) -> Optional[str]:
        user = self.user_getter()
        if user is None or not user.name.startswith("system:node:"):
            return None
        if "system:nodes" not in getattr(user, "groups", ()):
            return None
        return user.name[len("system:node:"):]

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        me = self._node_name()
        if me is None:
            return obj  # not a kubelet identity: plugin doesn't apply
        meta = obj.get("metadata") or {}
        name = obj.get("name") or meta.get("name", "")
        ns = obj.get("namespace") or meta.get("namespace", "default")
        if kind == "nodes":
            if name != me:
                raise AdmissionDenied(
                    f"node {me!r} is not allowed to modify node {name!r}")
            # label self-escalation guard (admission.go getModifiedLabels
            # / NodeRestriction label plumbing, 1.16+): a kubelet may not
            # set or change labels in the node-restriction.kubernetes.io/
            # namespace on its own Node — those are the operator-asserted
            # isolation labels workloads select on
            RESTRICTED = "node-restriction.kubernetes.io/"
            holder = obj.get("metadata") if "metadata" in obj else obj
            # distinguish "labels map present" (a label write — possibly
            # EMPTY, which would strip everything) from "no labels key"
            # (a status-only update body): only the former is guarded
            labels_provided = isinstance(holder, dict) and "labels" in holder
            want = (holder.get("labels") or {}) if labels_provided else {}
            cur = self.cluster.get("nodes", "", me)
            have = dict(cur.metadata.labels) if cur is not None else {}
            for k, v in want.items():
                if RESTRICTED in k and have.get(k) != v:
                    raise AdmissionDenied(
                        f"node {me!r} may not set restricted label {k!r}")
            if labels_provided:
                for k in have:
                    if RESTRICTED in k and k not in want:
                        raise AdmissionDenied(
                            f"node {me!r} may not remove restricted "
                            f"label {k!r}")
            return obj
        if kind == "leases":
            # confined to kube-node-lease (admission.go admitLease): a
            # kubelet named like another component must not be able to
            # hijack that component's leader-election lease elsewhere
            if ns != "kube-node-lease" or name != me:
                raise AdmissionDenied(
                    f"node {me!r} may only modify its own lease in "
                    f"kube-node-lease (got {ns}/{name})")
            return obj
        if kind == "pods":
            if op == "CREATE":
                anns = (meta.get("annotations") or {})
                if self.MIRROR_ANNOTATION not in anns:
                    raise AdmissionDenied(
                        f"node {me!r} may only create mirror pods")
                bound = (obj.get("spec") or {}).get("nodeName", "")
                if bound != me:
                    raise AdmissionDenied(
                        f"node {me!r} may only create mirror pods bound "
                        f"to itself (got {bound!r})")
                return obj
            # UPDATE (status) / DELETE: only pods bound to this node
            cur = self.cluster.get("pods", ns, name)
            bound = cur.spec.node_name if cur is not None else ""
            if bound != me:
                raise AdmissionDenied(
                    f"node {me!r} may only {op.lower()} pods bound to "
                    f"itself")
            return obj
        # other kinds: RBAC already scopes what system:nodes can touch
        return obj


class ServiceAccount:
    """Inject the default ServiceAccount and require the referenced one
    to exist (plugin/pkg/admission/serviceaccount/admission.go: empty
    spec.serviceAccountName becomes "default"; a pod referencing a
    missing SA is rejected — the SA controller creates 'default' per
    namespace, so steady-state pods always pass)."""

    def __init__(self, cluster):
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return obj
        spec = obj.setdefault("spec", {})
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace", "default")
        if op == "UPDATE":
            # the field is immutable after CREATE (admission.go rejects
            # spec changes via ValidatePodUpdate); an omitted field on a
            # read-modify-write body keeps the stored value rather than
            # silently clearing it
            cur = self.cluster.get("pods", ns, meta.get("name", ""))
            cur_sa = (cur.spec.service_account_name
                      if cur is not None else "")
            provided = spec.get("serviceAccountName")
            if provided and cur_sa and provided != cur_sa:
                raise AdmissionDenied(
                    "pod updates may not change serviceAccountName "
                    f"(have {cur_sa!r}, got {provided!r})")
            if cur_sa:
                spec["serviceAccountName"] = cur_sa
            return obj
        if not spec.get("serviceAccountName"):
            spec["serviceAccountName"] = "default"
        sa = spec["serviceAccountName"]
        if self.cluster.get("serviceaccounts", ns, sa) is None:
            raise AdmissionDenied(
                f'service account {ns}/{sa} was not found, retry after '
                f'the service account is created')
        return obj


class AlwaysAdmit:
    """plugin/pkg/admission/admit: the no-op plugin (deprecated in the
    reference, kept for chain-configuration parity)."""

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        return obj


class AlwaysDeny:
    """plugin/pkg/admission/deny: reject everything (testing plugin)."""

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        raise AdmissionDenied("admission plugin AlwaysDeny denied the "
                              "request")


class NamespaceExists:
    """plugin/pkg/admission/namespace/exists: reject namespaced writes
    into namespaces that do not exist (subsumed by NamespaceLifecycle in
    the default chain; offered for configuration parity)."""

    def __init__(self, cluster):
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if op != "CREATE" or kind not in NAMESPACED_KINDS:
            return obj
        ns = _meta(obj).get("namespace", "default")
        if ns in IMMORTAL_NAMESPACES:
            return obj
        if self.cluster.get("namespaces", "", ns) is None:
            raise AdmissionDenied(f"namespace {ns!r} does not exist")
        return obj


class NamespaceAutoProvision:
    """plugin/pkg/admission/namespace/autoprovision: create the target
    namespace on demand instead of rejecting the write."""

    def __init__(self, cluster):
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if op != "CREATE" or kind not in NAMESPACED_KINDS:
            return obj
        ns = _meta(obj).get("namespace", "default")
        if self.cluster.get("namespaces", "", ns) is None:
            from kubernetes_tpu.runtime.cluster import ConflictError

            try:
                self.cluster.create("namespaces", {
                    "namespace": "", "name": ns,
                    "kind": "Namespace", "apiVersion": "v1",
                    "metadata": {"name": ns},
                })
            except ConflictError:
                pass  # raced another provisioner: fine
        return obj


class ExtendedResourceToleration:
    """plugin/pkg/admission/extendedresourcetoleration/admission.go: a
    pod requesting extended resources (device plugins) gets a toleration
    for each such resource's taint key, so dedicated device nodes can be
    tainted with their resource name and only consumers land there."""

    @staticmethod
    def _extended(name: str) -> bool:
        # not a native resource: has a domain prefix that isn't
        # kubernetes.io (helpers.IsExtendedResourceName)
        return "/" in name and not name.startswith("kubernetes.io/")

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op != "CREATE":
            return obj
        spec = obj.get("spec") or {}
        wanted = set()
        for c in spec.get("containers") or []:
            for res in ((c.get("resources") or {}).get("requests")
                        or {}):
                if self._extended(res):
                    wanted.add(res)
        if not wanted:
            return obj
        tols = spec.setdefault("tolerations", [])
        have = {(t.get("key"), t.get("operator")) for t in tols}
        for res in sorted(wanted):
            if (res, "Exists") not in have:
                tols.append({"key": res, "operator": "Exists",
                             "effect": "NoSchedule"})
        return obj


class PodTolerationRestriction:
    """plugin/pkg/admission/podtolerationrestriction: merge the
    namespace's default tolerations into the pod and reject tolerations
    outside the namespace whitelist (both carried as namespace
    annotations, like PodNodeSelector)."""

    DEFAULT_ANN = "scheduler.alpha.kubernetes.io/defaultTolerations"
    WHITELIST_ANN = "scheduler.alpha.kubernetes.io/tolerationsWhitelist"

    def __init__(self, cluster):
        self.cluster = cluster

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        import json as _json

        if kind != "pods" or op != "CREATE":
            return obj
        ns = _meta(obj).get("namespace", "default")
        rec = self.cluster.get("namespaces", "", ns)
        if not isinstance(rec, dict):
            return obj
        anns = ((rec.get("metadata") or {}).get("annotations")
                or rec.get("annotations") or {})
        spec = obj.setdefault("spec", {})
        if anns.get(self.DEFAULT_ANN):
            try:
                defaults = _json.loads(anns[self.DEFAULT_ANN])
            except ValueError:
                defaults = []
            tols = spec.setdefault("tolerations", [])
            have = {(t.get("key"), t.get("effect")) for t in tols}
            for t in defaults:
                if (t.get("key"), t.get("effect")) not in have:
                    tols.append(t)
        if anns.get(self.WHITELIST_ANN):
            try:
                allowed = _json.loads(anns[self.WHITELIST_ANN])
            except ValueError:
                allowed = []
            keys = {t.get("key") for t in allowed}
            for t in spec.get("tolerations") or []:
                if t.get("key") not in keys:
                    raise AdmissionDenied(
                        f"pod toleration {t.get('key')!r} is not in the "
                        f"namespace whitelist")
        return obj


class SecurityContextDeny:
    """plugin/pkg/admission/securitycontext/scdeny: reject pods setting
    the identity-altering securityContext fields (the pre-PSP hammer)."""

    POD_FIELDS = ("supplementalGroups", "fsGroup")
    CONTAINER_FIELDS = ("runAsUser", "runAsGroup", "seLinuxOptions")

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return obj
        spec = obj.get("spec") or {}
        sc = spec.get("securityContext") or {}
        for f in self.POD_FIELDS + self.CONTAINER_FIELDS:
            if sc.get(f) is not None:
                raise AdmissionDenied(
                    f"SecurityContextDeny: pod securityContext.{f} is "
                    "forbidden")
        for c in spec.get("containers") or []:
            csc = c.get("securityContext") or {}
            for f in self.CONTAINER_FIELDS:
                if csc.get(f) is not None:
                    raise AdmissionDenied(
                        f"SecurityContextDeny: container "
                        f"securityContext.{f} is forbidden")
        return obj


class LimitPodHardAntiAffinityTopology:
    """plugin/pkg/admission/antiaffinity: required pod anti-affinity may
    only use the kubernetes.io/hostname topology key (unbounded custom
    topologies make scheduling O(zones) adversarial)."""

    HOSTNAME = "kubernetes.io/hostname"

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return obj
        aff = ((obj.get("spec") or {}).get("affinity") or {})
        anti = aff.get("podAntiAffinity") or {}
        for term in anti.get(
                "requiredDuringSchedulingIgnoredDuringExecution") or []:
            key = term.get("topologyKey", "")
            if key and key != self.HOSTNAME:
                raise AdmissionDenied(
                    "pod with required anti-affinity topologyKey "
                    f"{key!r} is limited to {self.HOSTNAME}")
        return obj


class PodPreset:
    """Inject env/volumes/volumeMounts from matching PodPreset objects
    (plugin/pkg/admission/podpreset/admission.go): presets select pods by
    label in the same namespace; a merge CONFLICT (same env name or
    volume name, different value) skips injection for that pod rather
    than failing the create; applied presets are recorded in the
    podpreset.admission.kubernetes.io/podpreset-<name> annotation."""

    ANNOTATION_PREFIX = "podpreset.admission.kubernetes.io"

    def __init__(self, cluster):
        self.cluster = cluster

    def _matching(self, ns: str, labels: dict) -> List[dict]:
        from kubernetes_tpu.api.labels import selector_from_label_selector

        if not self.cluster.has_kind("podpresets"):
            return []
        out = []
        for pp in self.cluster.list("podpresets"):
            if not isinstance(pp, dict) or pp.get("namespace") != ns:
                continue
            sel = selector_from_label_selector(
                (pp.get("spec") or {}).get("selector") or {})
            if sel is None or sel.matches(labels or {}):
                out.append(pp)
        return sorted(out, key=lambda p: p.get("name", ""))

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op != "CREATE":
            return obj
        meta = _meta(obj)
        presets = self._matching(
            meta.get("namespace", "default"), meta.get("labels") or {})
        if not presets:
            return obj
        spec = obj.setdefault("spec", {})
        # merge with conflict detection across ALL presets first
        # (safeToApplyPodPresetsOnPod): any conflict -> no injection
        env_merged: Dict[str, dict] = {}
        vol_merged: Dict[str, dict] = {}
        for pp in presets:
            ps = pp.get("spec") or {}
            for e in ps.get("env") or []:
                cur = env_merged.get(e.get("name"))
                if cur is not None and cur != e:
                    return obj  # conflict: skip injection (klog-warn path)
                env_merged[e.get("name")] = e
            for v in ps.get("volumes") or []:
                cur = vol_merged.get(v.get("name"))
                if cur is not None and cur != v:
                    return obj
                vol_merged[v.get("name")] = v
        # container-level conflict PRECHECK before any mutation
        # (safeToApplyPodPresetsOnPod): a conflict in container N must
        # not leave containers 0..N-1 partially injected with mounts
        # referencing volumes that were never added
        for c in spec.get("containers") or []:
            have = {e.get("name"): e for e in c.get("env") or []}
            for name, e in env_merged.items():
                if name in have and have[name] != e:
                    return obj  # conflict: skip injection entirely
        for c in spec.get("containers") or []:
            have = {e.get("name"): e for e in c.get("env") or []}
            c["env"] = list((c.get("env") or [])) + [
                e for n, e in env_merged.items() if n not in have]
            mounts = {m.get("name") for m in c.get("volumeMounts") or []}
            for pp in presets:
                for m in (pp.get("spec") or {}).get("volumeMounts") or []:
                    if m.get("name") not in mounts:
                        c.setdefault("volumeMounts", []).append(m)
                        mounts.add(m.get("name"))
        have_vols = {v.get("name") for v in spec.get("volumes") or []}
        for name, v in vol_merged.items():
            if name not in have_vols:
                spec.setdefault("volumes", []).append(v)
        anns = meta.setdefault("annotations", {})
        for pp in presets:
            anns[f"{self.ANNOTATION_PREFIX}/podpreset-{pp.get('name')}"] = \
                str(pp.get("resourceVersion", "0"))
        return obj


class AlwaysPullImages:
    """Force every container's imagePullPolicy to Always
    (plugin/pkg/admission/alwayspullimages/admission.go): in a multi-
    tenant cluster a pod must not ride a node-cached private image it
    could not itself pull."""

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return obj
        spec = obj.get("spec") or {}
        for key in ("containers", "initContainers"):
            for c in spec.get(key) or []:
                c["imagePullPolicy"] = "Always"
        return obj


class EventRateLimit:
    """Token-bucket cap on Event creates
    (plugin/pkg/admission/eventratelimit/admission.go, server +
    namespace scopes): a crash-looping fleet must not write-storm the
    store.  Over-limit creates are REJECTED (429 semantics surfaced as
    the admission denial)."""

    # bounded per-namespace cache (the reference uses an LRU of the same
    # size, eventratelimit defaults cacheSize=4096)
    MAX_NS_BUCKETS = 4096

    def __init__(self, qps: float = 50.0, burst: int = 100,
                 namespace_qps: float = 10.0, namespace_burst: int = 50,
                 now: Optional[Callable[[], float]] = None):
        import threading as _threading
        import time as _time
        from collections import OrderedDict

        self._now = now or _time.monotonic
        self._server = self._bucket(qps, burst)
        self._ns_cfg = (namespace_qps, namespace_burst)
        self._ns: "OrderedDict[str, dict]" = OrderedDict()
        # this plugin runs in the pre-write-lock admission phase, so
        # concurrent requests reach the read-modify-write in _take
        # simultaneously — one small lock keeps the cap exact
        self._lock = _threading.Lock()

    def _bucket(self, qps: float, burst: int) -> dict:
        return {"qps": qps, "burst": burst, "tokens": float(burst),
                "t": self._now()}

    @staticmethod
    def _take(b: dict, now: float) -> bool:
        b["tokens"] = min(b["burst"], b["tokens"] + (now - b["t"]) * b["qps"])
        b["t"] = now
        if b["tokens"] >= 1.0:
            b["tokens"] -= 1.0
            return True
        return False

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "events" or op != "CREATE":
            return obj
        now = self._now()
        ns = (obj.get("metadata") or {}).get("namespace") \
            or obj.get("namespace", "default")
        with self._lock:
            nsb = self._ns.get(ns)
            if nsb is None:
                nsb = self._ns[ns] = self._bucket(*self._ns_cfg)
                if len(self._ns) > self.MAX_NS_BUCKETS:
                    self._ns.popitem(last=False)  # evict least-recent
            else:
                self._ns.move_to_end(ns)
            if not self._take(self._server, now) or not self._take(nsb, now):
                raise AdmissionDenied(
                    f"event rate limit exceeded (namespace {ns!r})")
        return obj


class StorageObjectInUseProtection:
    """Stamp the protection finalizers at create time
    (plugin/pkg/admission/storage/storageobjectinuseprotection/
    admission.go) — the admission half of the pvc/pv-protection
    controllers (runtime/protection.py lifts them when safe)."""

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if op != "CREATE":
            return obj
        fin = {"persistentvolumeclaims": "kubernetes.io/pvc-protection",
               "persistentvolumes": "kubernetes.io/pv-protection"}.get(kind)
        if fin is None:
            return obj
        meta = _meta(obj)
        fins = list(meta.get("finalizers") or [])
        if fin not in fins:
            meta["finalizers"] = fins + [fin]
        return obj


class PersistentVolumeClaimResize:
    """Gate claim resizes (plugin/pkg/admission/storage/
    persistentvolumeclaimresize/admission.go): shrinking is never
    allowed; growing requires the claim's StorageClass to set
    allowVolumeExpansion."""

    def __init__(self, cluster):
        self.cluster = cluster

    @staticmethod
    def _request(obj: dict) -> Optional[Quantity]:
        spec = obj.get("spec") or {}
        req = ((spec.get("resources") or {}).get("requests") or {}
               ).get("storage")
        return parse_quantity(req) if req is not None else None

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "persistentvolumeclaims" or op != "UPDATE":
            return obj
        meta = obj.get("metadata") or {}
        ns = obj.get("namespace") or meta.get("namespace", "default")
        name = obj.get("name") or meta.get("name", "")
        cur = self.cluster.get("persistentvolumeclaims", ns, name)
        if cur is None:
            return obj
        old_req = getattr(cur, "request", None)
        new_req = self._request(obj)
        if old_req is None or new_req is None:
            return obj
        if new_req.value < old_req.value:
            raise AdmissionDenied(
                "persistent volume claims may not shrink "
                f"({old_req} -> {new_req})")
        if new_req.value > old_req.value:
            sc_name = getattr(cur, "storage_class", "")
            sc = (self.cluster.get("storageclasses", "", sc_name)
                  if sc_name and self.cluster.has_kind("storageclasses")
                  else None)
            allow = False
            if sc is not None:
                allow = bool(sc.get("allowVolumeExpansion")
                             if isinstance(sc, dict)
                             else getattr(sc, "allow_volume_expansion",
                                          False))
            if not allow:
                raise AdmissionDenied(
                    f"storage class {sc_name!r} does not allow volume "
                    "expansion")
        return obj


class PodSecurityPolicy:
    """PSP validation distilled (plugin/pkg/admission/security/
    podsecuritypolicy/admission.go:1-379): with policies registered, a
    pod is admitted iff AT LEAST ONE admits every security-relevant
    field; with none, the plugin is inert (the reference fails open
    only when the plugin is disabled — an empty policy set here means
    the operator opted out of PSP).

    Policy fields honored (spec.): privileged, hostNetwork, hostPID,
    hostIPC, hostPorts ranges, runAsUser.rule (RunAsAny |
    MustRunAsNonRoot), volumes ('*' or source-kind names)."""

    def __init__(self, cluster):
        self.cluster = cluster

    @staticmethod
    def _violations(psp: dict, pod: dict) -> Optional[str]:
        spec = psp.get("spec") or {}
        pspec = pod.get("spec") or {}
        sc = pspec.get("securityContext") or {}
        for c in pspec.get("containers") or []:
            csc = c.get("securityContext") or {}
            if csc.get("privileged") and not spec.get("privileged"):
                return f"privileged container {c.get('name')!r}"
            run_rule = (spec.get("runAsUser") or {}).get("rule", "RunAsAny")
            if run_rule == "MustRunAsNonRoot":
                uid = csc.get("runAsUser", sc.get("runAsUser"))
                if uid == 0:
                    return f"container {c.get('name')!r} runs as root"
                if uid is None and not csc.get(
                        "runAsNonRoot", sc.get("runAsNonRoot")):
                    return (f"container {c.get('name')!r} must set "
                            "runAsNonRoot")
            for p in c.get("ports") or []:
                hp = p.get("hostPort")
                if hp:
                    ranges = spec.get("hostPorts") or []
                    if not any(r.get("min", 0) <= hp <= r.get("max", 0)
                               for r in ranges):
                        return f"host port {hp} not allowed"
        for flag in ("hostNetwork", "hostPID", "hostIPC"):
            if pspec.get(flag) and not spec.get(flag):
                return f"{flag} is not allowed"
        allowed_vols = spec.get("volumes") or ["*"]
        if "*" not in allowed_vols:
            for v in pspec.get("volumes") or []:
                src = next((k for k in v if k != "name"), None)
                if src is not None and src not in allowed_vols:
                    return f"volume source {src!r} not allowed"
        return None

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        if kind != "pods" or op != "CREATE":
            return obj
        if not self.cluster.has_kind("podsecuritypolicies"):
            return obj
        psps = [p for p in self.cluster.list("podsecuritypolicies")
                if isinstance(p, dict)]
        if not psps:
            return obj
        reasons = []
        for psp in sorted(psps, key=lambda p: p.get("name", "")):
            why = self._violations(psp, obj)
            if why is None:
                return obj  # first admitting policy wins
            reasons.append(f"{psp.get('name')}: {why}")
        raise AdmissionDenied(
            "unable to validate against any pod security policy: "
            + "; ".join(reasons))


def default_admission_chain(cluster, user_getter: Optional[Callable] = None,
                            with_service_account: bool = False,
                            ) -> List[Callable]:
    """The enabled-by-default chain in reference order
    (pkg/kubeapiserver/options/plugins.go:43-77: NamespaceLifecycle,
    LimitRanger, ServiceAccount, ..., Priority, DefaultTolerationSeconds,
    TaintNodesByCondition, ..., NodeRestriction, ResourceQuota last).

    NodeRestriction joins when a user_getter is provided (it needs the
    authenticated request identity — authn must be on); ServiceAccount
    joins on request (it requires the SA controller to be running, or
    every pod create fails for want of the default SA)."""
    chain: List[Callable] = [
        NamespaceLifecycle(cluster),
        EventRateLimit(),
        LimitRanger(cluster),
        PodPreset(cluster),
        AlwaysPullImages(),
    ]
    if with_service_account:
        chain.append(ServiceAccount(cluster))
    chain += [
        PodNodeSelector(cluster),
        Priority(cluster),
        DefaultTolerationSeconds(),
        TaintNodesByCondition(),
        StorageObjectInUseProtection(),
        PersistentVolumeClaimResize(cluster),
        PodSecurityPolicy(cluster),
    ]
    if user_getter is not None:
        chain.append(NodeRestriction(cluster, user_getter))
    # dynamic admission: the Mutating/Validating webhook pair sits after
    # the compiled-in plugins, before ResourceQuota (plugins.go:43-77);
    # with no configurations registered it is a no-op
    from kubernetes_tpu.apiserver.webhooks import WebhookDispatcher

    chain.append(WebhookDispatcher(cluster))
    chain.append(ResourceQuota(cluster))
    return chain
