"""REST API server over the LocalCluster store (SURVEY.md layer 4 slice).

The reference's write path (SURVEY section 3.3) is: handler chain
(authn/authz) -> admission chain -> registry strategy -> etcd3 storage ->
watch fan-out.  This server reproduces the layers that shape behavior:

  * kube-style REST paths over HTTP JSON:
      GET  /healthz, /metrics, /version
      GET/POST          /api/v1/nodes[/{name}]
      GET/POST          /api/v1/namespaces/{ns}/pods[/{name}]
      PUT/DELETE        .../{name}            (PUT honors resourceVersion)
      POST              .../pods/{name}/binding   (the Binding subresource:
                        sets spec.nodeName — pkg/registry/core/pod)
      GET/POST/PUT/DELETE /apis/apps/v1/namespaces/{ns}/replicasets[/{name}]
      GET  /api/v1/watch     chunked JSON-lines watch stream
  * an admission chain (plugin/pkg/admission analog): callables
    (op, kind, obj_dict) -> obj_dict run in order on every write; raising
    AdmissionDenied turns into HTTP 403, mutations flow through;
  * optimistic concurrency: PUT with metadata.resourceVersion mismatching
    the stored revision returns 409 (etcd3 txn CAS).

Storage is the LocalCluster (etcd3-semantics store); any scheduler /
controller wired to the same cluster observes API writes immediately.
"""

from __future__ import annotations

import json
import queue as _queue
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple

from kubernetes_tpu.api import binary as k8s_binary
from kubernetes_tpu.api.serialize import object_to_dict
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.runtime.cluster import ConflictError, LocalCluster
from kubernetes_tpu.utils import metrics as m

LIST_KINDS = {"pods": "PodList", "nodes": "NodeList",
              "replicasets": "ReplicaSetList", "services": "ServiceList",
              "deployments": "DeploymentList",
              "poddisruptionbudgets": "PodDisruptionBudgetList",
              "endpoints": "EndpointsList",
              "jobs": "JobList",
              "daemonsets": "DaemonSetList",
              "statefulsets": "StatefulSetList",
              "cronjobs": "CronJobList",
              "horizontalpodautoscalers": "HorizontalPodAutoscalerList",
              "namespaces": "NamespaceList",
              "limitranges": "LimitRangeList",
              "resourcequotas": "ResourceQuotaList",
              "priorityclasses": "PriorityClassList",
              "customresourcedefinitions": "CustomResourceDefinitionList",
              "apiservices": "APIServiceList",
              "secrets": "SecretList",
              "serviceaccounts": "ServiceAccountList",
              "roles": "RoleList",
              "rolebindings": "RoleBindingList",
              "clusterroles": "ClusterRoleList",
              "clusterrolebindings": "ClusterRoleBindingList",
              "persistentvolumes": "PersistentVolumeList",
              "persistentvolumeclaims": "PersistentVolumeClaimList",
              "storageclasses": "StorageClassList",
              "replicationcontrollers": "ReplicationControllerList",
              "certificatesigningrequests":
                  "CertificateSigningRequestList",
              "configmaps": "ConfigMapList",
              "mutatingwebhookconfigurations":
                  "MutatingWebhookConfigurationList",
              "validatingwebhookconfigurations":
                  "ValidatingWebhookConfigurationList"}

# kinds stored as plain dicts carrying the original wire body plus flat
# namespace/name keys for the store (cluster-scoped kinds use "")
_DICT_KINDS = {
    "namespaces": "",          # cluster-scoped
    "priorityclasses": "",     # cluster-scoped
    "limitranges": "default",
    "resourcequotas": "default",
    "customresourcedefinitions": "",  # cluster-scoped
    "apiservices": "",                # cluster-scoped
    "secrets": "default",
    "serviceaccounts": "default",
    "roles": "default",
    "rolebindings": "default",
    "clusterroles": "",               # cluster-scoped
    "clusterrolebindings": "",        # cluster-scoped
    "certificatesigningrequests": "",  # cluster-scoped
    "configmaps": "default",
    "mutatingwebhookconfigurations": "",   # cluster-scoped
    "validatingwebhookconfigurations": "",  # cluster-scoped
}


# the canonical exception lives with the plugins; re-exported here so
# handler code and external callers share one type
from kubernetes_tpu.apiserver.admission import AdmissionDenied  # noqa: E402

from dataclasses import dataclass  # noqa: E402


@dataclass
class TLSConfig:
    """Secure-serving material (secure_serving.go SecureServingInfo):
    the serving keypair plus, optionally, the CA that client certs must
    chain to (enables x509 authn)."""

    cert_path: str
    key_path: str
    client_ca_path: str = ""


def _decode(kind: str, d: dict):
    if kind == "pods":
        return Pod.from_dict(d)
    if kind == "nodes":
        return Node.from_dict(d)
    if kind == "replicasets":
        from kubernetes_tpu.runtime.controllers import ReplicaSet

        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        rs = ReplicaSet(
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            replicas=int(spec.get("replicas", 0)),
            selector=dict((spec.get("selector") or {}).get("matchLabels") or {}),
            template=spec.get("template") or {},
        )
        if meta.get("uid"):
            rs.uid = meta["uid"]
        if meta.get("annotations"):
            rs.annotations = dict(meta["annotations"])
        for ref in meta.get("ownerReferences") or []:
            if ref.get("controller"):
                rs.owner_uid = ref.get("uid", "")
        return rs
    if kind == "replicationcontrollers":
        from kubernetes_tpu.runtime.controllers import ReplicationController

        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        # RC selector is a PLAIN map (core/v1), not a LabelSelector
        rc = ReplicationController(
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            replicas=int(spec.get("replicas", 1)),
            selector=dict(spec.get("selector") or {}),
            template=spec.get("template") or {},
        )
        if meta.get("uid"):
            rc.uid = meta["uid"]
        return rc
    if kind == "deployments":
        from kubernetes_tpu.runtime.controllers import Deployment

        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        strat = spec.get("strategy") or {}
        ru = strat.get("rollingUpdate") or {}
        dep = Deployment(
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            replicas=int(spec.get("replicas", 1)),  # k8s defaults to 1
            selector=dict((spec.get("selector") or {}).get("matchLabels") or {}),
            template=spec.get("template") or {},
            strategy=strat.get("type", "RollingUpdate"),
            max_surge=ru.get("maxSurge", "25%"),
            max_unavailable=ru.get("maxUnavailable", "25%"),
        )
        if meta.get("uid"):
            dep.uid = meta["uid"]
        dep.labels = dict(meta.get("labels") or {})
        dep.annotations = dict(meta.get("annotations") or {})
        return dep
    if kind == "poddisruptionbudgets":
        from kubernetes_tpu.api.types import PodDisruptionBudget

        return PodDisruptionBudget.from_dict(d)
    if kind == "endpoints":
        # accept our flat form (GET round-trip), the metadata form, and a
        # k8s-wire subsets[].addresses form
        meta = d.get("metadata") or {}
        addresses = list(d.get("addresses") or ())
        if not addresses:
            for sub in d.get("subsets") or ():
                addresses.extend(sub.get("addresses") or ())
        return {"namespace": d.get("namespace") or meta.get("namespace", "default"),
                "name": d.get("name") or meta.get("name", ""),
                "addresses": addresses}
    if kind == "services":
        meta = d.get("metadata") or {}
        return {
            "namespace": meta.get("namespace", "default"),
            "name": meta.get("name", ""),
            "selector": dict((d.get("spec") or {}).get("selector") or {}),
        }
    if kind == "daemonsets":
        from kubernetes_tpu.runtime.controllers import DaemonSet

        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        ds = DaemonSet(
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            selector=dict((spec.get("selector") or {}).get("matchLabels") or {}),
            template=spec.get("template") or {},
        )
        if meta.get("uid"):
            ds.uid = meta["uid"]
        return ds
    if kind == "statefulsets":
        from kubernetes_tpu.runtime.controllers import StatefulSet

        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        st = StatefulSet(
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            replicas=int(spec.get("replicas", 1)),
            selector=dict((spec.get("selector") or {}).get("matchLabels") or {}),
            template=spec.get("template") or {},
            volume_claim_templates=tuple(
                spec.get("volumeClaimTemplates") or ()),
        )
        if meta.get("uid"):
            st.uid = meta["uid"]
        return st
    if kind == "cronjobs":
        from kubernetes_tpu.runtime.controllers import CronJob, cron_matches

        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        # reject malformed schedules at the write path (422), not at tick
        # time (cronjob strategy validation)
        cron_matches(spec.get("schedule", "* * * * *"), time.localtime())
        status = d.get("status") or {}
        lst = status.get("lastScheduleTime")
        cj = CronJob(
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            schedule=spec.get("schedule", "* * * * *"),
            job_template=spec.get("jobTemplate") or {},
            concurrency_policy=spec.get("concurrencyPolicy", "Allow"),
            suspend=bool(spec.get("suspend", False)),
            last_schedule_minute=(
                int(lst) // 60 if lst is not None else -1
            ),
        )
        if meta.get("uid"):
            cj.uid = meta["uid"]
        return cj
    if kind == "horizontalpodautoscalers":
        from kubernetes_tpu.runtime.controllers import HorizontalPodAutoscaler

        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        ref = spec.get("scaleTargetRef") or {}
        status = d.get("status") or {}
        hpa = HorizontalPodAutoscaler(
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            target_kind=ref.get("kind", "Deployment"),
            target_name=ref.get("name", ""),
            min_replicas=int(spec.get("minReplicas", 1)),
            max_replicas=int(spec.get("maxReplicas", 10)),
            target_cpu_utilization=int(
                spec.get("targetCPUUtilizationPercentage", 80)
            ),
            current_replicas=int(status.get("currentReplicas", 0)),
            desired_replicas=int(status.get("desiredReplicas", 0)),
        )
        if meta.get("uid"):
            hpa.uid = meta["uid"]
        return hpa
    if kind == "jobs":
        from kubernetes_tpu.runtime.controllers import Job

        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        conds = {c.get("type"): c.get("status") for c in status.get("conditions") or []}
        job = Job(
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            completions=int(spec.get("completions", 1)),
            parallelism=int(spec.get("parallelism", 1)),
            template=spec.get("template") or {},
            backoff_limit=int(spec.get("backoffLimit", 6)),
            ttl_seconds_after_finished=(
                int(spec["ttlSecondsAfterFinished"])
                if spec.get("ttlSecondsAfterFinished") is not None else None
            ),
            succeeded=int(status.get("succeeded", 0)),
            failed=int(status.get("failed", 0)),
            complete=conds.get("Complete") == "True",
            failed_state=conds.get("Failed") == "True",
            finished_at=float(status.get("completionTime") or 0.0),
        )
        if meta.get("uid"):
            job.uid = meta["uid"]
        for ref in meta.get("ownerReferences") or []:
            if ref.get("controller"):
                job.owner_uid = ref.get("uid", "")
        return job
    if kind == "leases":
        meta = d.get("metadata") or {}
        out = dict(d)
        out["namespace"] = d.get("namespace") or meta.get("namespace", "")
        out["name"] = d.get("name") or meta.get("name", "")
        # the SERVER stamps renewTime: remote agents' clocks (and their
        # monotonic epochs) are meaningless to the lease-age check the
        # nodelifecycle controller runs on this process's clock
        out["renew_time"] = time.monotonic()
        return out
    if kind == "persistentvolumes":
        from kubernetes_tpu.api.storage import PersistentVolume

        return PersistentVolume.from_dict(d)
    if kind == "persistentvolumeclaims":
        from kubernetes_tpu.api.storage import PersistentVolumeClaim

        return PersistentVolumeClaim.from_dict(d)
    if kind == "storageclasses":
        from kubernetes_tpu.api.storage import StorageClass

        return StorageClass.from_dict(d)
    from kubernetes_tpu.apiserver.extensions import flatten_wire_dict

    if kind in _DICT_KINDS:
        default_ns = _DICT_KINDS[kind]
        return flatten_wire_dict(d, None if default_ns == "" else default_ns)
    if "." in kind:
        # CRD-established custom resource ("<plural>.<group>"): stored as
        # its wire dict; the path namespace was injected into metadata
        # before decode (cluster-scoped CRs have none -> "")
        return flatten_wire_dict(d, default_ns="")
    raise ValueError(f"unknown kind {kind!r}")


class APIServer:
    def __init__(
        self,
        cluster: Optional[LocalCluster] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        admission: Optional[List[Callable[[str, str, dict], dict]]] = None,
        audit_path: Optional[str] = None,
        audit_policy: Optional[dict] = None,
        authenticator=None,
        authorizer=None,
        tls: Optional["TLSConfig"] = None,
        flow_control=None,
    ):
        self.cluster = cluster if cluster is not None else LocalCluster()
        # APF-style inflight limiting (apiserver/fairness.py): accepts a
        # FlowControlConfig or a prebuilt InflightLimiter; None = open
        # server (unlimited, the historical behavior)
        from kubernetes_tpu.apiserver.fairness import (
            FlowControlConfig,
            InflightLimiter,
        )

        if isinstance(flow_control, FlowControlConfig):
            flow_control = InflightLimiter(flow_control)
        self.flow_control: Optional[InflightLimiter] = flow_control
        # per-request custom-resource version (set by _route_extension,
        # consumed by the conversion seams; thread-local because the
        # HTTP server runs one thread per connection)
        import threading as _threading

        self._cr_req = _threading.local()
        # authn/authz handler-chain slots (config.go:544-550).  Both None =
        # open server (embedded/test mode, the historical behavior); with an
        # authenticator, bad tokens 401 and missing tokens degrade to the
        # anonymous identity; with an authorizer, denied requests 403.
        self.authenticator = authenticator
        self.authorizer = authorizer
        # per-request identity for admission plugins (NodeRestriction needs
        # the caller); each request runs on its own handler thread
        self.request_user = threading.local()
        # API audit (staging/src/k8s.io/apiserver/pkg/audit): one JSON line
        # per WRITE request — verb, path, response code, stage
        # ResponseComplete — appended to audit_path when configured
        self._audit_f = open(audit_path, "a") if audit_path else None
        self._audit_lock = threading.Lock()
        # audit policy (audit/policy/checker.go:28-38): first matching
        # rule's level wins — None drops the event, Metadata logs
        # verb/resource/code, Request adds the request body,
        # RequestResponse adds the response body.  No policy = Metadata
        # for every write (the historical behavior); a policy with no
        # matching rule logs nothing.
        self.audit_policy = audit_policy
        # ordered admission chain (mutating-then-validating collapses to
        # "each plugin may mutate or raise")
        self.admission: List[Callable[[str, str, dict], dict]] = list(
            admission or []
        )
        # serializes admission + write so read-then-create policy checks
        # (quota) are atomic across the threaded handler pool
        self._write_lock = threading.Lock()
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._httpd.daemon_threads = True
        # secure serving (secure_serving.go:1-238): wrap the listener in
        # TLS; with a client CA configured, request (not require) client
        # certs — the x509 authenticator turns them into identities, and
        # cert-less clients fall through to bearer tokens
        self.tls = tls
        if tls is not None:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls.cert_path,
                                keyfile=tls.key_path)
            if tls.client_ca_path:
                ctx.load_verify_locations(cafile=tls.client_ca_path)
                ctx.verify_mode = ssl.CERT_OPTIONAL
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True)
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        h, p = self.address
        scheme = "https" if self.tls is not None else "http"
        return f"{scheme}://{h}:{p}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._audit_f is not None:
            self._audit_f.close()
            self._audit_f = None

    def current_user(self):
        """The authenticated identity of the request being handled on THIS
        thread (parked by the authn step) — what NodeRestriction consumes."""
        return getattr(self.request_user, "user", None)

    # ----------------------------------------------------------- admission

    def _audit_level(self, verb: str, kind: str, ns: str,
                     user: str) -> str:
        """First matching policy rule's level (audit/policy/checker.go:
        28-38 LevelForPolicy): rules filter on verbs / users /
        namespaces / resources (each omitted = match-all); an explicit
        policy with no matching rule audits nothing."""
        if self.audit_policy is None:
            return "Metadata"
        for r in self.audit_policy.get("rules") or []:
            if r.get("verbs") and verb.lower() not in [
                    v.lower() for v in r["verbs"]]:
                continue
            if r.get("users") and (user or "") not in r["users"]:
                continue
            if r.get("namespaces") and ns not in r["namespaces"]:
                continue
            groups = r.get("resources")
            if groups:
                if not any(
                    "*" in (g.get("resources") or [])
                    or kind in (g.get("resources") or [])
                    for g in groups
                ):
                    continue
            return r.get("level", "Metadata")
        return "None"

    def _audit(self, verb: str, path: str, code: int,
               handler=None) -> None:
        """ResponseComplete audit event (audit/v1 Event), shaped by the
        policy level: Metadata = verb/resource/code/user; Request adds
        requestObject; RequestResponse adds responseObject."""
        if self._audit_f is None:
            return
        import time as _t

        kind, ns, name = "", "", ""
        r = self._route(path.partition("?")[0])
        if r is not None:
            kind, ns, name = r[0], r[1], r[2]
        user = self.current_user()
        username = getattr(user, "name", "") if user is not None else ""
        level = self._audit_level(verb, kind, ns, username)
        if level == "None":
            return
        ev = {
            "kind": "Event",
            "apiVersion": "audit.k8s.io/v1",
            "level": level,
            "stage": "ResponseComplete",
            "verb": verb.lower(),
            "requestURI": path,
            "objectRef": {"resource": kind, "namespace": ns, "name": name},
            "user": {"username": username},
            "responseStatus": {"code": code},
            "stageTimestamp": _t.strftime(
                "%Y-%m-%dT%H:%M:%SZ", _t.gmtime()
            ),
        }
        if level in ("Request", "RequestResponse") and handler is not None:
            body = getattr(handler, "_audit_req_body", None)
            if body is not None:
                ev["requestObject"] = body
        if level == "RequestResponse" and handler is not None:
            resp = getattr(handler, "_audit_resp_obj", None)
            if resp is not None:
                ev["responseObject"] = resp
        line = json.dumps(ev)
        with self._audit_lock:
            self._audit_f.write(line + "\n")
            self._audit_f.flush()

    def _cr_request_version(self, kind: str):
        d = getattr(self._cr_req, "data", None)
        return d[1] if d and d[0] == kind else None

    def _cr_to_request_version(self, kind: str, obj):
        """READ seam: a custom resource leaves the server in the version
        the request named (storage -> request conversion)."""
        if "." not in kind or not isinstance(obj, dict):
            return obj
        v = self._cr_request_version(kind)
        if not v:
            return obj
        from kubernetes_tpu.apiserver.extensions import (
            convert_cr,
            find_crd_for_kind,
        )

        crd = find_crd_for_kind(self.cluster, kind)
        if crd is None:
            return obj
        return convert_cr(self.cluster, crd, obj, v)

    def _cr_list_to_request_version(self, kind: str, items: list) -> list:
        """LIST read seam: one batched ConversionReview for the whole
        list (webhook_converter.go sends all objects in one review)."""
        if "." not in kind or not items:
            return items
        v = self._cr_request_version(kind)
        if not v:
            return items
        from kubernetes_tpu.apiserver.extensions import (
            convert_cr_objects,
            find_crd_for_kind,
        )

        crd = find_crd_for_kind(self.cluster, kind)
        if crd is None:
            return items
        return convert_cr_objects(self.cluster, crd, items, v)

    def _cr_to_storage_version(self, kind: str, body):
        """WRITE seam: a custom resource persists in the CRD's storage
        version whatever version the request used (apiextensions
        CustomResourceDefinitionVersion.storage)."""
        if "." not in kind or not isinstance(body, dict):
            return body
        from kubernetes_tpu.apiserver.extensions import (
            convert_cr,
            crd_storage_version,
            find_crd_for_kind,
        )

        crd = find_crd_for_kind(self.cluster, kind)
        if crd is None:
            return body
        return convert_cr(self.cluster, crd, body, crd_storage_version(crd))

    def _validate_extension(self, kind: str, body: dict) -> None:
        """Write-path schema checks: typed-field validation for the core
        dict-backed kinds (api/corev1.py — the per-kind strategy Validate
        analog, surfaced as 422), establishment sanity for CRDs, and
        openAPIV3Schema validation for custom-resource instances
        (apiextensions-apiserver validation.go)."""
        from kubernetes_tpu.api import corev1

        corev1.validate(kind, body)
        from kubernetes_tpu.apiserver.extensions import (
            crd_schema,
            find_crd_for_kind,
            validate_crd_spec,
            validate_schema,
        )

        if kind == "customresourcedefinitions":
            validate_crd_spec(body)
            return
        if "." in kind:
            crd = find_crd_for_kind(self.cluster, kind)
            if crd is not None:
                schema = crd_schema(crd)
                if schema:
                    validate_schema(body, schema)

    def _admit(self, op: str, kind: str, obj_dict: dict) -> dict:
        for plugin in self.admission:
            obj_dict = plugin(op, kind, obj_dict)
        return obj_dict

    def _admit_split(self, op: str, kind: str, obj_dict: dict,
                     locked: bool) -> dict:
        """The write handlers run admission in two phases: everything up
        to ResourceQuota OUTSIDE the write lock (webhook dispatch does
        remote HTTP — holding the lock there would serialize every write
        behind slow webhooks and self-deadlock any webhook that writes
        back to this apiserver), then ResourceQuota INSIDE the lock
        (its read-then-check must be atomic with the create).  The other
        compiled-in plugins only READ cluster state, so running them
        pre-lock keeps their semantics."""
        from kubernetes_tpu.apiserver.admission import ResourceQuota

        for plugin in self.admission:
            if isinstance(plugin, ResourceQuota) == locked:
                obj_dict = plugin(op, kind, obj_dict)
        return obj_dict

    # ------------------------------------------------------------- routes

    def _route(self, path: str):
        """-> (kind, namespace, name, subresource) or None.

        Dynamic groups resolve through the extension mechanisms: a
        CustomResourceDefinition's group/version/plural maps to its storage
        kind (apiextensions-apiserver analog), and an APIService proxies
        the whole group prefix to its backing server (kube-aggregator
        analog; returned as ("@proxy", url, "", ""))."""
        parts = [p for p in path.split("?")[0].split("/") if p]
        # /api/v1/... or /apis/apps/v1/...
        if parts[:2] == ["api", "v1"]:
            rest = parts[2:]
        elif parts[:3] == ["apis", "apps", "v1"]:
            rest = parts[3:]
        elif parts[:3] == ["apis", "policy", "v1beta1"]:
            rest = parts[3:]
        elif parts[:3] == ["apis", "batch", "v1"]:
            rest = parts[3:]
        elif parts[:3] == ["apis", "batch", "v1beta1"]:
            rest = parts[3:]
        elif parts[:3] == ["apis", "autoscaling", "v1"]:
            rest = parts[3:]
        elif parts[:3] == ["apis", "metrics.k8s.io", "v1beta1"]:
            rest = ["@metrics"] + parts[3:]
        elif parts[:1] == ["apis"] and len(parts) >= 3:
            ext = self._route_extension(parts[1], parts[2], parts[3:])
            if ext is not None:
                return ext
            return None
        else:
            return None
        if not rest:
            return None
        if rest[0] == "watch":
            return ("watch", "", "", "")
        if rest[0] == "namespaces" and len(rest) >= 3:
            ns, kind = rest[1], rest[2]
            name = rest[3] if len(rest) > 3 else ""
            sub = rest[4] if len(rest) > 4 else ""
        else:
            kind, ns = rest[0], ""
            name = rest[1] if len(rest) > 1 else ""
            sub = rest[2] if len(rest) > 2 else ""
        if "." in kind:
            # custom resources are reachable ONLY through their CRD's
            # /apis/{group}/{version} route (which enforces establishment
            # and schema); the storage kind must not leak into core paths
            return None
        return (kind, ns, name, sub)

    def _route_extension(self, group: str, version: str, rest):
        """Resolve /apis/{group}/{version}/... via CRDs, then APIServices.
        Only SERVED versions route (a declared-but-unserved version 404s,
        apiextensions types.go:67-104); the requested version is recorded
        per-thread so reads convert storage -> request version and writes
        convert request -> storage version."""
        from kubernetes_tpu.apiserver.extensions import crd_served_versions

        for crd in self.cluster.list("customresourcedefinitions"):
            spec = crd.get("spec") or {}
            if spec.get("group") != group:
                continue
            if version not in crd_served_versions(crd):
                continue
            plural = (spec.get("names") or {}).get("plural", "")
            storage_kind = f"{plural}.{group}"
            if rest[:1] == ["namespaces"] and len(rest) >= 3 and rest[2] == plural:
                self.cluster.register_kind(storage_kind)  # lazy re-establish
                name = rest[3] if len(rest) > 3 else ""
                self._cr_req.data = (storage_kind, version)
                return (storage_kind, rest[1], name, "")
            if rest[:1] == [plural]:
                self.cluster.register_kind(storage_kind)
                name = rest[1] if len(rest) > 1 else ""
                self._cr_req.data = (storage_kind, version)
                return (storage_kind, "", name, "")
        for svc in self.cluster.list("apiservices"):
            spec = svc.get("spec") or {}
            if spec.get("group") == group and spec.get("version") == version:
                url = (spec.get("service") or {}).get("url", "")
                if url:
                    return ("@proxy", url, "", "")
        return None

    def _make_handler(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _wants_binary(self) -> bool:
                return (k8s_binary.BINARY_MEDIA_TYPE
                        in self.headers.get("Accept", ""))

            def _send(self, obj, code: int = 200):
                # content negotiation (protobuf.go analog): clients opt
                # in to the binary wire format via Accept; default traffic
                # AND errors stay JSON (error-handling clients parse
                # Status bodies as JSON regardless of their data Accept)
                if code < 400 and self._wants_binary():
                    body = k8s_binary.dumps(obj)
                    ct = k8s_binary.BINARY_MEDIA_TYPE
                else:
                    body = json.dumps(obj).encode()
                    ct = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ct)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _status(self, code: int, reason: str, message: str):
                self._send(
                    {"kind": "Status", "apiVersion": "v1", "code": code,
                     "reason": reason, "message": message},
                    code,
                )

            def _too_many_requests(self, message: str,
                                   retry_after_s: float) -> None:
                """THE 429 path — shared by the inflight limiter's
                rejection and the eviction-blocked-by-PDB response: a
                Status body plus the Retry-After header clients key
                their backoff on (the reference stamps it in both
                places: filters/maxinflight.go tooManyRequests and
                registry/core/pod/rest/eviction.go)."""
                self._audit_resp_obj = obj = {
                    "kind": "Status", "apiVersion": "v1", "code": 429,
                    "reason": "TooManyRequests", "message": message,
                }
                body = json.dumps(obj).encode()
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header(
                    "Retry-After",
                    str(max(1, int(-(-retry_after_s // 1)))),  # ceil, >=1s
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) or b"{}"
                if (k8s_binary.BINARY_MEDIA_TYPE
                        in self.headers.get("Content-Type", "")):
                    return k8s_binary.loads(raw)
                return json.loads(raw)

            # -------------------------------------------------- authn/authz

            def _authenticate(self):
                """WithAuthentication: -> UserInfo, or None after sending
                401.  No Authorization header degrades to the anonymous
                identity; a present-but-invalid bearer token is 401."""
                from kubernetes_tpu.apiserver.auth import (
                    ANONYMOUS,
                    SUPERUSER_GROUP,
                    AuthenticationError,
                    UserInfo,
                )

                # refresh per request: handler threads are reused across
                # keep-alive requests, so a stale identity must never
                # survive into the next request's admission run
                outer.request_user.user = None
                # x509 client-cert authn runs FIRST in the union
                # (authentication/request/x509: CN = user, O = groups);
                # the TLS layer already verified the chain against the
                # client CA, so a presented cert IS the identity
                if outer.tls is not None and outer.tls.client_ca_path:
                    try:
                        der = self.connection.getpeercert(binary_form=True)
                    except (AttributeError, ValueError):
                        der = None
                    if der:
                        from kubernetes_tpu.utils.pki import (
                            identity_from_cert_der,
                        )

                        cn, orgs = identity_from_cert_der(der)
                        if cn:
                            user = UserInfo(
                                cn, orgs + ("system:authenticated",))
                            outer.request_user.user = user
                            return user
                if outer.authenticator is None:
                    # open server: every caller is effectively the admin
                    user = UserInfo("system:admin", (SUPERUSER_GROUP,))
                    outer.request_user.user = user
                    return user
                hdr = self.headers.get("Authorization", "")
                if not hdr:
                    outer.request_user.user = ANONYMOUS
                    return ANONYMOUS
                if not hdr.startswith("Bearer "):
                    self._status(401, "Unauthorized",
                                 "unsupported authorization scheme")
                    return None
                try:
                    user = outer.authenticator.authenticate(hdr[7:].strip())
                    outer.request_user.user = user
                    return user
                except AuthenticationError as e:
                    self._status(401, "Unauthorized", str(e))
                    return None

            def _authorize(self, verb: str, resource: str,
                           ns: str = "", name: str = ""):
                """WithAuthorization: -> UserInfo, or None after sending
                401/403.  Also parks the identity in request_user so the
                admission chain can see the caller."""
                user = self._authenticate()
                if user is None:
                    return None
                if outer.authorizer is not None and not (
                    outer.authorizer.authorize(user, verb, resource, ns, name)
                ):
                    where = f' in namespace "{ns}"' if ns else ""
                    self._status(
                        403, "Forbidden",
                        f'User "{user.name}" cannot {verb} resource '
                        f'"{resource}"{where}',
                    )
                    return None
                outer.request_user.user = user
                return user

            # ------------------------------------------------------- GET

            def do_GET(self):
                if self.path in ("/healthz", "/livez", "/readyz"):
                    # healthz (legacy) + livez/readyz split
                    # (apiserver/pkg/server/healthz): this single-process
                    # server is ready exactly when it is alive
                    self._send_text(b"ok")
                    return
                if self.path == "/metrics":
                    self._send_text(
                        m.REGISTRY.expose().encode(),
                        ct="text/plain; version=0.0.4",
                    )
                    return
                if self.path.partition("?")[0].startswith("/debug"):
                    # EVERY debug endpoint — flight recorder, ledger,
                    # telemetry, perf/quality observatories, capacity,
                    # autoscaler, replicas, profile, timeline, and the
                    # index — routes through the ONE shared table
                    # (runtime/ledger.py DEBUG_RENDERERS), the same
                    # table the health server walks: a new endpoint
                    # registered there is exposed on both servers, and
                    # can no longer be forgotten on one.  In embedded
                    # deployments (--with-scheduler) the scheduling
                    # happens in this process, so the process defaults
                    # these renderers read ARE the live instances.
                    # Inflight-exempt (see the `limited` wrapper):
                    # diagnosing an overload needs them reachable.
                    from kubernetes_tpu.runtime.ledger import (
                        debug_dispatch,
                    )

                    path, _, query = self.path.partition("?")
                    body = debug_dispatch(path, query)
                    if body is None:
                        self._status(404, "NotFound", self.path)
                    else:
                        self._send_text(body, ct="application/json")
                    return
                if self.path == "/version":
                    self._send({"gitVersion": "v1.15-tpu", "major": "1",
                                "minor": "15"})
                    return
                if self._is_discovery_path():
                    # discovery + openapi stay open like /healthz (the
                    # reference binds system:discovery to every identity)
                    self._serve_discovery()
                    return
                r = outer._route(self.path)
                if r is None:
                    self._status(404, "NotFound", self.path)
                    return
                kind, ns, name, _sub = r
                if kind == "pods" and name and _sub == "log":
                    # the pods/log subresource.  Containers in this
                    # framework are pause-anchored sandboxes with no stdout
                    # stream, so the served log is the pod's LIFECYCLE log
                    # — the recorder's event trail for the pod, rendered as
                    # text lines (the kubelet-proxied GetContainerLogs
                    # distilled to the data that actually exists)
                    if self._authorize("get", "pods/log", ns, name) is None:
                        return
                    if outer.cluster.get("pods", ns, name) is None:
                        self._status(404, "NotFound", f"pods {ns}/{name}")
                        return
                    lines = [
                        f"{e.last_timestamp:.3f} {e.type} {e.reason}: "
                        f"{e.message}"
                        for e in outer.cluster.events.events(
                            namespace=ns, name=name)
                        if e.kind == "Pod"
                    ]
                    self._send({"kind": "PodLog", "log":
                                "\n".join(lines) + ("\n" if lines else "")})
                    return
                if kind == "watch":
                    # the firehose streams every kind: requires a grant on
                    # resource "*" (the remote scheduler runs as admin)
                    if self._authorize("watch", "*") is None:
                        return
                    self._serve_watch()
                    return
                if kind == "@metrics":
                    if self._authorize("get", "metrics.k8s.io") is None:
                        return
                    self._serve_metrics_api(ns, name)
                    return
                if kind == "events":
                    # the events API is served from the recorder (the
                    # components' user-visible audit trail, tools/record):
                    # a virtual read-only kind
                    if self._authorize("list", "events", ns) is None:
                        return
                    evs = outer.cluster.events.events(
                        namespace=ns or None, name=name or None)
                    items = [{
                        "metadata": {"namespace": e.namespace,
                                     "name": f"{e.name}.{i}"},
                        "involvedObject": {"kind": e.kind,
                                           "namespace": e.namespace,
                                           "name": e.name},
                        "type": e.type, "reason": e.reason,
                        "message": e.message, "count": e.count,
                        "firstTimestamp": e.first_timestamp,
                        "lastTimestamp": e.last_timestamp,
                        # the scheduling-cycle join key (utils/trace.py);
                        # omitted when the emitter carried no context
                        **({"traceID": e.trace_id}
                           if getattr(e, "trace_id", "") else {}),
                    } for i, e in enumerate(evs)]
                    # fieldSelector works here too (`kubectl get events
                    # --field-selector type=Warning` is the canonical use)
                    query = self.path.partition("?")[2]
                    if query:
                        from urllib.parse import parse_qs

                        fs = parse_qs(query).get("fieldSelector", [""])[0]
                        if fs:
                            from kubernetes_tpu.api.fields import (
                                FieldSelector,
                            )

                            try:
                                sel = FieldSelector.parse(fs)
                            except ValueError as e:
                                self._status(400, "BadRequest", str(e))
                                return
                            items = [d for d in items if sel.matches(d)]
                    self._send({"kind": "EventList", "apiVersion": "v1",
                                "items": items})
                    return
                if kind == "@proxy":
                    # the backend does its own authz; still authenticate +
                    # gate the aggregation hop itself
                    if self._authorize("get", "proxy") is None:
                        return
                    self._proxy(ns)  # ns slot carries the backend URL
                    return
                if self._authorize("get" if name else "list",
                                   kind, ns, name) is None:
                    return
                if kind not in LIST_KINDS and not outer.cluster.has_kind(kind):
                    self._status(404, "NotFound", f"unknown resource {kind}")
                    return
                if name:
                    obj, rv = outer.cluster.get_with_rv(kind, ns, name)
                    if obj is None:
                        self._status(404, "NotFound", f"{kind} {ns}/{name}")
                        return
                    # copy before injecting: for dict-backed kinds
                    # object_to_dict returns the STORED dict by reference —
                    # mutating it here would alter live cluster state from
                    # the handler thread, outside the cluster lock
                    out = dict(object_to_dict(kind, obj))
                    if "." in kind:  # custom resource: serve the REQUEST
                        try:
                            out = dict(
                                outer._cr_to_request_version(kind, out))
                        except Exception as e:  # conversion webhook down
                            self._status(500, "InternalError",
                                         f"conversion failed: {e}")
                            return
                    out["metadata"] = dict(out.get("metadata") or {})
                    # expose the revision so read-modify-write clients can
                    # round-trip it into PUT's CAS (etcd3 mod_revision analog)
                    out["metadata"]["resourceVersion"] = str(rv)
                    if kind == "certificatesigningrequests":
                        # status.certificate carries a BEARER credential in
                        # this framework (the reference's PEM is public):
                        # only the requestor (or an admin) may read it
                        user = outer.current_user()
                        requestor = (out.get("spec") or {}).get(
                            "requestorUsername", "")
                        if (outer.authenticator is not None
                                and user is not None
                                and user.name != requestor
                                and not user.in_group("system:masters")):
                            status = dict(out.get("status") or {})
                            status.pop("certificate", None)
                            out["status"] = status
                    self._send(out)
                else:
                    def ns_of(o):
                        if isinstance(o, dict):
                            return o.get("namespace", "")
                        return getattr(o, "namespace", "")

                    items = [
                        object_to_dict(kind, o)
                        for o in outer.cluster.list(kind)
                        if not ns or ns_of(o) == ns
                    ]
                    if "." in kind:
                        try:
                            items = outer._cr_list_to_request_version(
                                kind, items)
                        except Exception as e:  # conversion webhook down
                            self._status(500, "InternalError",
                                         f"conversion failed: {e}")
                            return
                    # LIST filtering: fieldSelector (apimachinery/pkg/
                    # fields) and labelSelector query params
                    query = self.path.partition("?")[2]
                    if query:
                        from urllib.parse import parse_qs

                        params = parse_qs(query)
                        fs = params.get("fieldSelector", [""])[0]
                        if fs:
                            from kubernetes_tpu.api.fields import (
                                FieldSelector,
                            )

                            try:
                                sel = FieldSelector.parse(fs)
                            except ValueError as e:
                                self._status(400, "BadRequest", str(e))
                                return
                            items = [d for d in items if sel.matches(d)]
                        ls = params.get("labelSelector", [""])[0]
                        if ls:
                            from kubernetes_tpu.api import labels as klabels

                            try:
                                lsel = klabels.parse_selector(ls)
                            except ValueError as e:
                                self._status(400, "BadRequest", str(e))
                                return
                            items = [
                                d for d in items
                                if lsel.matches(
                                    (d.get("metadata") or {}).get(
                                        "labels") or {})
                            ]
                    self._send({"kind": LIST_KINDS.get(kind, "List"),
                                "apiVersion": "v1", "items": items})

            # -------------------------------------------------- discovery

            def _is_discovery_path(self) -> bool:
                """/api, /apis, /api/v1, /apis/{g}, /apis/{g}/{v},
                /openapi/v2 — group/version docs, never resource routes."""
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if parts == ["api"] or parts == ["apis"]:
                    return True
                if parts == ["api", "v1"]:
                    return True
                if parts == ["openapi", "v2"]:
                    return True
                return parts[:1] == ["apis"] and len(parts) in (2, 3)

            def _groups(self):
                """(group -> {version, ...}) from the scheme + live CRDs
                (the aggregated discovery the RESTMapper walks)."""
                from kubernetes_tpu.api import scheme as _scheme

                groups: dict = {}
                for kind in _scheme.kinds():
                    gvk = _scheme.gvk_for(kind)
                    if gvk.group:
                        groups.setdefault(gvk.group, set()).add(gvk.version)
                for crd in outer.cluster.list("customresourcedefinitions"):
                    spec = crd.get("spec") or {}
                    g = spec.get("group", "")
                    if not g:
                        continue
                    vs = {spec.get("version")} | {
                        v.get("name") for v in spec.get("versions") or []
                    }
                    groups.setdefault(g, set()).update(v for v in vs if v)
                return groups

            def _resources_for(self, group: str, version: str):
                from kubernetes_tpu.api import scheme as _scheme

                out = []
                for kind in _scheme.kinds():
                    gvk = _scheme.gvk_for(kind)
                    if gvk.group != group or gvk.version != version:
                        continue
                    out.append({
                        "name": kind,
                        "kind": gvk.kind,
                        "namespaced": not _scheme.is_cluster_scoped(kind),
                        "verbs": ["create", "delete", "get", "list",
                                  "update", "watch"],
                    })
                for crd in outer.cluster.list("customresourcedefinitions"):
                    spec = crd.get("spec") or {}
                    if spec.get("group") != group:
                        continue
                    vs = {spec.get("version")} | {
                        v.get("name") for v in spec.get("versions") or []
                    }
                    if version not in vs:
                        continue
                    names = spec.get("names") or {}
                    out.append({
                        "name": names.get("plural", ""),
                        "kind": names.get("kind", ""),
                        "namespaced": spec.get("scope", "Namespaced")
                        == "Namespaced",
                        "verbs": ["create", "delete", "get", "list",
                                  "update"],
                    })
                return out

            def _serve_discovery(self):
                """Group/version discovery docs + /openapi/v2 (the
                endpoints kubectl's RESTMapper and `kubectl explain`
                walk; ref apiserver/pkg/endpoints/discovery + openapi)."""
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if parts == ["api"]:
                    self._send({"kind": "APIVersions", "versions": ["v1"]})
                    return
                if parts == ["apis"]:
                    groups = []
                    for g, versions in sorted(self._groups().items()):
                        vlist = [{"groupVersion": f"{g}/{v}", "version": v}
                                 for v in sorted(versions)]
                        groups.append({
                            "name": g,
                            "versions": vlist,
                            "preferredVersion": vlist[0],
                        })
                    self._send({"kind": "APIGroupList", "groups": groups})
                    return
                if parts == ["api", "v1"]:
                    self._send({
                        "kind": "APIResourceList",
                        "groupVersion": "v1",
                        "resources": self._resources_for("", "v1"),
                    })
                    return
                if parts[:1] == ["apis"] and len(parts) == 2:
                    g = parts[1]
                    versions = sorted(self._groups().get(g, ()))
                    if not versions:
                        self._status(404, "NotFound", f"group {g}")
                        return
                    vlist = [{"groupVersion": f"{g}/{v}", "version": v}
                             for v in versions]
                    self._send({"kind": "APIGroup", "name": g,
                                "versions": vlist,
                                "preferredVersion": vlist[0]})
                    return
                if parts[:1] == ["apis"] and len(parts) == 3:
                    g, v = parts[1], parts[2]
                    res = self._resources_for(g, v)
                    if not res:
                        self._status(404, "NotFound", f"{g}/{v}")
                        return
                    self._send({"kind": "APIResourceList",
                                "groupVersion": f"{g}/{v}",
                                "resources": res})
                    return
                # /openapi/v2: a swagger 2.0 doc with one path entry per
                # served collection and shallow kind definitions
                from kubernetes_tpu.api import scheme as _scheme

                paths = {}
                definitions = {}
                for kind in _scheme.kinds():
                    gvk = _scheme.gvk_for(kind)
                    coll = _scheme.rest_path(kind, "{namespace}")
                    paths[coll] = {
                        "get": {"operationId": f"list-{kind}"},
                        "post": {"operationId": f"create-{kind}"},
                    }
                    definitions[f"io.k8s.api.{gvk.group or 'core'}."
                                f"{gvk.version}.{gvk.kind}"] = {
                        "type": "object",
                        "description": f"{gvk.kind} "
                        f"({gvk.group or 'core'}/{gvk.version}), served "
                        f"at {coll}",
                        # the universal envelope every kind shares
                        # (kubectl explain's top level); per-field depth
                        # lives in the typed models (api/types.py,
                        # api/corev1.py)
                        "properties": {
                            "apiVersion": {"type": "string"},
                            "kind": {"type": "string"},
                            "metadata": {"type": "object"},
                            "spec": {"type": "object"},
                            "status": {"type": "object"},
                        },
                        "x-kubernetes-group-version-kind": [{
                            "group": gvk.group, "version": gvk.version,
                            "kind": gvk.kind,
                        }],
                    }
                self._send({
                    "swagger": "2.0",
                    "info": {"title": "kubernetes-tpu", "version": "v1.15"},
                    "paths": paths,
                    "definitions": definitions,
                })

            def _serve_metrics_api(self, ns: str, name: str):
                """metrics.k8s.io/v1beta1 analog (staging/src/k8s.io/metrics
                resource-metrics API): usage derived from Running pods\'
                requests — the hollow world\'s stand-in for cadvisor stats
                (a real node would report measured usage at this same seam).
                Paths: .../nodes[/{name}] and .../namespaces/{ns}/pods."""
                route = self.path.split("?")[0].split("/")
                # /apis/metrics.k8s.io/v1beta1/<rest...>
                rest = [p for p in route if p][3:]
                pods = outer.cluster.list("pods")
                # OBSERVED samples published by kubelets' stats providers
                # (runtime/kubelet_resources.StatsProvider.publish) win
                # over the declared-requests fallback — metrics.k8s.io
                # serves measured usage when a measurement exists
                observed = {}
                if outer.cluster.has_kind("podmetrics"):
                    for s in outer.cluster.list("podmetrics"):
                        observed[(s.get("namespace"), s.get("name"))] = (
                            float(s.get("cpu_milli", 0.0)),
                            float(s.get("memory_bytes", 0.0)),
                        )

                def pod_usage(p):
                    hit = observed.get((p.namespace, p.name))
                    if hit is not None:
                        return hit
                    cpu = mem = 0.0
                    for c in p.spec.containers:
                        if "cpu" in c.requests:
                            cpu += c.requests["cpu"].milli
                        if "memory" in c.requests:
                            mem += float(c.requests["memory"])
                    return cpu, mem

                if rest[:1] == ["nodes"]:
                    want = rest[1] if len(rest) > 1 else ""
                    items = []
                    for node in outer.cluster.list("nodes"):
                        if want and node.name != want:
                            continue
                        cpu = mem = 0.0
                        for p in pods:
                            if (
                                p.spec.node_name == node.name
                                and p.status.phase == "Running"
                            ):
                                c_, m_ = pod_usage(p)
                                cpu += c_
                                mem += m_
                        items.append({
                            "metadata": {"name": node.name},
                            "usage": {"cpu": f"{int(cpu)}m",
                                      "memory": f"{int(mem)}"},
                        })
                    if want:
                        if not items:
                            self._status(404, "NotFound", f"node {want}")
                            return
                        self._send(items[0])
                        return
                    self._send({"kind": "NodeMetricsList",
                                "apiVersion": "metrics.k8s.io/v1beta1",
                                "items": items})
                    return
                if rest[:1] == ["namespaces"] and rest[2:3] == ["pods"]:
                    ns_want = rest[1]
                    items = []
                    for p in pods:
                        if p.namespace != ns_want or p.status.phase != "Running":
                            continue
                        cpu, mem = pod_usage(p)
                        # container usage must SUM to the pod line (a
                        # client totaling containers reads the same
                        # number): distribute the pod-level measurement
                        # proportionally to requests, evenly when none
                        reqs = [
                            (float(c.requests["cpu"].milli)
                             if "cpu" in c.requests else 1.0,
                             float(c.requests["memory"])
                             if "memory" in c.requests else 1.0)
                            for c in p.spec.containers
                        ]
                        tot_c = sum(r[0] for r in reqs) or 1
                        tot_m = sum(r[1] for r in reqs) or 1
                        items.append({
                            "metadata": {"name": p.name,
                                         "namespace": p.namespace},
                            "containers": [{
                                "name": c.name,
                                "usage": {
                                    "cpu": f"{int(cpu * (r[0] / tot_c))}m",
                                    "memory": f"{int(mem * (r[1] / tot_m))}",
                                },
                            } for c, r in zip(p.spec.containers, reqs)],
                            "usage": {"cpu": f"{int(cpu)}m",
                                      "memory": f"{int(mem)}"},
                        })
                    self._send({"kind": "PodMetricsList",
                                "apiVersion": "metrics.k8s.io/v1beta1",
                                "items": items})
                    return
                self._status(404, "NotFound", self.path)

            def _proxy(self, backend: str):
                """kube-aggregator: forward this request verbatim to the
                APIService's backing server and relay the response."""
                n = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(n) if n else None
                req = urllib.request.Request(
                    backend.rstrip("/") + self.path, data=data,
                    method=self.command,
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        payload = resp.read()
                        self.send_response(resp.status)
                        ct = resp.headers.get(
                            "Content-Type", "application/json"
                        )
                        self.send_header("Content-Type", ct)
                        self.send_header("Content-Length", str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                except urllib.error.HTTPError as e:
                    payload = e.read()
                    self.send_response(e.code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except OSError as e:
                    self._status(502, "BadGateway",
                                 f"APIService backend {backend}: {e}")

            def _send_text(self, body: bytes, ct: str = "text/plain"):
                self.send_response(200)
                self.send_header("Content-Type", ct)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_watch(self):
                """Chunked watch stream, replay-then-follow: JSON-lines by
                default, length-prefixed binary frames when the client
                Accepts the binary media type (the protobuf watch
                negotiation analog)."""
                use_binary = self._wants_binary()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    k8s_binary.BINARY_MEDIA_TYPE if use_binary
                    else "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                q: "_queue.Queue" = _queue.Queue(maxsize=10000)
                overflow = threading.Event()

                def fan(event, kind, obj):
                    # fan runs synchronously inside the store's write lock;
                    # event_rv is the revision THIS event committed at —
                    # clients mirror the remote's resourceVersions for CAS
                    # round-trips (see LocalCluster._notify)
                    rv = getattr(outer.cluster, "event_rv", None)
                    if event == "DELETED":
                        rv = None  # no CAS target once the object is gone
                    try:
                        q.put_nowait((event, kind, obj, rv))
                    except _queue.Full:
                        # a watcher this far behind must re-list; closing the
                        # stream is the 410 Gone analog — never drop silently
                        overflow.set()

                # replay + end-of-replay BOOKMARK delivered under the store
                # lock: no live event can precede the bookmark (the k8s
                # watch-bookmark contract the reflector's atomic swap needs)
                outer.cluster.watch(fan, bookmark=True)
                def chunk(b: bytes) -> bytes:
                    return f"{len(b):x}\r\n".encode() + b + b"\r\n"

                try:
                    while not overflow.is_set():
                        try:
                            event, kind, obj, rv = q.get(timeout=1.0)
                        except _queue.Empty:
                            # heartbeat chunk keeps the connection honest
                            self.wfile.write(
                                chunk(k8s_binary.HEARTBEAT_FRAME) if use_binary
                                else b"1\r\n\n\r\n")
                            self.wfile.flush()
                            continue
                        payload = {
                            "type": event,
                            "kind": kind,
                            "object": (
                                object_to_dict(kind, obj)
                                if obj is not None else None
                            ),
                        }
                        if rv is not None:
                            payload["resourceVersion"] = str(rv)
                        if use_binary:
                            body = chunk(k8s_binary.frame(k8s_binary.dumps(payload)))
                        else:
                            body = chunk(json.dumps(payload).encode() + b"\n")
                        self.wfile.write(body)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    outer.cluster.unwatch(fan)

            # ------------------------------------------------------ writes

            def do_POST(self):
                if (self.path.split("?")[0].rstrip("/")
                        == "/apis/authorization.k8s.io/v1"
                        "/selfsubjectaccessreviews"):
                    # SelfSubjectAccessReview (registry/authorization/
                    # selfsubjectaccessreview/rest.go): any AUTHENTICATED
                    # caller may ask "can I ...?" about itself — the
                    # kubectl auth can-i backend.  Anonymous callers are
                    # rejected (system:unauthenticated has no SSAR grant
                    # upstream; answering would let a scanner enumerate
                    # system:anonymous's grants)
                    user = self._authenticate()
                    if user is None:
                        return
                    if (outer.authenticator is not None
                            and user.name == "system:anonymous"):
                        self._status(403, "Forbidden",
                                     "anonymous cannot create "
                                     "selfsubjectaccessreviews")
                        return
                    try:
                        body = self._body()
                    except ValueError:
                        self._status(400, "BadRequest", "invalid JSON")
                        return
                    ra = ((body.get("spec") or {})
                          .get("resourceAttributes") or {})
                    # subresource folds into the resource string exactly
                    # as the serving path authorizes ("pods/exec")
                    resource = ra.get("resource", "")
                    if ra.get("subresource"):
                        resource = f"{resource}/{ra['subresource']}"
                    allowed = (outer.authorizer is None
                               or outer.authorizer.authorize(
                                   user,
                                   ra.get("verb", ""),
                                   resource,
                                   ra.get("namespace", ""),
                                   ra.get("name", "")))
                    self._send({
                        "kind": "SelfSubjectAccessReview",
                        "apiVersion": "authorization.k8s.io/v1",
                        "status": {"allowed": bool(allowed)},
                    }, code=201)
                    return
                if self.path.partition("?")[0].startswith("/debug"):
                    # debug POST verbs route through the same shared
                    # table as the GETs (runtime/ledger.py debug_post)
                    # — currently /debug/capacity/enact: run ONE
                    # guarded actuation round NOW (?dryRun=1 decides +
                    # records without mutating).  Inflight-exempt like
                    # its siblings
                    from kubernetes_tpu.runtime.ledger import debug_post

                    path, _, query = self.path.partition("?")
                    res = debug_post(path, query)
                    if res is None:
                        self._status(404, "NotFound", self.path)
                        return
                    code, body = res
                    if code != 200:
                        try:
                            msg = json.loads(body).get("error", "")
                        except Exception:  # noqa: BLE001
                            msg = body.decode(errors="replace")
                        reason = ("Conflict" if code == 409
                                  else "InternalError")
                        self._status(code, reason, msg)
                        return
                    self._send_text(body + b"\n", ct="application/json")
                    return
                r = outer._route(self.path)
                if r is None:
                    self._status(404, "NotFound", self.path)
                    return
                kind, ns, name, sub = r
                if kind == "@proxy":
                    if self._authorize("create", "proxy") is None:
                        return
                    # before _body(): the proxy relays the raw stream itself
                    self._proxy(ns)
                    return
                # subresources authorize as "<resource>/<sub>" (RBAC rules
                # must name them explicitly, e.g. "pods/binding")
                if self._authorize(
                    "create", f"{kind}/{sub}" if sub else kind, ns, name
                ) is None:
                    return
                try:
                    body = self._body()
                except ValueError:
                    self._status(400, "BadRequest", "invalid JSON")
                    return
                try:
                    if kind == "pods" and sub == "eviction":
                        # policy/v1beta1 Eviction (registry/core/pod/rest/
                        # eviction.go): delete only if every matching PDB
                        # still allows a disruption; a blocked eviction is
                        # 429 TooManyRequests (kubectl drain retries it)
                        from kubernetes_tpu.api.labels import (
                            selector_from_label_selector,
                        )

                        pod = outer.cluster.get("pods", ns, name)
                        if pod is None:
                            self._status(404, "NotFound", f"pod {ns}/{name}")
                            return
                        with outer._write_lock:
                            matching = []
                            for pdb in outer.cluster.list(
                                    "poddisruptionbudgets"):
                                if pdb.metadata.namespace != ns:
                                    continue
                                sel = selector_from_label_selector(
                                    pdb.selector or {})
                                if sel is not None and sel.matches(
                                        pod.labels):
                                    matching.append(pdb)
                            blocked = next(
                                (p.metadata.name for p in matching
                                 if p.disruptions_allowed <= 0), None)
                            if blocked is not None:
                                # a blocked eviction is retryable once the
                                # disruption window reopens: same 429 +
                                # Retry-After construction as the limiter
                                fc = outer.flow_control
                                self._too_many_requests(
                                    "Cannot evict pod as it would "
                                    f"violate the pod's disruption "
                                    f"budget {blocked!r}",
                                    fc.config.retry_after_s
                                    if fc is not None else 1.0,
                                )
                                return
                            # consume the budget immediately (the registry
                            # decrements before the async controller
                            # recomputes, closing the thundering-drain race)
                            import dataclasses as _dc

                            for pdb in matching:
                                outer.cluster.update(
                                    "poddisruptionbudgets",
                                    _dc.replace(
                                        pdb, disruptions_allowed=max(
                                            0,
                                            pdb.disruptions_allowed - 1)))
                            outer.cluster.delete("pods", ns, name)
                        self._status(201, "Created", "eviction granted")
                        return
                    if kind == "pods" and sub == "exec":
                        # pods/exec subresource (registry/core/pod/rest/
                        # subresources.go ExecREST; the reference upgrades
                        # to SPDY streams and proxies the kubelet's :10250
                        # /exec — this plane's network is the cluster
                        # object, so the dispatch rides the kubelet's
                        # registered exec handler and the result returns
                        # as one JSON document)
                        pod = outer.cluster.get("pods", ns, name)
                        if pod is None:
                            self._status(404, "NotFound", f"pod {ns}/{name}")
                            return
                        node = getattr(pod.spec, "node_name", "") or ""
                        fn = outer.cluster.node_exec.get(node)
                        if fn is None:
                            self._status(
                                501, "NotImplemented",
                                f"node {node!r} has no exec-capable "
                                "runtime (hollow kubelets serve no exec)")
                            return
                        command = body.get("command") or []
                        if not command:
                            self._status(400, "BadRequest", "empty command")
                            return
                        try:
                            res = fn(ns, name, body.get("container", ""),
                                     command,
                                     float(body.get("timeout") or 10.0))
                        except KeyError as e:
                            self._status(404, "NotFound", str(e))
                            return
                        except Exception as e:  # runtime down mid-exec
                            self._status(500, "InternalError", str(e))
                            return
                        self._send({
                            "kind": "ExecResult",
                            "stdout": res.get("stdout", ""),
                            "stderr": res.get("stderr", ""),
                            "exitCode": int(res.get("exit_code", 0)),
                        })
                        return
                    if kind == "pods" and sub == "binding":
                        # Binding subresource: {"target": {"name": node}}
                        node = (body.get("target") or {}).get("name", "")
                        pod = outer.cluster.get("pods", ns, name)
                        if pod is None:
                            self._status(404, "NotFound", f"pod {ns}/{name}")
                            return
                        # cross-component trace propagation (utils/
                        # trace.py): a scheduler that carried its cycle's
                        # traceparent gets the trace id stamped onto the
                        # bound pod, joining this bind to the cycle span
                        from kubernetes_tpu.utils.trace import trace_id_of

                        tid = trace_id_of(
                            self.headers.get("Traceparent", "")
                        )
                        if not outer.cluster.bind(pod, node, trace_id=tid):
                            self._status(409, "Conflict",
                                         "pod already bound or gone")
                            return
                        self._status(201, "Created", "binding recorded")
                        return
                    if kind not in LIST_KINDS and not outer.cluster.has_kind(
                        kind
                    ):
                        self._status(404, "NotFound", f"unknown resource {kind}")
                        return
                    # path namespace first: admission plugins must see the
                    # namespace the object actually lands in
                    meta = body.setdefault("metadata", {})
                    if ns and not meta.get("namespace"):
                        meta["namespace"] = ns
                    # the registry stamps creation time (ObjectMeta
                    # PrepareForCreate); age-based reconcilers (csrcleaner,
                    # token cleaner) depend on it
                    meta.setdefault("creationTimestamp", time.time())
                    if kind == "certificatesigningrequests":
                        # the registry stamps the REQUESTOR identity from
                        # authn (csr strategy PrepareForCreate) — a client
                        # must not be able to claim someone else's — and
                        # strips any client-supplied status (a preset
                        # certificate/Approved condition would be adopted
                        # as if the signer granted it)
                        body.pop("status", None)
                        user = outer.current_user()
                        if user is not None:
                            csr_spec = body.setdefault("spec", {})
                            csr_spec["requestorUsername"] = user.name
                            csr_spec["requestorGroups"] = list(user.groups)
                    # pre-lock admission phase (incl. webhook HTTP
                    # dispatch — see _admit_split), then one write at a
                    # time: quota admission is a read-then-create, so it
                    # runs atomically with the create under the lock
                    # (etcd serializes writes the same way)
                    body = outer._admit_split("CREATE", kind, body,
                                              locked=False)
                    with outer._write_lock:
                        body = outer._admit_split("CREATE", kind, body,
                                                  locked=True)
                        # schema validation AFTER admission: mutating
                        # plugins must not produce out-of-schema objects
                        if "." in kind:  # persist the STORAGE version
                            body = outer._cr_to_storage_version(kind, body)
                        outer._validate_extension(kind, body)
                        obj = _decode(kind, body)
                        rv = outer.cluster.create(kind, obj)
                    if kind == "customresourcedefinitions":
                        # establish the new REST resource immediately
                        from kubernetes_tpu.apiserver.extensions import (
                            crd_storage_kind,
                        )

                        outer.cluster.register_kind(crd_storage_kind(body))
                    out = object_to_dict(kind, obj)
                    out.setdefault("metadata", {})["resourceVersion"] = str(rv)
                    self._send(out, 201)
                except AdmissionDenied as e:
                    self._status(403, "Forbidden", str(e))
                except ConflictError as e:
                    self._status(409, "AlreadyExists", str(e))
                except Exception as e:
                    self._status(422, "Invalid", f"{type(e).__name__}: {e}")

            def do_PATCH(self):
                """PATCH: application/merge-patch+json (RFC 7386, null
                deletes a key — also accepted for strategic-merge, the
                closest semantics this object model has) or
                application/json-patch+json (RFC 6902) — apimachinery
                types.PatchType.  Applies against the stored wire form,
                then rides the normal UPDATE pipeline (admission +
                validation + CAS against the revision read here)."""
                r = outer._route(self.path)
                if r is not None and r[0] == "@proxy":
                    if self._authorize("patch", "proxy") is None:
                        return
                    self._proxy(r[1])
                    return
                if r is None or not r[2]:
                    self._status(404, "NotFound", self.path)
                    return
                kind, ns, name, sub = r
                if self._authorize(
                    "patch", f"{kind}/{sub}" if sub else kind, ns, name
                ) is None:
                    return
                try:
                    patch = self._body()
                except ValueError:
                    self._status(400, "BadRequest", "invalid JSON")
                    return
                cur, rv = outer.cluster.get_with_rv(kind, ns, name)
                if cur is None:
                    self._status(404, "NotFound", f"{kind} {ns}/{name}")
                    return
                body = dict(object_to_dict(kind, cur))
                if "." in kind:
                    # multi-version CR: the patch is expressed in the
                    # REQUEST version, so apply it there — convert the
                    # stored object up, merge, and let the write seam
                    # convert the result back to storage
                    try:
                        body = dict(
                            outer._cr_to_request_version(kind, body))
                    except Exception as e:
                        self._status(500, "InternalError",
                                     f"conversion failed: {e}")
                        return
                ctype = self.headers.get("Content-Type", "")
                try:
                    if "json-patch" in ctype:
                        from kubernetes_tpu.apiserver.webhooks import (
                            apply_json_patch,
                        )

                        body = apply_json_patch(body, patch)
                    else:
                        def merge(dst, src):
                            out = dict(dst)
                            for k, v in src.items():
                                if v is None:
                                    out.pop(k, None)
                                elif (isinstance(v, dict)
                                      and isinstance(out.get(k), dict)):
                                    out[k] = merge(out[k], v)
                                else:
                                    out[k] = v
                            return out

                        body = merge(body, patch)
                except Exception as e:
                    self._status(422, "Invalid", f"patch failed: {e}")
                    return
                try:
                    meta = body.setdefault("metadata", {})
                    if ns and not meta.get("namespace"):
                        meta["namespace"] = ns
                    meta["name"] = name  # a patch cannot rename
                    body = outer._admit_split("UPDATE", kind, body,
                                              locked=False)
                    with outer._write_lock:
                        body = outer._admit_split("UPDATE", kind, body,
                                                  locked=True)
                        if "." in kind:  # persist the STORAGE version
                            body = outer._cr_to_storage_version(kind, body)
                        outer._validate_extension(kind, body)
                        obj = _decode(kind, body)
                        if kind in (
                            "replicasets", "deployments", "jobs"
                        ) and not meta.get("uid"):
                            if cur is not None and hasattr(cur, "uid"):
                                obj.uid = cur.uid
                        new_rv = outer.cluster.update(kind, obj,
                                                      expect_rv=rv)
                    out = dict(object_to_dict(kind, obj))
                    out["metadata"] = dict(out.get("metadata") or {})
                    out["metadata"]["resourceVersion"] = str(new_rv)
                    self._send(out)
                except AdmissionDenied as e:
                    self._status(403, "Forbidden", str(e))
                except ConflictError as e:
                    self._status(409, "Conflict", str(e))
                except Exception as e:
                    self._status(422, "Invalid", f"{type(e).__name__}: {e}")

            def do_PUT(self):
                r = outer._route(self.path)
                if r is not None and r[0] == "@proxy":
                    if self._authorize("update", "proxy") is None:
                        return
                    self._proxy(r[1])
                    return
                if r is None or not r[2]:
                    self._status(404, "NotFound", self.path)
                    return
                kind, ns, name, sub = r
                if self._authorize(
                    "update", f"{kind}/{sub}" if sub else kind, ns, name
                ) is None:
                    return
                try:
                    body = self._body()
                except ValueError:
                    self._status(400, "BadRequest", "invalid JSON")
                    return
                try:
                    meta = body.setdefault("metadata", {})
                    if ns and not meta.get("namespace"):
                        meta["namespace"] = ns  # path ns first, as on POST
                    body = outer._admit_split("UPDATE", kind, body,
                                              locked=False)
                    with outer._write_lock:
                        body = outer._admit_split("UPDATE", kind, body,
                                                  locked=True)
                        if "." in kind:  # persist the STORAGE version
                            body = outer._cr_to_storage_version(kind, body)
                        outer._validate_extension(kind, body)
                        expect = meta.get("resourceVersion")
                        obj = _decode(kind, body)
                        if kind in (
                            "replicasets", "deployments", "jobs"
                        ) and not meta.get("uid"):
                            # keep the stored identity: a spec-only manifest
                            # must not orphan the owner's pods behind a
                            # fresh uid
                            cur = outer.cluster.get(kind, ns, name)
                            if cur is not None:
                                obj.uid = cur.uid
                        rv = outer.cluster.update(
                            kind, obj,
                            expect_rv=int(expect) if expect else None,
                        )
                    out = object_to_dict(kind, obj)
                    out.setdefault("metadata", {})["resourceVersion"] = str(rv)
                    self._send(out)
                except AdmissionDenied as e:
                    self._status(403, "Forbidden", str(e))
                except ConflictError as e:
                    self._status(409, "Conflict", str(e))
                except Exception as e:
                    self._status(422, "Invalid", f"{type(e).__name__}: {e}")

            def do_DELETE(self):
                r = outer._route(self.path)
                if r is not None and r[0] == "@proxy":
                    if self._authorize("delete", "proxy") is None:
                        return
                    self._proxy(r[1])
                    return
                if r is None or not r[2]:
                    self._status(404, "NotFound", self.path)
                    return
                kind, ns, name, sub = r
                if self._authorize(
                    "delete", f"{kind}/{sub}" if sub else kind, ns, name
                ) is None:
                    return
                if kind not in LIST_KINDS and not outer.cluster.has_kind(kind):
                    self._status(404, "NotFound", f"unknown resource {kind}")
                    return
                store_ns = "" if kind in ("nodes",) or (
                    kind in _DICT_KINDS and _DICT_KINDS[kind] == ""
                ) else ns
                cur = outer.cluster.get(kind, store_ns, name)
                if cur is None:
                    self._status(404, "NotFound", f"{kind} {ns}/{name}")
                    return
                try:
                    outer._admit(
                        "DELETE", kind,
                        {"metadata": {"namespace": store_ns, "name": name}},
                    )
                except AdmissionDenied as e:
                    self._status(403, "Forbidden", str(e))
                    return
                if kind == "namespaces":
                    # graceful namespace teardown: flip to Terminating and
                    # let the namespace controller empty + finalize it
                    # (pkg/registry/core/namespace strategy +
                    # pkg/controller/namespace)
                    obj = dict(cur) if isinstance(cur, dict) else cur
                    status = dict(obj.get("status") or {})
                    if status.get("phase") != "Terminating":
                        obj = dict(obj)
                        obj["status"] = {**status, "phase": "Terminating"}
                        try:
                            outer.cluster.update(kind, obj)
                        except ConflictError:
                            # the controller finalized it between our GET
                            # and UPDATE — deletion already done
                            pass
                    self._status(200, "Success", "namespace terminating")
                    return
                if kind == "customresourcedefinitions":
                    # un-establishing a CRD deletes its instances too
                    # (apiextensions finalizer semantics)
                    from kubernetes_tpu.apiserver.extensions import (
                        crd_storage_kind,
                    )

                    sk = crd_storage_kind(cur)
                    if outer.cluster.has_kind(sk):
                        for inst in list(outer.cluster.list(sk)):
                            outer.cluster.delete(
                                sk, inst.get("namespace", ""),
                                inst.get("name", ""),
                            )
                        outer.cluster.unregister_kind(sk)
                outer.cluster.delete(kind, store_ns, name)
                self._status(200, "Success", "deleted")

        # audit wiring: the event is written AT send_response time — before
        # the client can observe the response — so a caller that gets its
        # reply and immediately stops the server cannot race the audit
        # append (ResponseComplete ordering)
        real_send_response = Handler.send_response

        def send_response(self, code, message=None):
            verb = getattr(self, "_audit_verb", None)
            if verb is not None:
                self._audit_verb = None
                outer._audit(verb, self.path, code, handler=self)
            real_send_response(self, code, message)

        Handler.send_response = send_response
        # policy levels Request/RequestResponse need the bodies: stash the
        # parsed request body and the outgoing response object on the
        # handler as they pass through the existing seams
        real_body = Handler._body

        def _body_stash(self):
            b = real_body(self)
            self._audit_req_body = b
            return b

        Handler._body = _body_stash
        real_send = Handler._send

        def _send_stash(self, obj, code: int = 200):
            self._audit_resp_obj = obj
            real_send(self, obj, code)

        Handler._send = _send_stash
        for method, verb in (
            ("do_POST", "create"), ("do_PUT", "update"),
            ("do_DELETE", "delete"),
        ):
            inner = getattr(Handler, method)

            def wrapped(self, _inner=inner, _verb=verb):
                self._audit_verb = _verb
                # handler instances persist per keep-alive connection:
                # clear the body stashes so a bodiless request (DELETE)
                # cannot inherit the previous request's body into its
                # audit event
                self._audit_req_body = None
                self._audit_resp_obj = None
                try:
                    _inner(self)
                finally:
                    if getattr(self, "_audit_verb", None) is not None:
                        # the handler died before ANY response: still one
                        # event per write attempt (code 0 = no response)
                        self._audit_verb = None
                        outer._audit(_verb, self.path, 0, handler=self)

            setattr(Handler, method, wrapped)
        # APF-style inflight limiting (apiserver/fairness.py), OUTERMOST
        # wrapper: over-limit requests are rejected with 429 + Retry-After
        # before authn/admission/audit spend anything on them (the
        # reference's filter-chain order: WithMaxInFlightLimit wraps the
        # whole handler).  The liveness surface and long-lived watch
        # streams are exempt — health probes must work under overload,
        # and a watch would pin a readonly slot for its whole lifetime.
        if outer.flow_control is not None:
            # the debug family's exemption derives from the SAME table
            # that routes it (runtime/ledger.py DEBUG_ENDPOINTS), so a
            # newly registered endpoint is exempt on both servers by
            # construction instead of by remembering this tuple
            from kubernetes_tpu.runtime.ledger import DEBUG_ENDPOINTS

            exempt = ("/healthz", "/livez", "/readyz", "/metrics",
                      "/version", "/debug", "/debug/") \
                + tuple(DEBUG_ENDPOINTS)
            for method in ("do_GET", "do_POST", "do_PUT", "do_PATCH",
                           "do_DELETE"):
                inner = getattr(Handler, method)
                mutating = method != "do_GET"

                def limited(self, _inner=inner, _mutating=mutating):
                    path = self.path.partition("?")[0]
                    if path in exempt or path.startswith("/api/v1/watch"):
                        return _inner(self)
                    from kubernetes_tpu.apiserver.fairness import (
                        TooManyRequests,
                    )

                    fc = outer.flow_control
                    flow = fc.flow_of(
                        self.headers.get("Authorization", ""),
                        self.client_address[0],
                    )
                    try:
                        lim = fc.acquire(flow, _mutating)
                    except TooManyRequests as e:
                        self._too_many_requests(str(e), e.retry_after_s)
                        return
                    try:
                        _inner(self)
                    finally:
                        if lim is not None:
                            lim.release()

                setattr(Handler, method, limited)
        return Handler
