"""Authentication + RBAC authorization for the REST layer.

Reference: the apiserver handler chain wires WithAuthentication and
WithAuthorization around every request
(staging/src/k8s.io/apiserver/pkg/server/config.go:544-550); the stock
authorizer is RBAC (plugin/pkg/auth/authorizer/rbac/rbac.go) evaluating
Role/ClusterRole rules bound to users and groups
(rbac.go RuleAllows + VisitRulesFor); bearer tokens resolve through a
union of authenticators — bootstrap-token secrets
(plugin/pkg/auth/authenticator/token/bootstrap/bootstrap.go:116-180,
user ``system:bootstrap:<id>``, group ``system:bootstrappers``) and
service-account token secrets (pkg/serviceaccount/jwt.go, user
``system:serviceaccount:<ns>:<name>``).

This module reproduces those semantics over the LocalCluster store:

  * ``TokenAuthenticator`` resolves ``Authorization: Bearer`` tokens
    against (a) an in-process static table (the kubeadm admin
    credential), (b) ``bootstrap.kubernetes.io/token`` Secrets in
    kube-system, (c) ``kubernetes.io/service-account-token`` Secrets,
    and (d) generic ``kubernetes-tpu/auth-token`` Secrets carrying an
    explicit user+groups payload (the stand-in for client-cert
    identities like ``system:node:<name>`` — this snapshot's TLS
    bootstrap/CSR machinery distilled to its authentication outcome).
  * ``RBACAuthorizer`` evaluates live Role/ClusterRole(+Binding)
    objects from the store; ``system:masters`` is the hardwired
    superuser group (rbac.go:76-80 does the same via the legacy
    cluster-admin binding).
  * ``bootstrap_policy()`` is the default policy set kubeadm installs
    (plugin/pkg/auth/authorizer/rbac/bootstrappolicy/policy.go).

Unauthenticated requests run as ``system:anonymous`` in group
``system:unauthenticated`` (apiserver/pkg/authentication/request/
anonymous) — with RBAC on, that identity has no bindings, so anonymous
writes fail closed with 403; a *present but invalid* token is 401.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass(frozen=True)
class UserInfo:
    name: str
    groups: Tuple[str, ...] = ()

    def in_group(self, g: str) -> bool:
        return g in self.groups


ANONYMOUS = UserInfo("system:anonymous", ("system:unauthenticated",))
AUTHENTICATED = "system:authenticated"
SUPERUSER_GROUP = "system:masters"
NODES_GROUP = "system:nodes"
BOOTSTRAP_GROUP = "system:bootstrappers"

BOOTSTRAP_TOKEN_TYPE = "bootstrap.kubernetes.io/token"
SA_TOKEN_TYPE = "kubernetes.io/service-account-token"
AUTH_TOKEN_TYPE = "kubernetes-tpu/auth-token"
TOKEN_NS = "kube-system"


class AuthenticationError(Exception):
    """Presented credentials are invalid (HTTP 401) — distinct from no
    credentials at all, which degrades to the anonymous identity."""


def _secret_data(s: dict) -> dict:
    """Secrets carry payloads under .data (stringData accepted too);
    flattened dict-kind storage may hold them at top level."""
    out = {}
    out.update(s.get("data") or {})
    out.update(s.get("stringData") or {})
    return out


class TokenAuthenticator:
    """Union token authenticator over the store + a static table."""

    def __init__(self, cluster, static: Optional[Dict[str, UserInfo]] = None):
        self.cluster = cluster
        self._static: Dict[str, UserInfo] = dict(static or {})
        # LOCK ORDER CONSTRAINT: _on_event runs INSIDE the cluster's write
        # lock (store fan-out is synchronous), so nothing here may hold a
        # lock that authenticate() also holds while it calls INTO the
        # cluster — that is an ABBA deadlock wedging the whole apiserver.
        # The invalidation protocol is therefore lock-free on the event
        # side: _on_event only bumps a generation counter (its own tiny
        # lock, never held around cluster calls), and authenticate builds
        # the index outside any shared lock, publishing it only if the
        # generation is unchanged (a racing invalidation wins).
        self._gen = 0
        self._gen_lock = threading.Lock()
        # token -> UserInfo index over secret-backed credentials:
        # authenticate() is on every request's path, a linear store scan
        # there is O(fleet) per heartbeat
        self._index: Optional[Dict[str, UserInfo]] = None
        self._index_gen = -1
        self._watching = False
        self._watch_lock = threading.Lock()

    def add_static(self, token: str, name: str,
                   groups: Iterable[str] = ()) -> None:
        self._static = {**self._static,
                        token: UserInfo(name, tuple(groups) + (AUTHENTICATED,))}

    def _on_event(self, event, kind, obj) -> None:
        if kind == "secrets":
            with self._gen_lock:
                self._gen += 1

    @staticmethod
    def _secret_identity(s: dict) -> Optional[Tuple[str, UserInfo]]:
        """(token, identity) a Secret grants, or None."""
        stype = s.get("type", "")
        data = _secret_data(s)
        if stype == BOOTSTRAP_TOKEN_TYPE:
            # bootstrap.go:116-180: token is <id>.<secret>, both halves
            # must be present, usage-bootstrap-authentication must be true
            tid = data.get("token-id", "")
            tsec = data.get("token-secret", "")
            if (tid and tsec and s.get("namespace") == TOKEN_NS
                    and str(data.get(
                        "usage-bootstrap-authentication", "true"
                    )).lower() == "true"):
                groups = tuple(
                    g.strip() for g in str(
                        data.get("auth-extra-groups", "")
                    ).split(",") if g.strip()
                )
                return f"{tid}.{tsec}", UserInfo(
                    f"system:bootstrap:{tid}",
                    (BOOTSTRAP_GROUP,) + groups + (AUTHENTICATED,),
                )
        elif stype == SA_TOKEN_TYPE:
            tok = data.get("token", "")
            ns = data.get("namespace") or s.get("namespace", "default")
            sa = (data.get("serviceAccountName")
                  or s.get("annotations", {}).get(
                      "kubernetes.io/service-account.name", ""))
            if tok and sa:
                return tok, UserInfo(
                    f"system:serviceaccount:{ns}:{sa}",
                    ("system:serviceaccounts",
                     f"system:serviceaccounts:{ns}",
                     AUTHENTICATED),
                )
        elif stype == AUTH_TOKEN_TYPE:
            tok = data.get("token", "")
            if tok and data.get("user"):
                groups = data.get("groups") or []
                if isinstance(groups, str):
                    groups = [g for g in groups.split(",") if g]
                return tok, UserInfo(
                    data["user"], tuple(groups) + (AUTHENTICATED,))
        return None

    def _build_index(self) -> Dict[str, UserInfo]:
        index: Dict[str, UserInfo] = {}
        if self.cluster.has_kind("secrets"):
            for s in self.cluster.list("secrets"):
                if not isinstance(s, dict):
                    continue
                hit = self._secret_identity(s)
                if hit is not None:
                    index[hit[0]] = hit[1]
        return index

    def authenticate(self, token: str) -> UserInfo:
        """Resolve a bearer token or raise AuthenticationError."""
        hit = self._static.get(token)  # copy-on-write dict: lock-free read
        if hit is not None:
            return hit
        with self._watch_lock:
            if not self._watching:
                # lazy: subscribe for invalidation on the first lookup.
                # watch() replays synchronously into _on_event, which only
                # bumps the generation — no lock cycle with the store.
                self._watching = True
                self.cluster.watch(self._on_event)
        index = self._index
        with self._gen_lock:
            gen = self._gen
            fresh = self._index_gen == gen and index is not None
        if not fresh:
            index = self._build_index()  # cluster reads: NO auth lock held
            with self._gen_lock:
                if self._gen == gen:
                    # no invalidation raced the build: publish
                    self._index = index
                    self._index_gen = gen
                # else: leave stale markers; next request rebuilds
        hit = index.get(token)
        if hit is not None:
            return hit
        raise AuthenticationError("unknown bearer token")


# ---------------------------------------------------------------- RBAC


def _match(items, want: str) -> bool:
    return "*" in items or want in items


def _rule_allows(rule: dict, verb: str, resource: str, name: str) -> bool:
    """rbac/v1 PolicyRule semantics (rbac.go RuleAllows): verbs and
    resources with '*' wildcard; subresources must be named explicitly
    ('pods/binding') or covered by '*'; resourceNames (when present)
    restrict to listed objects except for create (no name yet)."""
    verbs = rule.get("verbs") or []
    resources = rule.get("resources") or []
    if not _match(verbs, verb):
        return False
    base = resource.split("/", 1)[0]
    if not ("*" in resources or resource in resources
            or (("/" not in resource) and base in resources)
            or f"{base}/*" in resources):
        return False
    rnames = rule.get("resourceNames") or []
    if rnames and verb != "create" and name not in rnames:
        return False
    return True


def _subject_matches(subj: dict, user: UserInfo) -> bool:
    kind = subj.get("kind", "")
    name = subj.get("name", "")
    if kind == "User":
        return name == user.name
    if kind == "Group":
        return user.in_group(name)
    if kind == "ServiceAccount":
        ns = subj.get("namespace", "default")
        return user.name == f"system:serviceaccount:{ns}:{name}"
    return False


def _subject_key(subj: dict) -> Optional[Tuple[str, str]]:
    """Index key a binding subject grants to (the inversion of
    _subject_matches): Users and ServiceAccounts collapse to the user-name
    axis, Groups to the group axis."""
    kind = subj.get("kind", "")
    name = subj.get("name", "")
    if kind == "User":
        return ("u", name)
    if kind == "Group":
        return ("g", name)
    if kind == "ServiceAccount":
        ns = subj.get("namespace", "default")
        return ("u", f"system:serviceaccount:{ns}:{name}")
    return None


class RBACAuthorizer:
    """Role/ClusterRole(+Binding) evaluation over live store objects.

    authorize() is on EVERY request's path — at kubemark fleet scale each
    heartbeat is authorized, so a linear scan over bindings (with a role
    re-fetch per binding) is the same O(fleet) trap the authenticator
    comment warns about (VERDICT r3 weak #4).  The fix is the same
    generation-invalidated index, under the SAME lock-order constraint
    (see TokenAuthenticator.__init__): events only bump a generation,
    the index is built outside any shared lock and published only if no
    invalidation raced it.  The index maps subject -> [(scope_ns | None,
    rules)] with roleRefs resolved at build time, so the hot path is a
    few dict lookups + rule matches for the user's own subjects.
    Reference semantics: rbac.go VisitRulesFor (which is also scan-based;
    the index is this snapshot's heartbeat-volume adaptation)."""

    _KINDS = ("clusterrolebindings", "rolebindings", "clusterroles", "roles")

    def __init__(self, cluster):
        self.cluster = cluster
        self._gen = 0
        self._gen_lock = threading.Lock()
        self._index: Optional[Dict[Tuple[str, str], List[tuple]]] = None
        self._index_gen = -1
        self._watching = False
        self._watch_lock = threading.Lock()

    def _on_event(self, event, kind, obj) -> None:
        if kind in self._KINDS:
            with self._gen_lock:
                self._gen += 1

    def _rules_for(self, kind: str, ns: str, role_name: str) -> List[dict]:
        if not self.cluster.has_kind(kind):
            return []
        role = self.cluster.get(kind, ns, role_name)
        if role is None:
            return []
        return list(role.get("rules") or [])

    def _build_index(self) -> Dict[Tuple[str, str], List[tuple]]:
        index: Dict[Tuple[str, str], List[tuple]] = {}

        def add(binding: dict, scope_ns: Optional[str]) -> None:
            ref = binding.get("roleRef") or {}
            if scope_ns is not None and ref.get("kind") != "ClusterRole":
                rules = self._rules_for("roles", scope_ns, ref.get("name", ""))
            else:
                rules = self._rules_for("clusterroles", "", ref.get("name", ""))
            if not rules:
                return
            entry = (scope_ns, tuple(rules))
            for s in binding.get("subjects") or []:
                key = _subject_key(s)
                if key is not None:
                    index.setdefault(key, []).append(entry)

        if self.cluster.has_kind("clusterrolebindings"):
            for b in self.cluster.list("clusterrolebindings"):
                add(b, None)
        if self.cluster.has_kind("rolebindings"):
            for b in self.cluster.list("rolebindings"):
                add(b, b.get("namespace") or "default")
        return index

    def _current_index(self) -> Dict[Tuple[str, str], List[tuple]]:
        with self._watch_lock:
            if not self._watching:
                # lazy: subscribe for invalidation on the first check.
                # watch() replays synchronously into _on_event, which only
                # bumps the generation — no lock cycle with the store.
                self._watching = True
                self.cluster.watch(self._on_event)
        index = self._index
        with self._gen_lock:
            gen = self._gen
            fresh = self._index_gen == gen and index is not None
        if not fresh:
            index = self._build_index()  # cluster reads: NO auth lock held
            with self._gen_lock:
                if self._gen == gen:
                    self._index = index
                    self._index_gen = gen
                # else: leave stale markers; next request rebuilds
        return index

    def authorize(self, user: UserInfo, verb: str, resource: str,
                  namespace: str = "", name: str = "") -> bool:
        if user.in_group(SUPERUSER_GROUP):
            return True  # the hardwired superuser escape hatch
        index = self._current_index()
        keys = [("u", user.name)] + [("g", g) for g in user.groups]
        for key in keys:
            for scope_ns, rules in index.get(key, ()):
                # cluster-scoped grants apply everywhere; namespaced
                # grants only inside their own namespace
                if scope_ns is not None and (
                        not namespace or scope_ns != namespace):
                    continue
                for rule in rules:
                    if _rule_allows(rule, verb, resource, name):
                        return True
        return False


class AlwaysAllowAuthorizer:
    def authorize(self, user, verb, resource, namespace="", name="") -> bool:
        return True


# -------------------------------------------------- default policy set


def bootstrap_policy() -> List[Tuple[str, dict]]:
    """The default roles+bindings kubeadm installs — the minimal subset
    of bootstrappolicy/policy.go this framework's components exercise.
    Returned as (kind, object) pairs for idempotent ensure-create."""
    return [
        ("clusterroles", {
            "namespace": "", "name": "cluster-admin",
            "rules": [{"verbs": ["*"], "resources": ["*"]}],
        }),
        ("clusterrolebindings", {
            "namespace": "", "name": "cluster-admin",
            "subjects": [{"kind": "Group", "name": SUPERUSER_GROUP}],
            "roleRef": {"kind": "ClusterRole", "name": "cluster-admin"},
        }),
        # kubeadm:node-bootstrapper: a joining machine may register its
        # node and heartbeat its lease — nothing else
        ("clusterroles", {
            "namespace": "", "name": "system:node-bootstrapper",
            "rules": [
                {"verbs": ["create", "get"], "resources": ["nodes"]},
                {"verbs": ["create", "update", "get"],
                 "resources": ["leases"]},
                # the TLS-bootstrap analog: submit a CSR and poll it
                # (certificates flow, runtime/certificates.py)
                {"verbs": ["create", "get"],
                 "resources": ["certificatesigningrequests"]},
            ],
        }),
        ("clusterrolebindings", {
            "namespace": "", "name": "kubeadm:node-bootstrapper",
            "subjects": [{"kind": "Group", "name": BOOTSTRAP_GROUP}],
            "roleRef": {"kind": "ClusterRole",
                        "name": "system:node-bootstrapper"},
        }),
        # system:node: what the hollow kubelet needs (the node authorizer
        # distilled into RBAC; NodeRestriction admission narrows writes
        # to the kubelet's OWN objects)
        ("clusterroles", {
            "namespace": "", "name": "system:node",
            "rules": [
                {"verbs": ["get", "list", "watch", "update", "patch"],
                 "resources": ["nodes", "nodes/status"]},
                {"verbs": ["get", "list", "watch"],
                 "resources": ["pods", "services", "endpoints"]},
                {"verbs": ["update", "patch"],
                 "resources": ["pods/status"]},
                {"verbs": ["create", "update", "get"],
                 "resources": ["leases"]},
                {"verbs": ["create"], "resources": ["events"]},
            ],
        }),
        ("clusterrolebindings", {
            "namespace": "", "name": "system:node",
            "subjects": [{"kind": "Group", "name": NODES_GROUP}],
            "roleRef": {"kind": "ClusterRole", "name": "system:node"},
        }),
        # discovery for any authenticated identity (read-only basics)
        ("clusterroles", {
            "namespace": "", "name": "system:basic-user",
            "rules": [{"verbs": ["get", "list"],
                       "resources": ["namespaces"]}],
        }),
        ("clusterrolebindings", {
            "namespace": "", "name": "system:basic-user",
            "subjects": [{"kind": "Group", "name": AUTHENTICATED}],
            "roleRef": {"kind": "ClusterRole", "name": "system:basic-user"},
        }),
    ]


def ensure_bootstrap_policy(cluster) -> None:
    """Create the default policy objects if absent (kubeadm's
    clusterrolebinding ensure step — idempotent)."""
    from kubernetes_tpu.runtime.cluster import ConflictError

    for kind, obj in bootstrap_policy():
        cluster.register_kind(kind)
        try:
            cluster.create(kind, dict(obj))
        except ConflictError:
            pass  # already installed
