"""API extension mechanisms: CRD schema validation + lookup helpers.

Reference: staging/src/k8s.io/apiextensions-apiserver (CustomResource
Definitions — establish a new REST resource at runtime, validate instances
against spec.validation.openAPIV3Schema) and staging/src/k8s.io/
kube-aggregator (APIService — delegate a whole group/version to another
server).  The routing halves live in APIServer._route_extension; this
module holds the pure logic.
"""

from __future__ import annotations

from typing import Optional


class SchemaError(ValueError):
    """Instance does not conform to the CRD's openAPIV3Schema."""


def crd_storage_kind(crd: dict) -> str:
    spec = crd.get("spec") or {}
    plural = (spec.get("names") or {}).get("plural", "")
    return f"{plural}.{spec.get('group', '')}"


def validate_crd_spec(crd: dict) -> None:
    """The establishment-time sanity checks (customresourcedefinition
    strategy validation): group, version(s), and names.plural required."""
    spec = crd.get("spec") or {}
    if not spec.get("group"):
        raise SchemaError("spec.group is required")
    if not spec.get("version") and not spec.get("versions"):
        raise SchemaError("spec.version (or versions) is required")
    if not (spec.get("names") or {}).get("plural"):
        raise SchemaError("spec.names.plural is required")


def crd_schema(crd: dict) -> Optional[dict]:
    return ((crd.get("spec") or {}).get("validation") or {}).get(
        "openAPIV3Schema"
    )


_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
}


def validate_schema(obj, schema: dict, path: str = "") -> None:
    """Validate obj against the supported openAPIV3Schema subset: type,
    properties, required, items, enum, minimum/maximum, pattern,
    min/maxLength, min/maxItems, additionalProperties (bool or schema),
    nullable.  Raises SchemaError naming the offending path
    (apiextensions validation.go behavior)."""
    if obj is None and schema.get("nullable"):
        return
    t = schema.get("type")
    if t:
        if t == "integer":
            ok = isinstance(obj, int) and not isinstance(obj, bool)
        elif t == "number":
            ok = (
                isinstance(obj, (int, float)) and not isinstance(obj, bool)
            )
        else:
            ok = isinstance(obj, _TYPES.get(t, object))
        if not ok:
            raise SchemaError(
                f"{path or '<root>'}: expected {t}, got {type(obj).__name__}"
            )
    if "enum" in schema and obj not in schema["enum"]:
        raise SchemaError(f"{path or '<root>'}: {obj!r} not in {schema['enum']}")
    if isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if "minimum" in schema and obj < schema["minimum"]:
            raise SchemaError(f"{path}: {obj} < minimum {schema['minimum']}")
        if "maximum" in schema and obj > schema["maximum"]:
            raise SchemaError(f"{path}: {obj} > maximum {schema['maximum']}")
    if isinstance(obj, str):
        if "pattern" in schema:
            import re as _re

            if _re.search(schema["pattern"], obj) is None:
                raise SchemaError(
                    f"{path or '<root>'}: {obj!r} does not match pattern "
                    f"{schema['pattern']!r}")
        if "minLength" in schema and len(obj) < schema["minLength"]:
            raise SchemaError(f"{path}: shorter than minLength "
                              f"{schema['minLength']}")
        if "maxLength" in schema and len(obj) > schema["maxLength"]:
            raise SchemaError(f"{path}: longer than maxLength "
                              f"{schema['maxLength']}")
    if isinstance(obj, dict):
        for req in schema.get("required") or []:
            if req not in obj:
                raise SchemaError(f"{path or '<root>'}: missing required "
                                  f"property {req!r}")
        props = schema.get("properties") or {}
        for k, sub in props.items():
            if k in obj:
                validate_schema(obj[k], sub, f"{path}.{k}" if path else k)
        addl = schema.get("additionalProperties")
        if addl is not None:
            extra = [k for k in obj if k not in props]
            if addl is False and extra:
                raise SchemaError(
                    f"{path or '<root>'}: unknown properties {extra}")
            if isinstance(addl, dict):
                for k in extra:
                    validate_schema(obj[k], addl,
                                    f"{path}.{k}" if path else k)
    if isinstance(obj, list):
        if "minItems" in schema and len(obj) < schema["minItems"]:
            raise SchemaError(f"{path}: fewer than minItems "
                              f"{schema['minItems']}")
        if "maxItems" in schema and len(obj) > schema["maxItems"]:
            raise SchemaError(f"{path}: more than maxItems "
                              f"{schema['maxItems']}")
        if "items" in schema:
            for i, item in enumerate(obj):
                validate_schema(item, schema["items"], f"{path}[{i}]")


def flatten_wire_dict(d: dict, default_ns: Optional[str] = None) -> dict:
    """Wire object -> store dict: copy with flat name/namespace keys lifted
    from metadata (the single flattening used for every dict-stored kind).

    default_ns=None  -> cluster-scoped: namespace forced to ""
    default_ns="x"   -> namespaced: metadata/top-level namespace, else "x"
    """
    meta = d.get("metadata") or {}
    out = dict(d)
    out["name"] = d.get("name") or meta.get("name", "")
    out["namespace"] = (
        "" if default_ns is None
        else (d.get("namespace") or meta.get("namespace") or default_ns)
    )
    return out


def find_crd_for_kind(cluster, storage_kind: str) -> Optional[dict]:
    for crd in cluster.list("customresourcedefinitions"):
        if crd_storage_kind(crd) == storage_kind:
            return crd
    return None
