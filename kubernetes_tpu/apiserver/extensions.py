"""API extension mechanisms: CRD schema validation + lookup helpers.

Reference: staging/src/k8s.io/apiextensions-apiserver (CustomResource
Definitions — establish a new REST resource at runtime, validate instances
against spec.validation.openAPIV3Schema) and staging/src/k8s.io/
kube-aggregator (APIService — delegate a whole group/version to another
server).  The routing halves live in APIServer._route_extension; this
module holds the pure logic.
"""

from __future__ import annotations

import json

from typing import Optional


class SchemaError(ValueError):
    """Instance does not conform to the CRD's openAPIV3Schema."""


def crd_storage_kind(crd: dict) -> str:
    spec = crd.get("spec") or {}
    plural = (spec.get("names") or {}).get("plural", "")
    return f"{plural}.{spec.get('group', '')}"


def validate_crd_spec(crd: dict) -> None:
    """The establishment-time sanity checks (customresourcedefinition
    strategy validation): group, version(s), and names.plural required."""
    spec = crd.get("spec") or {}
    if not spec.get("group"):
        raise SchemaError("spec.group is required")
    if not spec.get("version") and not spec.get("versions"):
        raise SchemaError("spec.version (or versions) is required")
    if not (spec.get("names") or {}).get("plural"):
        raise SchemaError("spec.names.plural is required")
    versions = spec.get("versions") or []
    if versions:
        n_storage = sum(1 for v in versions if v.get("storage"))
        if n_storage > 1:
            raise SchemaError(
                "exactly one version may set storage: true")
        if not any(v.get("served", True) for v in versions):
            raise SchemaError("at least one version must be served")
        strategy = ((spec.get("conversion") or {}).get("strategy")
                    or "None")
        if strategy not in ("None", "Webhook"):
            raise SchemaError(
                f"unknown conversion strategy {strategy!r}")


def crd_schema(crd: dict) -> Optional[dict]:
    return ((crd.get("spec") or {}).get("validation") or {}).get(
        "openAPIV3Schema"
    )


_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
}


def validate_schema(obj, schema: dict, path: str = "") -> None:
    """Validate obj against the supported openAPIV3Schema subset: type,
    properties, required, items, enum, minimum/maximum (+ exclusive
    variants), multipleOf, pattern, min/maxLength, min/maxItems,
    uniqueItems, min/maxProperties, additionalProperties (bool or
    schema), nullable, and the composition keywords allOf / anyOf /
    oneOf / not.  Raises SchemaError naming the offending path
    (apiextensions validation.go behavior)."""
    if obj is None and schema.get("nullable"):
        return
    for sub in schema.get("allOf") or []:
        validate_schema(obj, sub, path)
    any_of = schema.get("anyOf")
    if any_of:
        errs = []
        for sub in any_of:
            try:
                validate_schema(obj, sub, path)
                break
            except SchemaError as e:
                errs.append(str(e))
        else:
            raise SchemaError(
                f"{path or '<root>'}: matches no anyOf branch "
                f"({'; '.join(errs[:3])})")
    one_of = schema.get("oneOf")
    if one_of:
        matched = 0
        for sub in one_of:
            try:
                validate_schema(obj, sub, path)
                matched += 1
            except SchemaError:
                pass
        if matched != 1:
            raise SchemaError(
                f"{path or '<root>'}: matches {matched} oneOf branches "
                "(need exactly 1)")
    if "not" in schema:
        try:
            validate_schema(obj, schema["not"], path)
        except SchemaError:
            pass
        else:
            raise SchemaError(
                f"{path or '<root>'}: matches the 'not' schema")
    t = schema.get("type")
    if t:
        if t == "integer":
            ok = isinstance(obj, int) and not isinstance(obj, bool)
        elif t == "number":
            ok = (
                isinstance(obj, (int, float)) and not isinstance(obj, bool)
            )
        else:
            ok = isinstance(obj, _TYPES.get(t, object))
        if not ok:
            raise SchemaError(
                f"{path or '<root>'}: expected {t}, got {type(obj).__name__}"
            )
    if "enum" in schema and obj not in schema["enum"]:
        raise SchemaError(f"{path or '<root>'}: {obj!r} not in {schema['enum']}")
    if isinstance(obj, (int, float)) and not isinstance(obj, bool):
        def _bound(key, excl_key):
            """(limit, exclusive) handling BOTH exclusive forms: the
            OpenAPI 3.0 boolean flag next to minimum/maximum and the
            2019-draft numeric form where exclusiveMinimum IS the
            bound."""
            excl = schema.get(excl_key)
            if isinstance(excl, bool):
                return schema.get(key), excl
            if isinstance(excl, (int, float)):
                return excl, True
            return schema.get(key), False

        lo, lo_x = _bound("minimum", "exclusiveMinimum")
        if lo is not None:
            if lo_x and obj <= lo:
                raise SchemaError(f"{path}: {obj} <= exclusive minimum {lo}")
            if not lo_x and obj < lo:
                raise SchemaError(f"{path}: {obj} < minimum {lo}")
        hi, hi_x = _bound("maximum", "exclusiveMaximum")
        if hi is not None:
            if hi_x and obj >= hi:
                raise SchemaError(f"{path}: {obj} >= exclusive maximum {hi}")
            if not hi_x and obj > hi:
                raise SchemaError(f"{path}: {obj} > maximum {hi}")
        if schema.get("multipleOf"):
            mult = schema["multipleOf"]
            if isinstance(obj, int) and isinstance(mult, int):
                bad = obj % mult != 0  # exact for arbitrary-size ints
            else:
                q = obj / mult
                bad = abs(q - round(q)) > 1e-9
            if bad:
                raise SchemaError(
                    f"{path}: {obj} is not a multiple of {mult}")
    if isinstance(obj, str):
        if "pattern" in schema:
            import re as _re

            if _re.search(schema["pattern"], obj) is None:
                raise SchemaError(
                    f"{path or '<root>'}: {obj!r} does not match pattern "
                    f"{schema['pattern']!r}")
        if "minLength" in schema and len(obj) < schema["minLength"]:
            raise SchemaError(f"{path}: shorter than minLength "
                              f"{schema['minLength']}")
        if "maxLength" in schema and len(obj) > schema["maxLength"]:
            raise SchemaError(f"{path}: longer than maxLength "
                              f"{schema['maxLength']}")
    if isinstance(obj, dict):
        if "minProperties" in schema and len(obj) < schema["minProperties"]:
            raise SchemaError(f"{path or '<root>'}: fewer than "
                              f"minProperties {schema['minProperties']}")
        if "maxProperties" in schema and len(obj) > schema["maxProperties"]:
            raise SchemaError(f"{path or '<root>'}: more than "
                              f"maxProperties {schema['maxProperties']}")
        for req in schema.get("required") or []:
            if req not in obj:
                raise SchemaError(f"{path or '<root>'}: missing required "
                                  f"property {req!r}")
        props = schema.get("properties") or {}
        for k, sub in props.items():
            if k in obj:
                validate_schema(obj[k], sub, f"{path}.{k}" if path else k)
        addl = schema.get("additionalProperties")
        if addl is not None:
            extra = [k for k in obj if k not in props]
            if addl is False and extra:
                raise SchemaError(
                    f"{path or '<root>'}: unknown properties {extra}")
            if isinstance(addl, dict):
                for k in extra:
                    validate_schema(obj[k], addl,
                                    f"{path}.{k}" if path else k)
    if isinstance(obj, list):
        if "minItems" in schema and len(obj) < schema["minItems"]:
            raise SchemaError(f"{path}: fewer than minItems "
                              f"{schema['minItems']}")
        if "maxItems" in schema and len(obj) > schema["maxItems"]:
            raise SchemaError(f"{path}: more than maxItems "
                              f"{schema['maxItems']}")
        if schema.get("uniqueItems"):
            # canonical-form keys: O(n) via a set, and type-aware so the
            # JSON values 1 and true stay DISTINCT (Python True == 1)
            seen = set()
            for item in obj:
                key = (type(item).__name__,
                       json.dumps(item, sort_keys=True, default=str))
                if key in seen:
                    raise SchemaError(
                        f"{path or '<root>'}: duplicate item {item!r} "
                        "(uniqueItems)")
                seen.add(key)
        if "items" in schema:
            for i, item in enumerate(obj):
                validate_schema(item, schema["items"], f"{path}[{i}]")


def flatten_wire_dict(d: dict, default_ns: Optional[str] = None) -> dict:
    """Wire object -> store dict: copy with flat name/namespace keys lifted
    from metadata (the single flattening used for every dict-stored kind).

    default_ns=None  -> cluster-scoped: namespace forced to ""
    default_ns="x"   -> namespaced: metadata/top-level namespace, else "x"
    """
    meta = d.get("metadata") or {}
    out = dict(d)
    out["name"] = d.get("name") or meta.get("name", "")
    out["namespace"] = (
        "" if default_ns is None
        else (d.get("namespace") or meta.get("namespace") or default_ns)
    )
    return out


def find_crd_for_kind(cluster, storage_kind: str) -> Optional[dict]:
    for crd in cluster.list("customresourcedefinitions"):
        if crd_storage_kind(crd) == storage_kind:
            return crd
    return None


# ------------------------------------------------- versions + conversion


def crd_versions(crd: dict) -> list:
    """Normalized [{name, served, storage}] (apiextensions types.go:67-104
    CustomResourceDefinitionVersion).  The legacy single spec.version is a
    one-entry served+storage list; a versions[] entry defaults to
    served=True so pre-r05 single-version CRDs keep working."""
    spec = crd.get("spec") or {}
    out = []
    for v in spec.get("versions") or []:
        out.append({
            "name": v.get("name", ""),
            "served": bool(v.get("served", True)),
            "storage": bool(v.get("storage", False)),
        })
    if not out and spec.get("version"):
        out = [{"name": spec["version"], "served": True, "storage": True}]
    if out and not any(v["storage"] for v in out):
        out[0]["storage"] = True  # exactly one storage version
    return out


def crd_storage_version(crd: dict) -> str:
    for v in crd_versions(crd):
        if v["storage"]:
            return v["name"]
    vs = crd_versions(crd)
    return vs[0]["name"] if vs else ""


def crd_served_versions(crd: dict) -> list:
    return [v["name"] for v in crd_versions(crd) if v["served"]]


def convert_cr_objects(cluster, crd: dict, objs: list,
                       target_version: str) -> list:
    """Convert custom resources between served/storage versions, in ONE
    round trip for the whole list (ConversionReview.request.objects is a
    list — the reference batches a LIST exactly this way,
    apiextensions-apiserver pkg/apiserver/conversion/webhook_converter.go).

    Strategy None (the default) rewrites apiVersion only — identical
    schemas across versions (apiextensions types.go ConversionStrategy
    None).  Strategy Webhook POSTs one ConversionReview to
    spec.conversion.webhook(ClientConfig) — resolved and trusted exactly
    like admission webhooks (service refs + caBundle)."""
    import copy
    import uuid as _uuid

    spec = crd.get("spec") or {}
    group = spec.get("group", "")
    storage_v = crd_storage_version(crd)

    def src_of(obj):
        return (obj.get("apiVersion") or "").rpartition("/")[2] or storage_v

    if not target_version:
        return objs
    need = [i for i, o in enumerate(objs)
            if src_of(o) != target_version]
    if not need:
        return objs
    conv = spec.get("conversion") or {}
    strategy = conv.get("strategy") or "None"
    out_list = list(objs)
    if strategy == "None":
        for i in need:
            out = copy.deepcopy(objs[i])
            out["apiVersion"] = f"{group}/{target_version}"
            out_list[i] = out
        return out_list
    if strategy != "Webhook":
        raise SchemaError(f"unknown conversion strategy {strategy!r}")
    from kubernetes_tpu.apiserver.webhooks import (
        post_json,
        resolve_client_config,
    )

    cc = (conv.get("webhook") or {}).get("clientConfig") \
        or conv.get("webhookClientConfig") or {}
    url, ca = resolve_client_config(cluster, cc, crd_storage_kind(crd))
    wires = []
    for i in need:
        wire = copy.deepcopy(objs[i])
        wire["apiVersion"] = f"{group}/{src_of(objs[i])}"
        wires.append(wire)
    review = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "ConversionReview",
        "request": {
            "uid": str(_uuid.uuid4()),
            "desiredAPIVersion": f"{group}/{target_version}",
            "objects": wires,
        },
    }
    out = post_json(url, review, timeout=10.0, ca_bundle=ca)
    resp = out.get("response") or {}
    if (resp.get("result") or {}).get("status", "Success") != "Success":
        raise SchemaError(
            "conversion webhook failed: "
            + str((resp.get("result") or {}).get("message", "")))
    converted = resp.get("convertedObjects") or []
    if len(converted) != len(need):
        raise SchemaError(
            f"conversion webhook returned {len(converted)} objects "
            f"for {len(need)}")
    for i, obj in zip(need, converted):
        out_list[i] = obj
    return out_list


def convert_cr(cluster, crd: dict, obj: dict, target_version: str) -> dict:
    return convert_cr_objects(cluster, crd, [obj], target_version)[0]
