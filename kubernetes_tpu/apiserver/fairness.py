"""API Priority & Fairness-style inflight limiting for the REST layer.

The reference bounds apiserver demand twice over: the legacy
--max-requests-inflight / --max-mutating-requests-inflight gate
(apiserver/pkg/server/filters/maxinflight.go) and, later, API Priority and
Fairness (apiserver/pkg/util/flowcontrol): requests are classified into
flows, each flow gets a bounded queue, and the scarce inflight slots are
dealt fairly across flows so one greedy client cannot starve the rest.
Over-limit requests are rejected with 429 TooManyRequests + Retry-After
(filters/maxinflight.go:157-172) — the signal well-behaved clients back
off on.

This module distills that to the behavior-shaping core:

  * two verb classes — MUTATING (POST/PUT/PATCH/DELETE) and READONLY
    (GET) — each with its own inflight ceiling, like the reference's
    split flags;
  * a *flow* is (client identity, verb class); when the ceiling is hit,
    waiters park in per-flow FIFO queues of bounded length and slots are
    granted ROUND-ROBIN across flows with waiters (the fair-queuing
    analog, shed of its shuffle-sharding) — a flow with 100 queued
    requests and a flow with 1 alternate grants, so the greedy flow
    cannot starve the polite one;
  * a full flow queue, or a queue wait exceeding the timeout, rejects
    the request immediately — the caller turns that into
    429 + Retry-After.

The limiter is transport-agnostic (acquire/release around any handler);
apiserver/server.py wires it ahead of the admission chain and exempts the
liveness surface (/healthz, /metrics, ...) and long-lived watch streams,
exactly as the reference's filter chain does.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional

from kubernetes_tpu.utils import metrics as m

MUTATING = "mutating"
READONLY = "readOnly"

# verbs that write (the reference's readonly/mutating split,
# maxinflight.go:40-47); everything else is readonly
MUTATING_METHODS = frozenset({"POST", "PUT", "PATCH", "DELETE"})


class TooManyRequests(Exception):
    """Over-limit rejection: the HTTP layer renders this as
    429 TooManyRequests with a Retry-After header (the reference's
    tooManyRequests helper, filters/maxinflight.go:157-172)."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclass
class FlowControlConfig:
    """The operator knobs (the --max-requests-inflight family plus the
    APF queue shape)."""

    # inflight ceilings per verb class; <=0 disables limiting for that class
    max_inflight_mutating: int = 200
    max_inflight_readonly: int = 400
    # bounded per-flow queue: the (queues * queueLengthLimit) analog;
    # a flow with this many waiters already parked rejects further arrivals
    queue_length_per_flow: int = 50
    # how long a request may wait in its flow queue before 429
    queue_wait_timeout_s: float = 1.0
    # the Retry-After hint stamped on rejections (seconds)
    retry_after_s: float = 1.0


class _Waiter:
    __slots__ = ("event", "granted")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.granted = False


class _ClassLimiter:
    """One verb class: `limit` inflight slots, per-flow FIFO queues,
    round-robin grant across flows with waiters."""

    def __init__(self, kind: str, cfg: FlowControlConfig, limit: int):
        self.kind = kind
        self.cfg = cfg
        self.limit = limit
        self._lock = threading.Lock()
        self.inflight = 0
        # flow -> FIFO of parked waiters; the ring rotates through flows
        # that currently have waiters (round-robin fairness)
        self._queues: "OrderedDict[str, Deque[_Waiter]]" = OrderedDict()
        self._ring: Deque[str] = deque()
        # grants per flow since start (observability + fairness tests)
        self.grants: Dict[str, int] = {}

    # ---- internal (lock held) ----

    def _drop_flow_if_empty(self, flow: str) -> None:
        if not self._queues.get(flow):
            self._queues.pop(flow, None)
            try:
                self._ring.remove(flow)
            except ValueError:
                pass

    def _grant_waiters(self) -> None:
        """Hand free slots to parked waiters, one flow per grant in ring
        order (the fair-queuing dequeue).  Keeps the invariant that
        waiters exist only while inflight == limit."""
        while self.inflight < self.limit and self._ring:
            flow = self._ring[0]
            q = self._queues.get(flow)
            if not q:
                self._drop_flow_if_empty(flow)
                continue
            w = q.popleft()
            # rotate so the NEXT grant serves a different flow first
            self._ring.rotate(-1)
            self._drop_flow_if_empty(flow)
            self.inflight += 1
            w.granted = True
            w.event.set()
        m.APF_INFLIGHT.set(float(self.inflight), request_kind=self.kind)

    def _reject(self, flow: str, reason: str) -> TooManyRequests:
        m.APF_REJECTED.inc(request_kind=self.kind, reason=reason)
        return TooManyRequests(
            f"too many {self.kind} requests for flow {flow!r} ({reason}), "
            "please try again later",
            self.cfg.retry_after_s,
        )

    # ---- surface ----

    def acquire(self, flow: str) -> None:
        """Take one inflight slot for `flow`, or raise TooManyRequests.
        Queued waiters are granted slots round-robin ACROSS flows, FIFO
        within a flow; a new arrival never jumps past parked waiters."""
        with self._lock:
            self._grant_waiters()
            if self.inflight < self.limit and not self._ring:
                self.inflight += 1
                self.grants[flow] = self.grants.get(flow, 0) + 1
                m.APF_INFLIGHT.set(float(self.inflight),
                                   request_kind=self.kind)
                return
            q = self._queues.get(flow)
            depth = len(q) if q is not None else 0
            if depth >= max(self.cfg.queue_length_per_flow, 0):
                raise self._reject(flow, "queue full")
            if q is None:
                q = self._queues[flow] = deque()
            w = _Waiter()
            q.append(w)
            if flow not in self._ring:
                self._ring.append(flow)
        if w.event.wait(self.cfg.queue_wait_timeout_s):
            with self._lock:
                self.grants[flow] = self.grants.get(flow, 0) + 1
            return
        with self._lock:
            if w.granted:
                # the grant raced the timeout: the slot is ours after all
                self.grants[flow] = self.grants.get(flow, 0) + 1
                return
            q = self._queues.get(flow)
            if q is not None:
                try:
                    q.remove(w)
                except ValueError:
                    pass
                self._drop_flow_if_empty(flow)
        raise self._reject(flow, "timeout")

    def release(self) -> None:
        """Return a slot and replay it to the next waiter (round-robin
        across flows)."""
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            self._grant_waiters()

    def queued(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())


class InflightLimiter:
    """The two verb-class limiters behind one acquire/release surface."""

    def __init__(self, config: Optional[FlowControlConfig] = None):
        self.config = config or FlowControlConfig()
        self._classes: Dict[bool, Optional[_ClassLimiter]] = {
            True: (
                _ClassLimiter(MUTATING, self.config,
                              self.config.max_inflight_mutating)
                if self.config.max_inflight_mutating > 0 else None
            ),
            False: (
                _ClassLimiter(READONLY, self.config,
                              self.config.max_inflight_readonly)
                if self.config.max_inflight_readonly > 0 else None
            ),
        }

    @staticmethod
    def flow_of(auth_header: str, client_host: str) -> str:
        """Flow identity: the caller's credential when one is presented
        (per-user fairness, the APF flow-distinguisher on username),
        else the client address.  Runs BEFORE authn — the limiter must
        shed load without paying the authn path."""
        if auth_header:
            return f"cred:{hash(auth_header) & 0xFFFFFFFF:08x}"
        return f"host:{client_host}"

    def acquire(self, flow: str, mutating: bool) -> Optional[_ClassLimiter]:
        """Take a slot; returns the class limiter to release() on, or
        None when that class is unlimited.  Raises TooManyRequests."""
        lim = self._classes[bool(mutating)]
        if lim is None:
            return None
        lim.acquire(flow)
        return lim

    def queued(self, mutating: bool) -> int:
        lim = self._classes[bool(mutating)]
        return 0 if lim is None else lim.queued()

    def grants(self, mutating: bool) -> Dict[str, int]:
        lim = self._classes[bool(mutating)]
        return {} if lim is None else dict(lim.grants)
