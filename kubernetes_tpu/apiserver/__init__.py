from kubernetes_tpu.apiserver.server import AdmissionDenied, APIServer

__all__ = ["APIServer", "AdmissionDenied"]
