"""Dynamic admission: Mutating/Validating webhook dispatch.

The apiserver's main extensibility seam beyond CRDs (VERDICT r3 missing
#1): out-of-process webhooks registered through
MutatingWebhookConfiguration / ValidatingWebhookConfiguration objects,
called with an AdmissionReview on every matching write.

Reference:
  * staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/mutating/dispatcher.go:1-180
    — serial dispatch, JSONPatch application between webhooks;
  * .../validating/dispatcher.go — all validating webhooks must allow;
  * .../config + rules matching: operations / resources wildcards and
    namespaceSelector (plugin/webhook/rules/rules.go Matcher);
  * failurePolicy (apiserver/pkg/apis/admissionregistration types.go):
    Fail (a webhook error denies the request) vs Ignore (skip it).

The wire protocol is admission/v1 AdmissionReview JSON POSTed over the
hook's clientConfig target: a bare `url`, or an in-cluster `service:`
reference resolved through the service's Endpoints (the reference's
ServiceResolver, staging/src/k8s.io/apiserver/pkg/util/webhook/
client.go:119-146 + webhook.go serviceResolver).  A per-hook `caBundle`
builds the TLS trust for https targets (client.go:43-48) — in an
otherwise-HTTPS cluster, admission must not be the one cleartext hop.
Mutating responses patch the object with RFC 6902 JSON Patch (base64 in
.response.patch, patchType JSONPatch), applied between webhooks so each
sees its predecessors' edits — dispatcher.go:121-150.  Every round trip
lands in the apiserver_admission_webhook_admission_duration_seconds
histogram (a slow failurePolicy=Fail hook stalls all matching writes;
it must be observable).
"""

from __future__ import annotations

import base64
import json
import ssl
import time
import urllib.error
import urllib.request
import uuid
from typing import Callable, List, Optional

from kubernetes_tpu.apiserver.admission import AdmissionDenied
from kubernetes_tpu.utils import metrics as m

MUTATING_KIND = "mutatingwebhookconfigurations"
VALIDATING_KIND = "validatingwebhookconfigurations"


# ------------------------------------------------------- RFC 6902 patch


def _ptr_tokens(path: str) -> List[str]:
    if path == "":
        return []
    if not path.startswith("/"):
        raise ValueError(f"bad JSON pointer {path!r}")
    return [t.replace("~1", "/").replace("~0", "~")
            for t in path.split("/")[1:]]


def _locate(doc, tokens):
    """Parent container + final token for a pointer."""
    cur = doc
    for t in tokens[:-1]:
        cur = cur[int(t)] if isinstance(cur, list) else cur[t]
    return cur, tokens[-1]


def apply_json_patch(doc: dict, patch: List[dict]) -> dict:
    """Minimal RFC 6902: add / remove / replace / copy / move / test —
    the operations admission webhooks emit (jsonpatch.Patch.Apply)."""
    out = json.loads(json.dumps(doc))  # deep copy, JSON semantics
    for op in patch:
        kind = op.get("op")
        tokens = _ptr_tokens(op.get("path", ""))
        if not tokens:
            if kind in ("add", "replace"):
                out = json.loads(json.dumps(op.get("value")))
                continue
            raise ValueError(f"unsupported root op {kind}")
        parent, last = _locate(out, tokens)
        if kind == "add":
            if isinstance(parent, list):
                idx = len(parent) if last == "-" else int(last)
                parent.insert(idx, op.get("value"))
            else:
                parent[last] = op.get("value")
        elif kind == "replace":
            if isinstance(parent, list):
                parent[int(last)] = op.get("value")
            else:
                if last not in parent:
                    raise ValueError(f"replace of missing {op['path']}")
                parent[last] = op.get("value")
        elif kind == "remove":
            if isinstance(parent, list):
                parent.pop(int(last))
            else:
                del parent[last]
        elif kind in ("copy", "move"):
            src_parent, src_last = _locate(out, _ptr_tokens(op["from"]))
            val = (src_parent[int(src_last)]
                   if isinstance(src_parent, list) else src_parent[src_last])
            if kind == "move":
                if isinstance(src_parent, list):
                    src_parent.pop(int(src_last))
                else:
                    del src_parent[src_last]
            if isinstance(parent, list):
                idx = len(parent) if last == "-" else int(last)
                parent.insert(idx, val)
            else:
                parent[last] = val
        elif kind == "test":
            cur = (parent[int(last)] if isinstance(parent, list)
                   else parent.get(last))
            if cur != op.get("value"):
                raise ValueError(f"test failed at {op['path']}")
        else:
            raise ValueError(f"unsupported patch op {kind!r}")
    return out


# --------------------------------------------------------- rule matching


def _rule_matches(rule: dict, op: str, kind: str) -> bool:
    """rules.go Matcher: operations and resources with '*' wildcards
    (apiGroups/apiVersions accepted but not discriminating in this
    single-group surface)."""
    ops = rule.get("operations") or ["*"]
    if "*" not in ops and op not in ops:
        return False
    resources = rule.get("resources") or ["*"]
    return "*" in resources or kind in resources


def _webhook_matches(hook: dict, cluster, op: str, kind: str,
                     obj: dict) -> bool:
    rules = hook.get("rules") or []
    if not any(_rule_matches(r, op, kind) for r in rules):
        return False
    sel = hook.get("namespaceSelector")
    if sel:
        from kubernetes_tpu.api.labels import selector_from_label_selector

        s = selector_from_label_selector(sel)
        if s is not None:
            ns = (obj.get("metadata") or {}).get("namespace") \
                or obj.get("namespace", "")
            labels = {}
            if ns and cluster.has_kind("namespaces"):
                nso = cluster.get("namespaces", "", ns)
                if isinstance(nso, dict):
                    labels = (nso.get("labels")
                              or (nso.get("metadata") or {}).get("labels")
                              or {})
            if not s.matches(labels):
                return False
    return True


# ----------------------------------------------------- client resolution


def resolve_client_config(cluster, cc: dict, name: str = ""):
    """WebhookClientConfig -> (url, caBundle).  A `service:` reference
    resolves through the service's Endpoints (the reference's
    ServiceResolver yields the cluster-IP and relies on kube-proxy; this
    framework's dataplane is the Endpoints object itself), defaulting
    port 443 and scheme https — in-cluster admission/conversion traffic
    is never cleartext.  Shared by admission webhooks and the CRD
    conversion webhook client (apiserver/pkg/util/webhook/client.go)."""
    ca = cc.get("caBundle")
    if cc.get("url"):
        return cc["url"], ca
    svc = cc.get("service")
    if not svc:
        raise ValueError(f"webhook {name!r} has neither url nor service")
    ns = svc.get("namespace") or "default"
    svc_name = svc.get("name") or ""
    port = int(svc.get("port") or 443)
    path = svc.get("path") or "/"
    host = None
    if cluster.has_kind("endpoints"):
        ep = cluster.get("endpoints", ns, svc_name)
        if isinstance(ep, dict):
            for ss in ep.get("subsets") or []:
                addrs = ss.get("addresses") or []
                if addrs:
                    host = addrs[0].get("ip")
                    eports = ss.get("ports") or []
                    if eports:  # endpoints carry the TARGET port
                        port = int(eports[0].get("port") or port)
                    break
    if host is None and cluster.has_kind("services"):
        so = cluster.get("services", ns, svc_name)
        if isinstance(so, dict):
            host = (so.get("spec") or {}).get("clusterIP") \
                or so.get("clusterIP")
    if not host:
        raise ValueError(
            f"webhook {name!r}: service {ns}/{svc_name} "
            "has no reachable endpoint")
    if not path.startswith("/"):
        path = "/" + path
    return f"https://{host}:{port}{path}", ca


def post_json(url: str, payload: dict, timeout: float,
              ca_bundle: Optional[str] = None) -> dict:
    """One HTTPS-aware JSON POST with per-target caBundle trust (the
    conversion/admission webhook wire call)."""
    return WebhookDispatcher._http_post(url, payload, timeout, ca_bundle)


# ------------------------------------------------------------- dispatch


class WebhookDispatcher:
    """The MutatingAdmissionWebhook + ValidatingAdmissionWebhook plugin
    pair as one chain callable: mutating configurations run serially
    (each seeing prior patches), then every validating configuration
    must allow.  Plugs into APIServer._admit after the compiled-in chain
    (plugins.go order: the webhook pair sits just before ResourceQuota)."""

    def __init__(self, cluster, timeout_s: float = 10.0,
                 http_post: Optional[Callable] = None):
        self.cluster = cluster
        self.timeout_s = timeout_s
        self._post = http_post or self._http_post
        # injected test doubles may keep the legacy 3-arg signature
        # (url, payload, timeout) — detect the arity ONCE here; a
        # retry-on-TypeError fallback would double-dispatch a review
        # whenever a 4-arg post raises TypeError internally
        import inspect

        try:
            self._post_takes_ca = (
                len(inspect.signature(self._post).parameters) >= 4)
        except (TypeError, ValueError):
            self._post_takes_ca = True
        # hook name -> last round-trip seconds (debug view over the
        # WEBHOOK_LATENCY histogram)
        self.last_latency = {}

    @staticmethod
    def _http_post(url: str, payload: dict, timeout: float,
                   ca_bundle: Optional[str] = None) -> dict:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        ctx = None
        if url.startswith("https://"):
            if ca_bundle:
                # per-hook private trust (client.go:43-48 TLSConfig.RootCAs
                # from cc.CABundle); hostname/IP-SAN verification stays on
                ctx = ssl.create_default_context(
                    cadata=base64.b64decode(ca_bundle).decode())
            else:
                ctx = ssl.create_default_context()
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as resp:
            return json.loads(resp.read() or b"{}")

    def _resolve_target(self, hook: dict):
        return resolve_client_config(
            self.cluster, hook.get("clientConfig") or {},
            hook.get("name", ""))

    def _hooks(self, config_kind: str):
        if not self.cluster.has_kind(config_kind):
            return
        for cfg in sorted(self.cluster.list(config_kind),
                          key=lambda c: c.get("name", "")):
            if not isinstance(cfg, dict):
                continue
            for hook in cfg.get("webhooks") or []:
                yield hook

    def _call(self, hook: dict, op: str, kind: str, obj: dict) -> dict:
        """One AdmissionReview round trip -> the .response dict.
        Raises on transport errors (failurePolicy decides what happens)."""
        url, ca_bundle = self._resolve_target(hook)
        uid = str(uuid.uuid4())
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": uid,
                "operation": op,
                "resource": {"group": "", "version": "v1",
                             "resource": kind},
                "namespace": (obj.get("metadata") or {}).get("namespace")
                or obj.get("namespace", ""),
                "name": (obj.get("metadata") or {}).get("name")
                or obj.get("name", ""),
                "object": obj,
            },
        }
        timeout = float(hook.get("timeoutSeconds") or self.timeout_s)
        t0 = time.monotonic()
        try:
            if self._post_takes_ca:
                out = self._post(url, review, timeout, ca_bundle)
            else:
                out = self._post(url, review, timeout)
        finally:
            dt = time.monotonic() - t0
            m.WEBHOOK_LATENCY.observe(dt)
            self.last_latency[hook.get("name", "")] = dt
        return out.get("response") or {}

    def _dispatch(self, config_kind: str, op: str, kind: str,
                  obj: dict) -> dict:
        mutating = config_kind == MUTATING_KIND
        for hook in self._hooks(config_kind):
            if not _webhook_matches(hook, self.cluster, op, kind, obj):
                continue
            policy = hook.get("failurePolicy", "Fail")
            try:
                resp = self._call(hook, op, kind, obj)
            except Exception as e:
                if policy == "Ignore":
                    continue  # a down webhook must not block writes
                raise AdmissionDenied(
                    f"webhook {hook.get('name')!r} failed: {e}") from e
            if not resp.get("allowed", False):
                msg = ((resp.get("status") or {}).get("message")
                       or "denied by webhook")
                raise AdmissionDenied(
                    f"admission webhook {hook.get('name')!r} denied the "
                    f"request: {msg}")
            patch_b64 = resp.get("patch")
            if mutating and patch_b64:
                if resp.get("patchType", "JSONPatch") != "JSONPatch":
                    raise AdmissionDenied(
                        f"webhook {hook.get('name')!r}: unsupported "
                        f"patchType {resp.get('patchType')!r}")
                try:
                    patch = json.loads(base64.b64decode(patch_b64))
                    obj = apply_json_patch(obj, patch)
                except Exception as e:
                    if policy == "Ignore":
                        continue
                    raise AdmissionDenied(
                        f"webhook {hook.get('name')!r}: bad patch: {e}"
                    ) from e
        return obj

    def __call__(self, op: str, kind: str, obj: dict) -> dict:
        # never dispatch admission onto the webhook configuration kinds
        # themselves (the reference exempts the admissionregistration
        # group to avoid deadlocking the escape hatch)
        if kind in (MUTATING_KIND, VALIDATING_KIND):
            return obj
        if not isinstance(obj, dict):
            return obj
        obj = self._dispatch(MUTATING_KIND, op, kind, obj)
        self._dispatch(VALIDATING_KIND, op, kind, obj)
        return obj
