"""Informer machinery: DeltaFIFO -> shared indexed store -> handlers.

Reference: client-go tools/cache — delta_fifo.go:655 (per-key compressed
delta queues between the reflector and the processor),
shared_informer.go:650 (ONE upstream watch fanned out to N event
handlers over a shared indexed cache, with periodic resync),
thread_safe_store.go (the indexer), controller.go (processLoop: pop a
key's deltas, apply to the store, then notify handlers).

The framework's LocalCluster already *is* a listable/watchable store, so
the informer's upstream source is any LocalCluster-like object — the
in-process store, a PersistentCluster, or a Reflector mirror of a remote
apiserver.  What the informer adds over a raw ``cluster.watch`` is the
reference's client architecture: per-kind subscription, handler fan-out
decoupled from the write path (a slow handler no longer blocks the
store's write lock), delta compression, named indices for O(1) lookups
(pods-by-node, pods-by-namespace), and resync.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.runtime.cluster import ADDED, DELETED, MODIFIED, LocalCluster

# delta types (delta_fifo.go:77-97); Sync marks resync/replay deltas so
# handlers can tell a periodic re-list from a real change
D_ADDED = "Added"
D_UPDATED = "Updated"
D_DELETED = "Deleted"
D_SYNC = "Sync"

_EVENT_TO_DELTA = {ADDED: D_ADDED, MODIFIED: D_UPDATED, DELETED: D_DELETED}


class DeltaFIFO:
    """Per-key delta queues: producers append (type, obj) deltas under a
    key; the consumer pops ONE key's accumulated deltas at a time.  Two
    consecutive Deleted deltas compress into one (dedupDeltas,
    delta_fifo.go:571-602)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items: Dict[object, List[Tuple[str, object]]] = {}
        self._queue: deque = deque()
        self._closed = False

    def add(self, dtype: str, key, obj) -> None:
        with self._cond:
            deltas = self._items.get(key)
            if deltas is None:
                deltas = self._items[key] = []
                self._queue.append(key)
            if deltas and dtype == D_DELETED and deltas[-1][0] == D_DELETED:
                deltas[-1] = (D_DELETED, obj)  # dedup consecutive deletes
            else:
                deltas.append((dtype, obj))
            self._cond.notify()

    def pop(self, timeout: Optional[float] = None):
        """-> (key, [deltas]) or None on close/timeout."""
        with self._cond:
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            while not self._queue:
                if self._closed:
                    return None
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return None
                self._cond.wait(left)
            key = self._queue.popleft()
            return key, self._items.pop(key)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)


class Indexer:
    """Thread-safe object store with named indices
    (thread_safe_store.go): an index function maps an object to a list
    of index values; by_index(name, value) answers in O(result)."""

    def __init__(self, indexers: Optional[Dict[str, Callable]] = None):
        self._lock = threading.Lock()
        self._items: Dict[object, object] = {}
        self._indexers: Dict[str, Callable] = dict(indexers or {})
        self._indices: Dict[str, Dict[str, set]] = {
            name: {} for name in self._indexers
        }

    def add_indexer(self, name: str, fn: Callable) -> None:
        with self._lock:
            if name in self._indexers:
                return
            self._indexers[name] = fn
            idx: Dict[str, set] = {}
            for key, obj in self._items.items():
                for v in fn(obj):
                    idx.setdefault(v, set()).add(key)
            self._indices[name] = idx

    def _unindex(self, key, obj) -> None:
        for name, fn in self._indexers.items():
            idx = self._indices[name]
            for v in fn(obj):
                bucket = idx.get(v)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del idx[v]

    def _index(self, key, obj) -> None:
        for name, fn in self._indexers.items():
            for v in fn(obj):
                self._indices[name].setdefault(v, set()).add(key)

    def upsert(self, key, obj):
        """-> the previous object (None if new)."""
        with self._lock:
            old = self._items.get(key)
            if old is not None:
                self._unindex(key, old)
            self._items[key] = obj
            self._index(key, obj)
            return old

    def delete(self, key):
        with self._lock:
            old = self._items.pop(key, None)
            if old is not None:
                self._unindex(key, old)
            return old

    def get(self, key):
        with self._lock:
            return self._items.get(key)

    def list(self) -> List[object]:
        with self._lock:
            return list(self._items.values())

    def keys(self) -> List[object]:
        with self._lock:
            return list(self._items.keys())

    def by_index(self, name: str, value: str) -> List[object]:
        with self._lock:
            keys = self._indices.get(name, {}).get(value, ())
            return [self._items[k] for k in keys if k in self._items]

    def index_values(self, name: str) -> List[str]:
        with self._lock:
            return list(self._indices.get(name, {}))

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class SharedIndexInformer:
    """One upstream subscription on (cluster, kind), shared by N handlers.

    Source events land in a DeltaFIFO on the store's write path (cheap
    append); a dedicated process thread applies them to the Indexer and
    dispatches handlers — so handler latency never blocks writers, the
    decoupling shared_informer.go gets from its processor goroutines."""

    def __init__(self, cluster: LocalCluster, kind: str,
                 resync_period: float = 0.0):
        self.cluster = cluster
        self.kind = kind
        self.resync_period = resync_period
        self.store = Indexer()
        self.fifo = DeltaFIFO()
        self._handlers: List[Tuple[Optional[Callable], Optional[Callable],
                                   Optional[Callable]]] = []
        self._handlers_lock = threading.Lock()
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = False

    # ------------------------------------------------------------- config

    def add_event_handler(self, on_add: Optional[Callable] = None,
                          on_update: Optional[Callable] = None,
                          on_delete: Optional[Callable] = None) -> None:
        """on_add(obj), on_update(old, new), on_delete(obj) — dispatched
        AFTER the shared store reflects the change, so handlers reading
        the store see at-least-as-fresh state (shared_informer contract)."""
        with self._handlers_lock:
            self._handlers.append((on_add, on_update, on_delete))

    def add_indexer(self, name: str, fn: Callable) -> None:
        self.store.add_indexer(name, fn)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "SharedIndexInformer":
        if self._started:
            return self
        self._started = True
        self._thread = threading.Thread(target=self._process_loop,
                                        daemon=True)
        self._thread.start()
        # subscribing replays current state synchronously under the store
        # lock; the sentinel marks the end of the replay so has_synced
        # flips only after the replayed state is QUERYABLE in self.store
        self.cluster.watch(self._on_source_event)
        self.fifo.add(D_SYNC, ("", "\x00sync-sentinel"), None)
        return self

    def stop(self) -> None:
        self._stop.set()
        self.cluster.unwatch(self._on_source_event)
        self.fifo.close()

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # ------------------------------------------------------------ internals

    def _on_source_event(self, event: str, kind: str, obj) -> None:
        if kind != self.kind or event not in _EVENT_TO_DELTA:
            return
        key = LocalCluster._key(kind, obj)
        self.fifo.add(_EVENT_TO_DELTA[event], key, obj)

    def _resync_tick(self) -> None:
        for key in self.store.keys():
            obj = self.store.get(key)
            if obj is not None:
                self.fifo.add(D_SYNC, key, obj)

    def _process_loop(self) -> None:
        next_resync = (time.monotonic() + self.resync_period
                       if self.resync_period else None)
        while not self._stop.is_set():
            item = self.fifo.pop(timeout=0.2)
            if item is None:
                if self.fifo._closed:
                    return
                if next_resync and time.monotonic() >= next_resync:
                    self._resync_tick()
                    next_resync = time.monotonic() + self.resync_period
                continue
            key, deltas = item
            if key == ("", "\x00sync-sentinel"):
                self._synced.set()
                continue
            for dtype, obj in deltas:
                try:
                    self._apply(key, dtype, obj)
                except Exception:  # HandleError: a bad handler can't kill
                    pass           # the shared process loop

    def _apply(self, key, dtype: str, obj) -> None:
        if dtype == D_DELETED:
            old = self.store.delete(key)
            if old is None:
                return  # delete of something we never saw
            with self._handlers_lock:
                handlers = list(self._handlers)
            for _, _, on_delete in handlers:
                if on_delete is not None:
                    on_delete(obj)
            return
        old = self.store.upsert(key, obj)
        with self._handlers_lock:
            handlers = list(self._handlers)
        if old is None:
            # first sighting dispatches as add whatever the delta type
            # (a Sync for an unknown object is an add — processDeltas)
            for on_add, _, _ in handlers:
                if on_add is not None:
                    on_add(obj)
        else:
            # known object: update; resyncs re-deliver with old == new
            for _, on_update, _ in handlers:
                if on_update is not None:
                    on_update(old, obj)


class SharedInformerFactory:
    """One informer per kind, shared by every consumer
    (informers/factory.go)."""

    def __init__(self, cluster: LocalCluster):
        self.cluster = cluster
        self._informers: Dict[str, SharedIndexInformer] = {}
        self._lock = threading.Lock()

    def informer(self, kind: str,
                 resync_period: float = 0.0) -> SharedIndexInformer:
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = SharedIndexInformer(self.cluster, kind, resync_period)
                self._informers[kind] = inf
            return inf

    def start(self) -> "SharedInformerFactory":
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.start()
        return self

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        with self._lock:
            informers = list(self._informers.values())
        deadline = time.monotonic() + timeout
        for inf in informers:
            if not inf.wait_for_sync(max(0.0, deadline - time.monotonic())):
                return False
        return True

    def stop(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.stop()


def wire_scheduler_informers(factory: SharedInformerFactory,
                             scheduler) -> SharedInformerFactory:
    """AddAllEventHandlers through the informer stack
    (pkg/scheduler/eventhandlers.go:319-378 wired onto shared informers,
    the way cmd/kube-scheduler/app/server.go does): nodes/pods/services
    informers feed the scheduler cache + queue.  Functionally equivalent
    to runtime.cluster.wire_scheduler, but events traverse
    reflector->DeltaFIFO->shared store first — the real client-side
    pipeline, usable against a remote mirror."""
    from kubernetes_tpu.runtime.cluster import (
        wire_scheduler_defaults as _defaults,
    )

    _defaults(factory.cluster, scheduler)
    cache = scheduler.cache
    queue = scheduler.queue
    # responsibleForPod: only pods naming THIS scheduler enter its queue
    from kubernetes_tpu.runtime.scheduler import responsible_for

    def responsible(pod) -> bool:
        return responsible_for(pod, scheduler)

    def node_add(node):
        cache.add_node(node)
        queue.move_all_to_active()

    def node_update(_old, node):
        cache.update_node(node)
        queue.move_all_to_active()

    def node_delete(node):
        cache.remove_node(node.name)
        queue.move_all_to_active()

    ninf = factory.informer("nodes")
    ninf.add_event_handler(on_add=node_add, on_update=node_update,
                           on_delete=node_delete)

    def _terminal(pod) -> bool:
        return pod.status.phase in ("Succeeded", "Failed")

    def pod_add(pod):
        if _terminal(pod):
            cache.remove_pod(pod)
            queue.delete(pod)
            queue.move_all_to_active()
            return
        if pod.spec.node_name:
            cache.add_pod(pod)
            queue.move_all_to_active()
        elif responsible(pod):
            queue.add(pod)

    def pod_update(_old, pod):
        if _terminal(pod):
            cache.remove_pod(pod)
            queue.delete(pod)
            queue.move_all_to_active()
            return
        if pod.spec.node_name:
            cache.add_pod(pod)
            queue.delete(pod)
        else:
            cache.remove_pod(pod)
            queue.delete(pod)
            if responsible(pod):
                queue.add(pod)

    def pod_delete(pod):
        if _terminal(pod):
            return
        if pod.spec.node_name:
            cache.remove_pod(pod)
            queue.move_all_to_active()
        else:
            queue.delete(pod)

    pinf = factory.informer("pods")
    # the index the node-side consumers want anyway (assignedPods)
    pinf.add_indexer("byNode", lambda p: [p.spec.node_name]
                     if p.spec.node_name else [])
    pinf.add_event_handler(on_add=pod_add, on_update=pod_update,
                           on_delete=pod_delete)

    def svc_add(svc):
        cache.encoder.add_spread_selector(svc["namespace"], svc["selector"])
        queue.move_all_to_active()

    factory.informer("services").add_event_handler(on_add=svc_add)

    # storage events unblock volume-bound pods (eventhandlers.go wires
    # PV/PVC/StorageClass informers to MoveAllToActiveQueue the same way)
    def pv_upsert(pv):
        cache.encoder.add_pv(pv)
        queue.move_all_to_active()

    factory.informer("persistentvolumes").add_event_handler(
        on_add=pv_upsert, on_update=lambda _o, pv: pv_upsert(pv),
        on_delete=lambda pv: (cache.encoder.remove_pv(pv.name),
                              queue.move_all_to_active()))

    def pvc_upsert(pvc):
        cache.encoder.add_pvc(pvc)
        queue.move_all_to_active()

    factory.informer("persistentvolumeclaims").add_event_handler(
        on_add=pvc_upsert, on_update=lambda _o, c: pvc_upsert(c),
        on_delete=lambda c: (cache.encoder.remove_pvc(c.namespace, c.name),
                             queue.move_all_to_active()))

    def sc_upsert(sc):
        cache.encoder.add_storage_class(sc)
        queue.move_all_to_active()

    factory.informer("storageclasses").add_event_handler(
        on_add=sc_upsert, on_update=lambda _o, s: sc_upsert(s),
        on_delete=lambda s: cache.encoder.remove_storage_class(s.name))
    return factory
