"""RemoteCluster: the typed-clientset analog — mirror reads, REST writes.

Reference: client-go's deployment pattern — controllers READ through
informer-fed listers (never the apiserver directly) and WRITE through a
typed clientset (kubernetes.Interface).  RemoteCluster packages exactly
that against this framework's REST server while presenting the
LocalCluster surface (get/list/watch/create/update/delete/bind), so
every controller, scheduler wiring, and informer written against
LocalCluster runs unmodified against a REMOTE control plane:

  * reads + watch  -> the Reflector's mirror (informer-cache staleness
    semantics, exactly like lister-backed controllers);
  * writes         -> REST verbs against the remote apiserver, with
    optimistic CAS carried through: the watch stream's resourceVersions
    are preserved in the mirror (reflector._apply), so get_with_rv +
    update(expect_rv=...) round-trips the REMOTE store's revision check
    and a stale write raises ConflictError from the remote 409.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from typing import Optional

from kubernetes_tpu.api import scheme
from kubernetes_tpu.client.reflector import (
    Reflector,
    _auth_headers,
    parse_retry_after,
)
from kubernetes_tpu.runtime.cluster import ConflictError, LocalCluster


class RemoteAPIError(RuntimeError):
    """Non-2xx REST response, carrying the HTTP status code (the
    apierrors.StatusError analog — callers branch on code, not message).
    429 responses additionally carry the server's Retry-After hint in
    seconds (0.0 when the server sent none)."""

    def __init__(self, code: int, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.code = code
        self.retry_after_s = retry_after_s


class RemoteCluster:
    """LocalCluster-surface client for a remote apiserver."""

    # bounded 429 retry: the limiter rejects BEFORE any processing, so a
    # re-send is safe for every verb (unlike a timeout, a 429 proves the
    # request did not execute); after this many paced attempts the 429
    # surfaces as RemoteAPIError(retry_after_s=...) for the caller
    MAX_429_RETRIES = 2

    def __init__(self, server: str, token: str = "", binary: bool = False):
        self.server = server.rstrip("/")
        self.token = token
        self._retry_rng = random.Random()
        # binary: negotiate the compact wire format for the watch stream
        # and write bodies (api/binary.py — the protobuf-client analog)
        self.binary = binary
        self.reflector = Reflector(server, token=token, binary=binary)
        self.mirror: LocalCluster = self.reflector.mirror
        # controllers record events locally (tools/record buffers and
        # posts; the buffered recorder is the shared piece)
        self.events = self.mirror.events

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "RemoteCluster":
        self.reflector.start()
        return self

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self.reflector.wait_for_sync(timeout)

    def stop(self) -> None:
        self.reflector.stop()

    # -------------------------------------------------------------- reads

    def get(self, kind, namespace, name):
        return self.mirror.get(kind, namespace, name)

    def get_with_rv(self, kind, namespace, name):
        return self.mirror.get_with_rv(kind, namespace, name)

    def list(self, kind):
        return self.mirror.list(kind)

    def watch(self, fn, bookmark: bool = False) -> None:
        self.mirror.watch(fn, bookmark=bookmark)

    def unwatch(self, fn) -> None:
        self.mirror.unwatch(fn)

    def has_kind(self, kind) -> bool:
        return self.mirror.has_kind(kind)

    def register_kind(self, kind) -> None:
        self.mirror.register_kind(kind)

    @property
    def kinds(self):
        return self.mirror.kinds

    # -------------------------------------------------------------- writes

    def _request(self, method: str, path: str, payload=None) -> dict:
        headers = _auth_headers(self.token, json_body=payload is not None)
        if self.binary and payload is not None:
            from kubernetes_tpu.api import binary as _bin

            data = _bin.dumps(payload)
            headers["Content-Type"] = _bin.BINARY_MEDIA_TYPE
        else:
            data = (json.dumps(payload).encode()
                    if payload is not None else None)
        from kubernetes_tpu.cmd.base import tls_urlopen

        attempt = 0
        while True:
            req = urllib.request.Request(
                self.server + path, data=data, method=method,
                headers=headers,
            )
            try:
                with tls_urlopen(req, timeout=30) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                body = e.read().decode(errors="replace")
                try:
                    out = json.loads(body)
                except ValueError:
                    out = {"kind": "Status", "code": e.code, "message": body}
                if e.code == 409:
                    raise ConflictError(out.get("message", "conflict"))
                retry_after = 0.0
                if e.code == 429:
                    # the apiserver shed this request BEFORE executing it
                    # (inflight limiter): honor Retry-After and re-send a
                    # bounded number of times, jittered so a fleet of
                    # clients doesn't return in lockstep
                    retry_after = parse_retry_after(e.headers) or 0.5
                    if attempt < self.MAX_429_RETRIES:
                        attempt += 1
                        time.sleep(
                            retry_after
                            * (1.0 + 0.25 * self._retry_rng.random())
                        )
                        continue
                raise RemoteAPIError(
                    e.code,
                    f"{method} {path}: {e.code} {out.get('message', body)}",
                    retry_after_s=retry_after,
                )

    def _encode(self, kind: str, obj, expect_rv: Optional[int] = None) -> dict:
        d = dict(scheme.encode(kind, obj))
        if expect_rv is not None:
            # copy before injecting: encode may return a stored dict by
            # reference for dict-backed kinds
            d["metadata"] = dict(d.get("metadata") or {})
            d["metadata"]["resourceVersion"] = str(expect_rv)
        return d

    def add_node(self, node) -> None:
        """LocalCluster helper parity (the hollow kubelet registers
        through whichever store surface it is handed)."""
        self.create("nodes", node)

    def add_pod(self, pod) -> None:
        self.create("pods", pod)

    def create(self, kind: str, obj) -> int:
        ns, name = LocalCluster._key(kind, obj)
        path = scheme.rest_path(kind, ns or "default")
        out = self._request("POST", path, self._encode(kind, obj))
        rv = (out.get("metadata") or {}).get("resourceVersion")
        return int(rv) if rv else 0

    def update(self, kind: str, obj, expect_rv: Optional[int] = None) -> int:
        ns, name = LocalCluster._key(kind, obj)
        path = scheme.rest_path(kind, ns or "default", name)
        out = self._request(
            "PUT", path, self._encode(kind, obj, expect_rv=expect_rv))
        rv = (out.get("metadata") or {}).get("resourceVersion")
        return int(rv) if rv else 0

    def delete(self, kind: str, namespace: str, name: str) -> None:
        path = scheme.rest_path(kind, namespace or "default", name)
        try:
            self._request("DELETE", path)
        except RemoteAPIError as e:
            if e.code != 404:  # vanished between read and delete: fine
                raise

    def bind(self, pod, node_name: str) -> bool:
        path = scheme.rest_path("pods", pod.namespace, pod.name) + "/binding"
        try:
            self._request("POST", path, {"target": {"name": node_name}})
            return True
        except (ConflictError, RuntimeError):
            return False

    def unbind(self, pod) -> bool:
        from kubernetes_tpu.client.reflector import remote_unbinder

        return remote_unbinder(self.server, token=self.token)(pod)
