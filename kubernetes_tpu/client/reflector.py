"""Reflector: LIST+WATCH a remote API server into a local mirror.

Reference: client-go tools/cache — reflector.go:401 (ListAndWatch),
delta_fifo.go, shared_informer.go.  The apiserver's /api/v1/watch stream
already replays current state as ADDED events then follows live (the
reflector LIST step folded into WATCH), and emits a BOOKMARK event at the
end of the replay; this client:

  * buffers the replay until the BOOKMARK, then swaps the full state into
    the mirror LocalCluster atomically (objects that vanished while
    disconnected are deleted — the re-list reconciliation);
  * applies live events after the bookmark as create/update/delete on the
    mirror, which fans them out to every local watcher (scheduler cache/
    queue wiring, controllers, proxies — anything written against
    LocalCluster runs unmodified against a REMOTE control plane);
  * reconnects with exponential backoff on stream loss and re-syncs.

RemoteBinder completes the loop: local placement decisions POST back to
the remote Binding subresource, exactly what a real scheduler process
does (SURVEY section 3.3).
"""

from __future__ import annotations

import json
import random
import threading
import urllib.error
import urllib.request


def _urlopen(req, timeout):
    from kubernetes_tpu.cmd.base import tls_urlopen

    return tls_urlopen(req, timeout)
from typing import Optional

from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.utils import klog


def _decode(kind: str, d: dict):
    from kubernetes_tpu.api import scheme

    return scheme.decode(kind, d)


def _auth_headers(token: str, json_body: bool = False) -> dict:
    headers = {"Content-Type": "application/json"} if json_body else {}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    # cross-component trace propagation (utils/trace.py): every REST
    # write issued inside a traced section (the scheduler's commit tail
    # sets the thread-local around binds/victim deletes) carries the
    # cycle's traceparent, so the apiserver can join the request to the
    # scheduling decision.  Untraced callers add no header.
    from kubernetes_tpu.utils.trace import (
        TRACEPARENT_HEADER,
        current_traceparent,
    )

    tp = current_traceparent()
    if tp:
        headers[TRACEPARENT_HEADER] = tp
    return headers


def decorrelated_jitter(prev: float, base: float, cap: float,
                        rng: random.Random) -> float:
    """Decorrelated-jitter backoff (the client-go wait.Backoff jitter
    discipline): next = min(cap, uniform(base, prev*3)).  Unlike plain
    exponential doubling, two clients that disconnected at the same
    instant (an apiserver restart drops EVERY watch at once) spread their
    reconnects across the whole window instead of stampeding back in
    lockstep."""
    return min(cap, rng.uniform(base, max(base, prev * 3.0)))


def parse_retry_after(headers) -> float:
    """The Retry-After header as seconds (0.0 when absent/unparseable).
    Only the delta-seconds form is emitted by this framework's apiserver;
    HTTP-date is out of scope."""
    try:
        return max(0.0, float(headers.get("Retry-After", "")))
    except (AttributeError, TypeError, ValueError):
        return 0.0


class Reflector:
    """Mirror a remote apiserver's store into a LocalCluster."""

    def __init__(self, server: str, mirror: Optional[LocalCluster] = None,
                 backoff: float = 0.5, max_backoff: float = 10.0,
                 token: str = "", binary: bool = False,
                 jitter_seed: Optional[int] = None):
        self.server = server.rstrip("/")
        self.mirror = mirror if mirror is not None else LocalCluster()
        self.backoff = backoff
        self.max_backoff = max_backoff
        # decorrelated reconnect jitter: unseeded by default (each process
        # lands elsewhere in the window); seedable for deterministic tests
        self._jitter_rng = random.Random(jitter_seed)
        self.token = token  # bearer credential for RBAC'd planes
        # negotiate the binary wire format for the watch stream (the
        # protobuf-for-high-QPS-clients analog, api/binary.py)
        self.binary = binary
        self.synced = threading.Event()   # set after the first bookmark
        self.resyncs = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Reflector":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        """WaitForCacheSync: block until the first replay landed."""
        return self.synced.wait(timeout)

    # ------------------------------------------------------------- internals

    def _run(self) -> None:
        delay = self.backoff
        while not self._stop.is_set():
            retry_after = 0.0
            try:
                self._list_and_watch()
                delay = self.backoff  # clean disconnect: reset backoff
            except urllib.error.HTTPError as e:
                # an overloaded apiserver sheds watch re-establishment
                # with 429 + Retry-After: honor the server's pacing hint
                # (it floors the reconnect pause below)
                klog.errorf("reflector: watch of %s failed: %r", self.server, e)
                if e.code == 429:
                    retry_after = parse_retry_after(e.headers)
            except Exception as e:
                # distinguish stream loss from decode/schema bugs — a silent
                # reconnect loop hides both (reflector.go logs via utilruntime
                # HandleError before backing off)
                klog.errorf("reflector: watch of %s failed: %r", self.server, e)
            if self._stop.is_set():
                return
            # decorrelated jitter: a fleet of reflectors dropped by one
            # apiserver blip must NOT reconnect in lockstep; Retry-After
            # (when the server sent one) floors the pause, with a jitter
            # fraction on top so even paced clients don't synchronize
            delay = decorrelated_jitter(
                delay, self.backoff, self.max_backoff, self._jitter_rng
            )
            wait = delay
            if retry_after > 0.0:
                wait = max(
                    wait,
                    retry_after * (1.0 + 0.2 * self._jitter_rng.random()),
                )
            self._stop.wait(wait)

    def _event_stream(self, resp):
        """Yield decoded event dicts; heartbeats yield None so the caller's
        stop check still runs ~1/s on an idle stream (a stopped reflector
        must release its socket and the server's watch fan-out entry
        promptly, not wait for the next real event)."""
        if self.binary:
            from kubernetes_tpu.api import binary as _bin

            for payload in _bin.read_frames(resp, heartbeats=True):
                yield _bin.loads(payload) if payload is not None else None
            return
        for raw in resp:
            raw = raw.strip()
            if not raw:
                continue
            try:
                yield json.loads(raw)
            except ValueError:
                yield None  # heartbeat chunk

    def _list_and_watch(self) -> None:
        headers = _auth_headers(self.token)
        if self.binary:
            from kubernetes_tpu.api.binary import BINARY_MEDIA_TYPE

            headers["Accept"] = BINARY_MEDIA_TYPE
        req = urllib.request.Request(
            self.server + "/api/v1/watch", headers=headers)
        with _urlopen(req, timeout=30) as resp:
            replay: list = []
            in_replay = True
            for ev in self._event_stream(resp):
                if self._stop.is_set():
                    return
                if ev is None:
                    continue  # heartbeat: only the stop check mattered
                etype = ev.get("type")
                if etype == "BOOKMARK":
                    if in_replay:
                        self._swap(replay)
                        in_replay = False
                        self.resyncs += 1
                        self.synced.set()
                    continue
                kind = ev.get("kind", "")
                obj_d = ev.get("object")
                if obj_d is None:
                    continue
                rv = ev.get("resourceVersion")
                rv = int(rv) if rv is not None else None
                if in_replay:
                    replay.append((kind, obj_d, rv))
                    continue
                self._apply(etype, kind, obj_d, rv)

    def _swap(self, replay) -> None:
        """Atomically reconcile the mirror to the replayed state (the
        re-list: stale mirror objects are deleted)."""
        fresh = {}
        for kind, obj_d, rv in replay:
            self.mirror.register_kind(kind)
            obj = _decode(kind, obj_d)
            fresh[(kind,) + self.mirror._key(kind, obj)] = (obj, rv)
        with self.mirror._lock:
            # delete what disappeared while we were away
            for kind in list(self.mirror.kinds):
                for key in list(self.mirror._store[kind]):
                    if (kind,) + key not in fresh:
                        ns, name = key
                        self.mirror.delete(kind, ns, name)
            for (kind, _ns, _name), (obj, rv) in fresh.items():
                # remote resourceVersions are preserved in the mirror so
                # CAS writes (expect_rv) round-trip to the remote store
                self.mirror.apply_event("MODIFIED", kind, obj, rv=rv)

    def _apply(self, etype: str, kind: str, obj_d: dict,
               rv: Optional[int] = None) -> None:
        self.mirror.register_kind(kind)
        obj = _decode(kind, obj_d)
        self.mirror.apply_event(etype, kind, obj, rv=rv)


def remote_victim_deleter(server: str, token: str = ""):
    """Preemption victim deletion against the remote apiserver (the
    PodPreemptor.DeletePod path, scheduler.go:319-326).  The DELETE event
    then reflects back into the mirror."""
    server = server.rstrip("/")

    def delete(pod) -> None:
        req = urllib.request.Request(
            f"{server}/api/v1/namespaces/{pod.namespace}/pods/{pod.name}",
            method="DELETE", headers=_auth_headers(token),
        )
        try:
            _urlopen(req, timeout=10)
        except (urllib.error.HTTPError, urllib.error.URLError):
            pass  # already gone / transient: the requeue path retries

    return delete


def remote_unbinder(server: str, token: str = ""):
    """Gang-rollback unbind against the remote apiserver: read-modify-write
    the pod with spec.nodeName cleared (the store-level unbind analog)."""
    server = server.rstrip("/")

    def unbind(pod, _retries: int = 3) -> bool:
        base = f"{server}/api/v1/namespaces/{pod.namespace}/pods/{pod.name}"
        for _ in range(_retries):
            try:
                get_req = urllib.request.Request(
                    base, headers=_auth_headers(token))
                with _urlopen(get_req, timeout=10) as resp:
                    d = json.loads(resp.read())
                d.setdefault("spec", {})["nodeName"] = ""
                # carry the fetched resourceVersion so the server's CAS
                # rejects this write if a concurrent status update / re-bind
                # landed between our GET and PUT (no silent clobber)
                req = urllib.request.Request(
                    base, data=json.dumps(d).encode(), method="PUT",
                    headers=_auth_headers(token, json_body=True),
                )
                with _urlopen(req, timeout=10) as resp:
                    return resp.status == 200
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    continue  # stale read: re-GET and retry the CAS
                return False
            except urllib.error.URLError:
                return False
        return False

    return unbind


class RemoteBinder:
    """Scheduler binder that POSTs the Binding subresource to the remote
    apiserver (scheduler.go:411-435 b.Create path)."""

    def __init__(self, server: str, token: str = ""):
        self.server = server.rstrip("/")
        self.token = token

    def __call__(self, pod, node_name: str) -> bool:
        body = json.dumps({"target": {"name": node_name}}).encode()
        req = urllib.request.Request(
            f"{self.server}/api/v1/namespaces/{pod.namespace}/pods/"
            f"{pod.name}/binding",
            data=body, method="POST",
            headers=_auth_headers(self.token, json_body=True),
        )
        try:
            with _urlopen(req, timeout=10) as resp:
                return resp.status in (200, 201)
        except urllib.error.HTTPError:
            return False  # 409 conflict etc -> scheduler rolls back + retries
        except urllib.error.URLError:
            return False
