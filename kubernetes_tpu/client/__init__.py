"""Client machinery: the client-go analog (SURVEY.md layer 5)."""

from kubernetes_tpu.client.reflector import (
    Reflector,
    RemoteBinder,
    remote_unbinder,
    remote_victim_deleter,
)

__all__ = [
    "Reflector", "RemoteBinder", "remote_unbinder", "remote_victim_deleter",
]
