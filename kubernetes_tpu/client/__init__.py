"""Client machinery: the client-go analog (SURVEY.md layer 5)."""

from kubernetes_tpu.client.informer import (
    DeltaFIFO,
    Indexer,
    SharedIndexInformer,
    SharedInformerFactory,
    wire_scheduler_informers,
)
from kubernetes_tpu.client.reflector import (
    Reflector,
    RemoteBinder,
    remote_unbinder,
    remote_victim_deleter,
)
from kubernetes_tpu.client.remote import RemoteCluster

__all__ = [
    "DeltaFIFO", "Indexer", "SharedIndexInformer", "SharedInformerFactory",
    "wire_scheduler_informers",
    "Reflector", "RemoteBinder", "remote_unbinder", "remote_victim_deleter",
    "RemoteCluster",
]
