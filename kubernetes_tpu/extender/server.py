"""HTTP scheduler-extender sidecar: the out-of-process seam.

Implements the reference's extender protocol (pkg/scheduler/core/extender.go;
wire types pkg/scheduler/api/v1/types.go; config api/types.go:203-233) so a
STOCK Go kube-scheduler can offload Filter/Prioritize/Preempt/Bind to the TPU
pipeline with `NodeCacheCapable: true`:

  POST <filterVerb>      ExtenderArgs{Pod, NodeNames}   -> ExtenderFilterResult
  POST <prioritizeVerb>  ExtenderArgs{Pod, NodeNames}   -> HostPriorityList
  POST <preemptVerb>     ExtenderPreemptionArgs          -> ExtenderPreemptionResult
  POST <bindVerb>        ExtenderBindingArgs             -> ExtenderBindingResult

NodeCacheCapable=true means the scheduler sends only node *names* and the
extender mirrors cluster state itself (api/types.go:226-229) — exactly the
device-resident-tensor model.  The mirror is fed by the sync endpoints
(the watch-ingest seam; a client-go informer relay or our LocalCluster can
drive them):

  POST /sync/node        add/update one Node (JSON)
  POST /sync/node/remove {"name": ...}
  POST /sync/pod         add one (assigned) Pod
  POST /sync/pod/remove  {"namespace": ..., "name": ...}
  POST /sync/service     {"namespace": ..., "selector": {...}}
  GET  /healthz, /metrics (Prometheus text)

Scoring contract: extender Prioritize returns 0..10 per node (weighted by the
extender's configured weight on the scheduler side, extender.go:318-358); we
return the TPU total score rescaled to 0..10.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.codec.schema import FilterConfig, NUM_PREDICATES, PREDICATE_ORDER
from kubernetes_tpu.models.generic import schedule_batch_independent
from kubernetes_tpu.models.preemption import (
    pick_preemption_node,
    preemption_candidates,
    sorted_victim_slots,
)
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.utils import metrics as m


class ExtenderServer:
    """Threaded HTTP server around a SchedulerCache + the device pipeline."""

    def __init__(
        self,
        cache: Optional[SchedulerCache] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        filter_config: Optional[FilterConfig] = None,
    ):
        self.cache = cache or SchedulerCache()
        self.cfg = filter_config or FilterConfig()
        enc = self.cache.encoder
        self.cfg = enc.adopt_filter_config(self.cfg)
        self._unsched = enc.interner.intern("node.kubernetes.io/unschedulable")
        # pods seen via /filter, so a later /bind can assume them with their
        # real resource requests; evicted on bind and on /sync pod events,
        # FIFO-capped so never-bound pods cannot leak for the server's life
        self._pending: "OrderedDict[tuple, Pod]" = OrderedDict()
        self._pending_cap = 10000
        # trace ids seen on incoming verb requests (the scheduler's
        # cycle traceparent, utils/trace.py): the extender half of the
        # end-to-end join — bounded, newest last
        self.seen_trace_ids: "deque[str]" = deque(maxlen=256)
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    @property
    def address(self):
        return self._httpd.server_address

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # ------------------------------------------------------------ pipeline

    @staticmethod
    def _arg(args: dict, *names):
        """Wire tolerance: the v1 wire format is lowercase ("pod",
        "nodenames" — api/v1/types.go:241-247 json tags) but accept the Go
        field spelling too."""
        for n in names:
            if n in args and args[n] is not None:
                return args[n]
        return None

    def _requested_nodes(self, args: dict, enc):
        names = self._arg(args, "nodenames", "NodeNames")
        if names is None:
            # non-NodeCacheCapable mode: full NodeList objects
            nl = self._arg(args, "nodes", "Nodes") or {}
            items = nl.get("items") if isinstance(nl, dict) else None
            if items:
                names = [n.get("metadata", {}).get("name", "") for n in items]
        return names if names is not None else list(enc.node_rows)

    def filter(self, args: dict) -> dict:
        pod_d = self._arg(args, "pod", "Pod")
        if pod_d is None:
            return {"nodenames": [], "failedNodes": {}, "error": "missing pod"}
        pod = Pod.from_dict(pod_d)
        enc = self.cache.encoder
        # hold the cache lock across compute AND row->name decode: a
        # concurrent /sync could recycle rows between the two (_pending is
        # guarded by the same lock against concurrent handler threads)
        with self.cache._lock:
            self._pending.pop((pod.namespace, pod.name), None)
            self._pending[(pod.namespace, pod.name)] = pod
            while len(self._pending) > self._pending_cap:
                self._pending.popitem(last=False)
            # encode BEFORE snapshot: terms register topology keys with
            # node-pair backfill that the snapshot must include
            batch = enc.encode_pods([pod])
            cluster, _ = self.cache.snapshot()
            out = schedule_batch_independent(
                cluster, batch, 0, self.cfg, self._unsched, enc.getzone_key
            )
            mask = np.asarray(out["mask"])[0]
            failure = np.asarray(out["failure"])[0]
            requested = self._requested_nodes(args, enc)
            ok, failed = [], {}
            for name in requested:
                row = enc.node_rows.get(name)
                if row is None:
                    failed[name] = "node not in extender cache"
                elif mask[row]:
                    ok.append(name)
                else:
                    idx = int(failure[row])
                    failed[name] = (
                        PREDICATE_ORDER[idx] if idx < NUM_PREDICATES else "Unschedulable"
                    )
        return {"nodenames": ok, "failedNodes": failed, "error": ""}

    def prioritize(self, args: dict) -> list:
        pod_d = self._arg(args, "pod", "Pod")
        if pod_d is None:
            return []
        pod = Pod.from_dict(pod_d)
        enc = self.cache.encoder
        with self.cache._lock:
            # encode BEFORE snapshot: terms register topology keys with
            # node-pair backfill that the snapshot must include
            batch = enc.encode_pods([pod])
            cluster, _ = self.cache.snapshot()
            out = schedule_batch_independent(
                cluster, batch, 0, self.cfg, self._unsched, enc.getzone_key
            )
            scores = np.asarray(out["scores"])[0]
            requested = self._requested_nodes(args, enc)
            # rescale the weighted total to the extender's 0..10 contract
            rows = [enc.node_rows[n] for n in requested if n in enc.node_rows]
            mx = max((scores[r] for r in rows), default=0.0)
            result = []
            for name in requested:
                row = enc.node_rows.get(name)
                s = 0 if row is None or mx <= 0 else int(10.0 * scores[row] / mx)
                result.append({"host": name, "score": s})
        return result

    def preempt(self, args: dict) -> dict:
        pod_d = self._arg(args, "pod", "Pod")
        if pod_d is None:
            return {"nodeNameToMetaVictims": {}}
        pod = Pod.from_dict(pod_d)
        enc = self.cache.encoder
        from kubernetes_tpu.ops import filter_batch

        from kubernetes_tpu.ops.predicates import required_affinity_ok

        with self.cache._lock:
            # encode BEFORE snapshot (topology-key backfill), as in filter/
            # prioritize above
            batch = enc.encode_pods([pod])
            cluster, _ = self.cache.snapshot()
            _, per_pred = filter_batch(cluster, batch, self.cfg, self._unsched)
            aff_ok = required_affinity_ok(cluster, batch)
            cands = preemption_candidates(
                np.asarray(per_pred), np.asarray(cluster.valid), np.asarray(aff_ok)
            )[0]
            arena = enc.pods_snapshot()
            violating = np.zeros(len(arena.node), bool)  # no PDB feed over the wire
            slots = sorted_victim_slots(
                arena.priority, arena.valid, arena.node, pod.spec.priority,
                violating, arena.start,
            )
            node_row, victim_ms, _, res = pick_preemption_node(
                enc, pod, cands, arena, slots, violating, self.cfg.max_vols
            )
            if node_row < 0:
                return {"nodeNameToMetaVictims": {}}
            node_name = enc.row_name(node_row)
            # the v1.15 scheduler (HTTPExtender.convertPodUIDToPod) matches
            # MetaPod.UID against pod.UID in its NodeInfo — emit the real uid
            victims = [
                {"uid": arena.uids[mi] or f"{arena.keys[mi][0]}/{arena.keys[mi][1]}"}
                for mi in victim_ms
            ]
        return {
            "nodeNameToMetaVictims": {
                node_name: {
                    "pods": victims,
                    "numPDBViolations": int(res.n_pdb_violations),
                }
            }
        }

    def bind(self, args: dict) -> dict:
        # assume into the mirror; the scheduler does the real API bind when
        # BindVerb is configured the extender owns binding (extender.go:360-385)
        name = self._arg(args, "PodName", "podName") or ""
        ns = self._arg(args, "PodNamespace", "podNamespace") or "default"
        node = self._arg(args, "Node", "node") or ""
        with self.cache._lock:
            rec = self.cache.encoder.pods.get((ns, name))
            if rec is not None:
                return {"Error": ""}
            # an unknown pod cannot be assumed with real resource accounting:
            # the NodeCacheCapable contract requires the extender mirror to
            # have seen it via /sync first — surface the miss instead of
            # fabricating an empty pod never charged to the node
            pending = self._pending.pop((ns, name), None)
        if pending is not None:
            self.cache.assume_pod(
                dataclasses.replace(
                    pending, spec=dataclasses.replace(pending.spec, node_name=node)
                )
            )
            return {"Error": ""}
        return {"Error": f"unknown pod {ns}/{name}: not in extender mirror"}

    # ------------------------------------------------------------- handler

    def _make_handler(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, obj, code=200, content_type="application/json"):
                body = (
                    obj.encode() if isinstance(obj, str) else json.dumps(obj).encode()
                )
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send("ok", content_type="text/plain")
                elif self.path == "/metrics":
                    self._send(m.REGISTRY.expose(), content_type="text/plain")
                else:
                    self._send({"error": "not found"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                from kubernetes_tpu.utils.trace import trace_id_of

                tid = trace_id_of(self.headers.get("Traceparent", ""))
                if tid:
                    outer.seen_trace_ids.append(tid)
                try:
                    args = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send({"Error": "bad json"}, 400)
                    return
                try:
                    if self.path == "/filter":
                        self._send(outer.filter(args))
                    elif self.path == "/prioritize":
                        self._send(outer.prioritize(args))
                    elif self.path == "/preempt":
                        self._send(outer.preempt(args))
                    elif self.path == "/bind":
                        self._send(outer.bind(args))
                    elif self.path == "/sync/node":
                        outer.cache.add_node(Node.from_dict(args))
                        self._send({"ok": True})
                    elif self.path == "/sync/node/remove":
                        outer.cache.remove_node(args["name"])
                        self._send({"ok": True})
                    elif self.path == "/sync/pod":
                        p = Pod.from_dict(args)
                        with outer.cache._lock:
                            outer._pending.pop((p.namespace, p.name), None)
                        outer.cache.add_pod(p)
                        self._send({"ok": True})
                    elif self.path == "/sync/pod/remove":
                        key = (args.get("namespace", "default"), args["name"])
                        with outer.cache._lock:
                            outer._pending.pop(key, None)
                        outer.cache.remove_pod(
                            Pod.from_dict(
                                {"metadata": {"name": key[1], "namespace": key[0]}}
                            )
                        )
                        self._send({"ok": True})
                    elif self.path == "/sync/service":
                        outer.cache.encoder.add_spread_selector(
                            args.get("namespace", "default"), args.get("selector") or {}
                        )
                        self._send({"ok": True})
                    else:
                        self._send({"error": "not found"}, 404)
                except Exception as e:  # surface errors in the reply, not a 500 stack
                    self._send({"Error": f"{type(e).__name__}: {e}"}, 500)

        return Handler
