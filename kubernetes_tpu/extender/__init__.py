from kubernetes_tpu.extender.server import ExtenderServer
