"""Scheduler-side HTTP extender client.

The analog of HTTPExtender (ref pkg/scheduler/core/extender.go:42-445): our
scheduler *calls out* to external extenders — filter round-trips narrow the
feasible set, prioritize results weight-merge into the score matrix
(generic_scheduler.go:774-804), and a bind-verb extender replaces the default
binder for pods it manages.  Config spelling mirrors ExtenderConfig
(pkg/scheduler/api/types.go:203-240: urlPrefix/filterVerb/prioritizeVerb/
bindVerb/weight/httpTimeout/nodeCacheCapable/managedResources/ignorable).

Tensor-pipeline integration: the reference chains extenders AFTER its in-tree
predicate scan per pod (generic_scheduler.go:527-554).  Here the device scan
is one launch for the whole batch, so extender verdicts are gathered host-side
FIRST and folded in as an extra feasibility mask / score addend — the same
intersection/addition semantics, reordered (extender approval is never a
union, so filtering before or after the device pass yields the same set).
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod

# transport-level failures retried for IDEMPOTENT verbs only (refused,
# reset, DNS blip, timeout — URLError wraps most of these from urllib;
# OSError covers raw sockets, ConnectionError/socket.timeout are
# subclasses).  A read timeout can fire AFTER the server executed the
# request, so only verbs that tolerate a duplicate (filter/prioritize/
# preempt re-evaluate the same state) retry; bind never does.
# Application-level failures are NOT transient and surface immediately:
# an HTTP error status (HTTPError — the server spoke), an HTTP 200 with
# an "error" body, or malformed JSON.
_TRANSIENT_HTTP_ERRORS = (urllib.error.URLError, TimeoutError, OSError)


class ExtenderError(Exception):
    """Non-ignorable extender failure: scheduling of the pod fails
    (generic_scheduler.go:533-541)."""


@dataclass(frozen=True)
class ExtenderConfig:
    """ref pkg/scheduler/api/types.go:203-240 (ExtenderConfig)."""

    url_prefix: str
    filter_verb: str = ""
    preempt_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    http_timeout: float = 30.0      # DefaultExtenderTimeout (extender.go:39)
    node_cache_capable: bool = False
    managed_resources: Tuple[str, ...] = ()
    ignorable: bool = False
    # bounded retry for TRANSIENT transport failures (no reference analog —
    # the reference fails the pod on the first round-trip error): up to
    # max_retries re-sends with jittered exponential backoff, the whole
    # attempt train capped by http_timeout as the TOTAL budget, so an
    # ignorable extender's flakiness delays a cycle by at most its
    # configured timeout before the scheduler skips it.
    max_retries: int = 2
    retry_backoff_s: float = 0.02

    @staticmethod
    def from_dict(d: dict) -> "ExtenderConfig":
        """Policy-JSON spelling (v1 Policy "extenders" entries).

        httpTimeout is a Go time.Duration, which marshals to JSON as integer
        NANOSECONDS — a real policy file says 100000000 for 100ms."""
        ns = d.get("httpTimeout")
        return ExtenderConfig(
            url_prefix=d.get("urlPrefix", ""),
            filter_verb=d.get("filterVerb", ""),
            preempt_verb=d.get("preemptVerb", ""),
            prioritize_verb=d.get("prioritizeVerb", ""),
            bind_verb=d.get("bindVerb", ""),
            weight=int(d.get("weight", 1)),
            http_timeout=float(ns) / 1e9 if ns else 30.0,
            node_cache_capable=bool(d.get("nodeCacheCapable", False)),
            managed_resources=tuple(
                r.get("name", "") for r in d.get("managedResources") or ()
            ),
            ignorable=bool(d.get("ignorable", False)),
            max_retries=int(d.get("maxRetries", 2)),
            retry_backoff_s=float(d.get("retryBackoffSeconds", 0.02)),
        )


def pod_to_dict(pod: Pod) -> dict:
    """Wire form of the fields our Pod model carries (ExtenderArgs.Pod)."""
    return {
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "uid": pod.metadata.uid,
            "labels": dict(pod.labels),
        },
        "spec": {
            "nodeName": pod.spec.node_name,
            "priority": pod.spec.priority,
            "containers": [_container_to_dict(c) for c in pod.spec.containers],
            "initContainers": [
                _container_to_dict(c) for c in pod.spec.init_containers
            ],
        },
    }


def _container_to_dict(c) -> dict:
    return {
        "name": c.name,
        "image": c.image,
        "resources": {
            "requests": {k: str(q) for k, q in c.requests.items()},
            "limits": {k: str(q) for k, q in c.limits.items()},
        },
        "ports": [
            {
                "hostPort": p.host_port,
                "containerPort": p.container_port,
                "protocol": p.protocol,
                "hostIP": p.host_ip,
            }
            for p in c.ports
        ],
    }


class HTTPExtender:
    """One configured extender endpoint.

    `transport` (tests): callable (url, payload_dict, timeout) -> response
    dict, replacing the urllib POST.
    """

    def __init__(
        self,
        config: ExtenderConfig,
        transport: Optional[Callable[[str, dict, float], dict]] = None,
    ):
        self.config = config
        self._transport = transport or self._http_post
        # deterministic per-endpoint jitter stream (tests stay seeded)
        self._retry_rng = random.Random(config.url_prefix)

    @property
    def name(self) -> str:                       # extender.go:119-122
        return self.config.url_prefix

    @property
    def is_binder(self) -> bool:                 # extender.go:384-387
        return bool(self.config.bind_verb)

    @property
    def is_ignorable(self) -> bool:
        return self.config.ignorable

    def is_interested(self, pod: Pod) -> bool:
        """extender.go:415-436: managed-resources gate — empty set means
        every pod; otherwise any container (incl. init) must request one."""
        managed = set(self.config.managed_resources)
        if not managed:
            return True
        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            if managed & set(c.requests) or managed & set(c.limits):
                return True
        return False

    @property
    def supports_preemption(self) -> bool:    # extender.go:129-132
        return bool(self.config.preempt_verb)

    # ------------------------------------------------------------- verbs

    def _args(self, pod: Pod, node_names: Sequence[str]) -> dict:
        """ExtenderArgs: names only when nodeCacheCapable, else node
        objects (extender.go:274-291)."""
        args: dict = {"pod": pod_to_dict(pod)}
        if self.config.node_cache_capable:
            args["nodenames"] = list(node_names)
        else:
            args["nodes"] = {
                "items": [{"metadata": {"name": n}} for n in node_names]
            }
        return args

    def filter(
        self, pod: Pod, node_names: Sequence[str]
    ) -> Tuple[List[str], Dict[str, str]]:
        """extender.go:258-316 Filter.  Returns (feasible subset, failed
        node -> reason).  Raises ExtenderError on transport/Error result."""
        if not self.config.filter_verb:
            return list(node_names), {}
        result = self._send(self.config.filter_verb, self._args(pod, node_names))
        try:
            if result.get("error"):
                raise ExtenderError(result["error"])
            if self.config.node_cache_capable and result.get("nodenames") is not None:
                ok = list(result["nodenames"])
            elif result.get("nodes") is not None:
                ok = [
                    it.get("metadata", {}).get("name", "")
                    for it in result["nodes"].get("items", [])
                ]
            else:
                ok = []
            return ok, dict(result.get("failedNodes") or {})
        except ExtenderError:
            raise
        except Exception as e:  # malformed 200 response
            raise ExtenderError(
                f"extender {self.name} filter: bad response: {e}"
            ) from e

    def prioritize(
        self, pod: Pod, node_names: Sequence[str]
    ) -> Tuple[Dict[str, float], int]:
        """extender.go:318-358 Prioritize: (host -> score, weight); scores
        merge additively as score*weight (generic_scheduler.go:790-799)."""
        if not self.config.prioritize_verb:
            return {n: 0.0 for n in node_names}, 0
        result = self._send(
            self.config.prioritize_verb, self._args(pod, node_names)
        )
        try:
            scores: Dict[str, float] = {}
            for item in result or []:
                scores[item.get("host", "")] = float(item.get("score", 0))
            return scores, self.config.weight
        except Exception as e:  # malformed 200 response (dict, strings, ...)
            raise ExtenderError(
                f"extender {self.name} prioritize: bad response: {e}"
            ) from e

    def process_preemption(
        self, pod: Pod, node_victims: Dict[str, dict]
    ) -> Dict[str, dict]:
        """extender.go:135-200 ProcessPreemption: candidate node ->
        MetaVictims ({"pods": [{"uid": ...}], "numPDBViolations": n});
        the extender returns the (possibly narrowed) map — a node absent
        from the reply is no longer a preemption candidate."""
        if not self.supports_preemption:
            raise ExtenderError(
                f"preempt verb is not defined for extender {self.name}"
            )
        args = {
            "pod": pod_to_dict(pod),
            "nodeNameToMetaVictims": node_victims,
        }
        result = self._send(self.config.preempt_verb, args)
        try:
            return dict(result.get("nodeNameToMetaVictims") or {})
        except Exception as e:
            raise ExtenderError(
                f"extender {self.name} preempt: bad response: {e}"
            ) from e

    def bind(self, namespace: str, name: str, uid: str, node: str) -> None:
        """extender.go:360-382 Bind; raises ExtenderError on failure."""
        if not self.is_binder:
            raise ExtenderError("unexpected empty bindVerb in extender")
        # ExtenderBindingArgs carries NO json tags in the reference
        # (api/v1/types.go), so the wire spelling is the Go field names
        result = self._send(
            self.config.bind_verb,
            {"PodName": name, "PodNamespace": namespace, "PodUID": uid,
             "Node": node},
            idempotent=False,  # a bind may have executed before the
            #                    transport error surfaced: never re-POST
        )
        if not isinstance(result, dict):
            raise ExtenderError(
                f"extender {self.name} bind: bad response: {result!r}"
            )
        # ExtenderBindingResult also has no json tags -> "Error" on the wire
        err = result.get("Error") or result.get("error")
        if err:
            raise ExtenderError(err)

    # --------------------------------------------------------- transport

    def _send(self, verb: str, args, idempotent: bool = True) -> dict:
        """One verb round-trip with bounded transient retry: up to
        config.max_retries re-sends with jittered exponential backoff for
        connection-level failures, the whole train budgeted by
        config.http_timeout (each attempt's transport timeout is the
        REMAINING budget, so retries can never stretch a cycle past the
        per-extender timeout the operator configured).

        idempotent=False (the bind verb) disables retry entirely: a read
        timeout can fire AFTER the server executed the request, and only
        idempotent verbs (filter/prioritize/preempt re-evaluate the same
        state) tolerate the duplicate."""
        url = self.config.url_prefix.rstrip("/") + "/" + verb
        cfg = self.config
        deadline = time.monotonic() + cfg.http_timeout
        delay = max(cfg.retry_backoff_s, 0.0)
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            try:
                return self._transport(url, args, max(remaining, 0.001))
            except ExtenderError:
                raise
            except urllib.error.HTTPError as e:
                # non-2xx status: the request REACHED the extender — never
                # retried (HTTPError subclasses URLError, so this must be
                # caught before the transient family)... with ONE carve-out:
                # 429 TooManyRequests means the extender shed the request
                # before executing it, so idempotent verbs retry, paced by
                # the server's Retry-After when it sent one
                if e.code != 429 or not idempotent:
                    raise ExtenderError(f"extender {url}: {e}") from e
                from kubernetes_tpu.client.reflector import parse_retry_after

                attempt += 1
                pause = max(
                    parse_retry_after(e.headers),
                    delay * (1.0 + self._retry_rng.random()),
                )
                if (
                    attempt > cfg.max_retries
                    or time.monotonic() + pause >= deadline
                ):
                    raise ExtenderError(
                        f"extender {url}: {e} (after {attempt} attempts)"
                    ) from e
                time.sleep(pause)
                delay *= 2.0
            except _TRANSIENT_HTTP_ERRORS as e:
                if not idempotent:
                    raise ExtenderError(f"extender {url}: {e}") from e
                attempt += 1
                # jitter spreads synchronized retries across pods' threads
                pause = delay * (1.0 + self._retry_rng.random())
                if (
                    attempt > cfg.max_retries
                    or time.monotonic() + pause >= deadline
                ):
                    raise ExtenderError(
                        f"extender {url}: {e} (after {attempt} attempts)"
                    ) from e
                time.sleep(pause)
                delay *= 2.0
            except Exception as e:  # malformed JSON, protocol errors
                raise ExtenderError(f"extender {url}: {e}") from e

    @staticmethod
    def _http_post(url: str, payload: dict, timeout: float) -> dict:
        headers = {"Content-Type": "application/json"}
        # the scheduler sets the cycle's trace context around the
        # extender fan-out (and the bind tail): every extender
        # round-trip carries the cycle's traceparent so the extender
        # side is joinable to the scheduling decision (utils/trace.py)
        from kubernetes_tpu.utils.trace import (
            TRACEPARENT_HEADER,
            current_traceparent,
        )

        tp = current_traceparent()
        if tp:
            headers[TRACEPARENT_HEADER] = tp
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers=headers,
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())


def build_extenders(configs: Sequence[dict]) -> List[HTTPExtender]:
    """Policy JSON "extenders" list -> clients (factory.go CreateFromConfig
    path that instantiates HTTPExtender per entry)."""
    return [HTTPExtender(ExtenderConfig.from_dict(c)) for c in configs]
