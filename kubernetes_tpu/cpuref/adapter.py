"""CpuEngineAdapter: the degraded-mode engine behind the device breaker.

BASELINE names "graceful fallback to the CPU path" as part of the north
star; this adapter is that path's engine seam.  While the device breaker
(runtime/health.DeviceHealth) is open, Scheduler routes each cycle's
placement through the object-level golden scheduler (cpuref/reference.py)
instead of the XLA engine — same pods in, same winners-shape out
(i32[B] node ROWS, -1 = unschedulable), so the entire commit tail
(assume, bind, events, metrics, requeues, preemption bookkeeping) runs
unchanged and the audit trail is indistinguishable from a device cycle.

Equivalence contract (pinned by tests/test_device_faults.py): on the same
snapshot the adapter reproduces the device engine's placements —
  * sequential-commit semantics: pod i sees pods 0..i-1 of its own batch
    already placed (resources, ports, spread counts, affinity pairs);
  * selectHost parity: winner = ties[(last_index0 + i) % len(ties)] with
    ties enumerated in device ROW order (ops/select.py select_host);
  * extender verdicts fold in as the same mask/score addends;
  * nominated pods are charged to their nominated nodes (pass one of the
    two-pass evaluation), matching encode_nominated + the nominated block.
Scores are computed in Python floats vs the device's f32; the float-blend
priorities can drift by 1 (the documented parity tolerance, PARITY.md), so
bit-identity holds whenever score gaps exceed that drift — which the
degraded-path tests arrange, and real ties resolve identically because the
rotation index, not the float, picks the winner.

Framework tensor plugins and extenders need no special handling here:
both run HOST-side in _encode_and_dispatch before the engine choice, and
their verdicts arrive as the extra_mask/extra_score addends either engine
consumes.  The one deliberate non-goal: percentageOfNodesToScore sampling
is ignored (all nodes scanned — degraded mode trades a little extra CPU
for the simpler exact scan, and a superset scan can only improve
placement).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.codec.schema import DEFAULT_PRIORITY_WEIGHTS, PRIORITY_ORDER
from kubernetes_tpu.cpuref.reference import CPUScheduler


class CpuEngineAdapter:
    """Builds a CPUScheduler view of the live cache per cycle and runs the
    sequential-commit placement loop over it.  Stateless between calls —
    every batch re-reads the encoder's retained objects under the cache
    lock, so degraded cycles always see the freshest committed state (the
    same property a new device snapshot would have)."""

    def __init__(self, cache, config):
        self.cache = cache      # runtime.cache.SchedulerCache
        self.config = config    # runtime.scheduler.SchedulerConfig

    # ------------------------------------------------------------ plumbing

    def _golden(self, extra_pods: Sequence[Pod] = ()):
        """(CPUScheduler, nodes-in-row-order, name->row) from the encoder's
        retained objects.  Caller holds the cache lock."""
        enc = self.cache.encoder
        rows = sorted((row, name) for name, row in enc.node_rows.items())
        nodes = [enc._row_node[row] for row, _ in rows]
        row_of = {name: row for row, name in rows}
        pods = [
            rec.pod
            for rec in enc.pods.values()
            if rec.pod is not None and rec.pod.spec.node_name
        ]
        golden = CPUScheduler(
            nodes,
            pods + list(extra_pods),
            list(enc._service_selectors),
            max_vols=tuple(self.config.filter_config.max_vols),
            pvs=list(enc.pvs.values()),
            pvcs=list(enc.pvcs.values()),
            storage_classes=list(enc.storage_classes.values()),
            service_affinity_labels=[
                enc.interner.string(k) for k in enc.service_affinity_keys
            ],
        )
        return golden, nodes, row_of

    def _weights(self) -> Dict[str, float]:
        w = self.config.weights
        if w is None:
            w = DEFAULT_PRIORITY_WEIGHTS
        return dict(zip(PRIORITY_ORDER, np.asarray(w, np.float64).tolist()))

    @staticmethod
    def _assumed_copy(pod: Pod, node_name: str) -> Pod:
        spec = copy.copy(pod.spec)
        spec.node_name = node_name
        assumed = copy.copy(pod)
        assumed.spec = spec
        return assumed

    # ------------------------------------------------------------- engine

    def schedule_batch(
        self,
        pods: Sequence[Pod],
        last_index0: int,
        extra_mask: Optional[np.ndarray] = None,
        extra_score: Optional[np.ndarray] = None,
        nominated: Sequence[Tuple[Pod, str]] = (),
        masked: frozenset = frozenset(),
        row_map: Optional[Dict[str, int]] = None,
    ) -> np.ndarray:
        """Place `pods` sequentially against the live cache state.

        extra_mask/extra_score are the device path's [Bp, N] extender/
        framework addends (row-indexed; Bp >= len(pods) from the pow2 pad);
        their COLUMNS are indexed by `row_map`, the snapshot-time
        name->row map the fan-out was built against — the live encoder's
        rows may have been recycled/regrown by informer threads since
        (scheduler.py documents this race for the extender path).  A node
        absent from row_map (added after the snapshot) is treated as
        masked when a mask exists: the device path would not have seen it
        either.  `masked` holds batch indices whose extender errored (the
        commit tail routes them by ext_failed regardless of the winner
        value).  Returns i32[len(pods)] LIVE device node rows (they feed
        enc.row_name), -1 = unschedulable."""
        hosts = np.full(len(pods), -1, np.int32)
        with self.cache._lock:
            nom_assumed = [
                self._assumed_copy(p, node) for p, node in nominated
            ]
            golden, nodes, row_of = self._golden(extra_pods=nom_assumed)
            name_of_row = {row_of[n.name]: n.name for n in nodes}
            mask_col = row_of if row_map is None else row_map
            weights = self._weights()

            def mask_ok(i, node):
                if extra_mask is None:
                    return True
                col = mask_col.get(node.name)
                if col is None or col >= extra_mask.shape[1]:
                    return False  # node unknown to the snapshot/fan-out
                return bool(extra_mask[i, col])

            for i, pod in enumerate(pods):
                if i in masked:
                    continue
                feasible = [
                    node
                    for node in nodes
                    if mask_ok(i, node) and golden.fits(pod, node)
                ]
                if not feasible:
                    continue
                totals = golden.total_scores(pod, weights)
                scores = []
                for node in feasible:
                    s = float(totals.get(node.name, 0.0))
                    if extra_score is not None:
                        col = mask_col.get(node.name)
                        if col is not None and col < extra_score.shape[1]:
                            s += float(extra_score[i, col])
                    scores.append(s)
                best = max(scores)
                # ties enumerate in ROW order (feasible preserves `nodes`,
                # which is row-sorted) — the select_host rotation contract
                ties = [
                    row_of[node.name]
                    for node, s in zip(feasible, scores)
                    if s == best
                ]
                win_row = ties[(int(last_index0) + i) % len(ties)]
                hosts[i] = win_row
                # in-batch sequential commit: later pods see this placement
                win_name = name_of_row[win_row]
                assumed = self._assumed_copy(pod, win_name)
                golden.pods.append(assumed)
                golden.by_node[win_name].append(assumed)
        return hosts

    # --------------------------------------------------------- preemption

    def preempt_candidates(self, pod: Pod, n_cap: int) -> np.ndarray:
        """bool[n_cap] by device row: nodes where preemption might help —
        the pod does not fit, but no UNRESOLVABLE predicate fails and its
        required-affinity rules hold (nodesWherePreemptionMightHelp,
        generic_scheduler.go:1013-1053 — the CPU stand-in for the device
        preempt eval while the breaker is open).  The host-side victim
        pick (models/preemption.pick_preemption_node) re-verifies every
        candidate, so a superset mask stays safe."""
        cands = np.zeros(int(n_cap), bool)
        with self.cache._lock:
            golden, nodes, row_of = self._golden()
            for node in nodes:
                preds = golden.predicates(pod, node)
                if all(preds.values()):
                    continue  # already fits: preemption not needed here
                if not all(
                    preds[p] for p in CPUScheduler.UNRESOLVABLE if p in preds
                ):
                    continue
                if not golden._affinity_rules_ok(pod, node):
                    continue
                row = row_of[node.name]
                if row < len(cands):
                    cands[row] = True
        return cands
