"""Object-level golden scheduler, mirroring pkg/scheduler/algorithm semantics.

Every function cites the reference Go code it reproduces.  Integer score math
uses Python ints, matching the reference's int64 truncation exactly.
"""

from __future__ import annotations

import json
import math
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api import labels as klabels
from kubernetes_tpu.api.resource import Quantity
from kubernetes_tpu.codec.schema import NUM_VOL_TYPES, VOL_CSI
from kubernetes_tpu.api.types import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    Node,
    Pod,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
    Taint,
)

MAX_PRIORITY = 10
ZONE_KEY = "failure-domain.beta.kubernetes.io/zone"
REGION_KEY = "failure-domain.beta.kubernetes.io/region"
ZONE_WEIGHTING = 2.0 / 3.0
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"


# ------------------------------------------------------------------ helpers


def get_zone_key(node: Node) -> Optional[str]:
    """ref pkg/util/node/node.go:126-143 GetZoneKey: region + ":\\x00:" + zone,
    None when both labels are absent/empty (node belongs to no zone)."""
    region = node.labels.get(REGION_KEY, "")
    zone = node.labels.get(ZONE_KEY, "")
    if not region and not zone:
        return None
    return region + ":\x00:" + zone


def pod_requests(pod: Pod) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, q in pod.resource_request().items():
        out[k] = q.milli if k == RESOURCE_CPU else float(q)
    return out


def nonzero_requests(pod: Pod) -> Tuple[float, float]:
    """ref pkg/scheduler/util/non_zero.go GetNonzeroRequests."""
    cpu = 0.0
    mem = 0.0
    for c in pod.spec.containers:
        cpu += (
            c.requests[RESOURCE_CPU].milli
            if RESOURCE_CPU in c.requests
            else DEFAULT_MILLI_CPU_REQUEST
        )
        mem += (
            float(c.requests[RESOURCE_MEMORY])
            if RESOURCE_MEMORY in c.requests
            else DEFAULT_MEMORY_REQUEST
        )
    return cpu, mem


from kubernetes_tpu.api.types import is_best_effort  # noqa: F401 (shared QoS rule)


def node_allocatable(node: Node) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, q in node.status.allocatable.items():
        out[k] = q.milli if k == RESOURCE_CPU else float(q)
    return out


def tolerations_tolerate(pod: Pod, taint: Taint) -> bool:
    return any(t.tolerates(taint) for t in pod.spec.tolerations)


def match_node_selector_term(pod_term, node: Node) -> bool:
    """ref v1helper.MatchNodeSelectorTerms: AND of matchExpressions (as label
    requirements) and matchFields (metadata.name); a term with an invalid
    label value never matches (NodeSelectorRequirementsAsSelector error)."""
    for expr in pod_term.match_expressions:
        if klabels.requirement_is_unbuildable(
            expr.key, expr.operator, expr.values
        ):
            return False
        req = klabels.Requirement(expr.key, expr.operator, tuple(expr.values))
        if not req.matches(node.labels):
            return False
    for expr in pod_term.match_fields:
        fields = {"metadata.name": node.name}
        req = klabels.Requirement(expr.key, expr.operator, tuple(expr.values))
        if not req.matches(fields):
            return False
    return bool(pod_term.match_expressions or pod_term.match_fields)


def _term_namespaces(term, pod: Pod):
    return set(term.namespaces) if term.namespaces else {pod.namespace}


def _term_matches_pod(term, src_pod: Pod, dst_pod: Pod) -> bool:
    """Does `term` (belonging to src_pod) select dst_pod?
    ref predicates.go podMatchesPodAffinityTerms."""
    if dst_pod.namespace not in _term_namespaces(term, src_pod):
        return False
    sel = klabels.selector_from_label_selector(term.label_selector)
    if sel is None:
        return False
    return sel.matches(dst_pod.labels)


def _topo_value(node: Optional[Node], key: str) -> Optional[str]:
    if node is None:
        return None
    return node.labels.get(key)


# ---------------------------------------------------------------- predicates


class CPUScheduler:
    """Golden scheduler over plain objects.  `nodes` is the cluster; `pods`
    are the scheduled/assumed pods (with spec.node_name set); `services` are
    (namespace, selector-dict) pairs for SelectorSpread."""

    def __init__(
        self,
        nodes: Sequence[Node],
        pods: Sequence[Pod] = (),
        services: Sequence[Tuple[str, Dict[str, str]]] = (),
        max_vols: Tuple[float, ...] = (39, 16, 1e9, 16, 1e9),
        pvs: Sequence = (),
        pvcs: Sequence = (),
        storage_classes: Sequence = (),
        service_affinity_labels: Sequence[str] = (),
    ):
        self.service_affinity_labels = list(service_affinity_labels)
        self.nodes = list(nodes)
        self.pods = list(pods)
        self.services = list(services)
        self.max_vols = max_vols
        self.pvs = {pv.name: pv for pv in pvs}
        self.pvcs = {(c.namespace, c.name): c for c in pvcs}
        self.storage_classes = {s.name: s for s in storage_classes}
        self.by_node: Dict[str, List[Pod]] = defaultdict(list)
        for p in self.pods:
            if p.spec.node_name:
                self.by_node[p.spec.node_name].append(p)
        self.node_by_name = {n.name: n for n in self.nodes}

    # ---- individual predicates (each returns True = fits) ----

    def pod_fits_resources(self, pod: Pod, node: Node) -> bool:
        alloc = node_allocatable(node)
        used: Dict[str, float] = defaultdict(float)
        for p in self.by_node[node.name]:
            for k, v in pod_requests(p).items():
                used[k] += v
        used[RESOURCE_PODS] += len(self.by_node[node.name])
        req = pod_requests(pod)
        req[RESOURCE_PODS] = 1
        for k, v in req.items():
            if v <= 0:
                continue
            if used.get(k, 0.0) + v > alloc.get(k, 0.0):
                return False
        return True

    def pod_fits_host(self, pod: Pod, node: Node) -> bool:
        return not pod.spec.node_name or pod.spec.node_name == node.name

    def pod_fits_host_ports(self, pod: Pod, node: Node) -> bool:
        want = [(p.protocol or "TCP", p.host_ip or "0.0.0.0", p.host_port) for p in pod.host_ports()]
        if not want:
            return True
        have = []
        for p in self.by_node[node.name]:
            for cp in p.host_ports():
                have.append((cp.protocol or "TCP", cp.host_ip or "0.0.0.0", cp.host_port))
        for proto, ip, port in want:
            for hproto, hip, hport in have:
                if proto == hproto and port == hport:
                    if ip == hip or ip == "0.0.0.0" or hip == "0.0.0.0":
                        return False
        return True

    def pod_match_node_selector(self, pod: Pod, node: Node) -> bool:
        for k, v in pod.spec.node_selector.items():
            if node.labels.get(k) != v:
                return False
        aff = pod.spec.affinity
        na = aff.node_affinity if aff else None
        if na and na.required is not None:
            if not any(match_node_selector_term(t, node) for t in na.required.terms):
                return False
        return True

    def check_service_affinity(self, pod: Pod, node: Node) -> bool:
        """ref predicates.go:993-1067 checkServiceAffinity: configured labels
        must be homogenous across a service's pods.  Pinned by the pod's own
        nodeSelector where present; otherwise backfilled from the node of the
        first same-namespace pod whose labels superset-match the pod's own
        (serviceAffinityMetadataProducer), excluding pods on the evaluated
        node (FilterOutPods)."""
        cfg = self.service_affinity_labels
        if not cfg:
            return True
        affinity = {
            k: pod.spec.node_selector[k]
            for k in cfg if k in pod.spec.node_selector
        }
        if len(cfg) > len(affinity):
            services = [
                (ns, sel) for ns, sel in self.services
                if ns == pod.namespace
                and klabels.selector_from_match_labels(sel).matches(pod.labels)
            ]
            if services:
                matches = [
                    p for p in self.pods
                    if p.namespace == pod.namespace
                    and all(
                        p.labels.get(k) == v for k, v in pod.labels.items()
                    )
                    and p.spec.node_name
                    and p.spec.node_name != node.name
                ]
                if matches:
                    src = self.node_by_name.get(matches[0].spec.node_name)
                    if src is not None:
                        for k in cfg:
                            if k not in affinity and k in src.labels:
                                affinity[k] = src.labels[k]
        return all(node.labels.get(k) == v for k, v in affinity.items())

    def pod_tolerates_node_taints(self, pod: Pod, node: Node, effects=(TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE)) -> bool:
        for t in node.spec.taints:
            if t.effect in effects and not tolerations_tolerate(pod, t):
                return False
        return True

    def check_node_unschedulable(self, pod: Pod, node: Node) -> bool:
        if not node.spec.unschedulable:
            return True
        return tolerations_tolerate(
            pod, Taint(key=TAINT_NODE_UNSCHEDULABLE, effect=TAINT_NO_SCHEDULE)
        )

    def check_node_condition(self, pod: Pod, node: Node) -> bool:
        c = node.status.conditions
        return not (
            c.get("Ready", "True") != "True"
            or c.get("OutOfDisk", "False") == "True"
            or c.get("NetworkUnavailable", "False") == "True"
        )

    def check_node_memory_pressure(self, pod: Pod, node: Node) -> bool:
        if node.status.conditions.get("MemoryPressure", "False") != "True":
            return True
        return not is_best_effort(pod)

    def check_node_disk_pressure(self, pod: Pod, node: Node) -> bool:
        return node.status.conditions.get("DiskPressure", "False") != "True"

    def check_node_pid_pressure(self, pod: Pod, node: Node) -> bool:
        return node.status.conditions.get("PIDPressure", "False") != "True"

    @staticmethod
    def _disk_vols(pod: Pod) -> Tuple[List[str], List[str]]:
        """(check tokens, advertise tokens) for NoDiskConflict
        (predicates.go isVolumeConflict :295-328): GCE-PD / RBD / ISCSI
        mounts that are BOTH read-only don't conflict, so an ro-allowance
        volume V advertises "V#any" (+"V#rw" when mounted read-write) and
        checks "V#any" when read-write but only "V#rw" when read-only;
        EBS conflicts regardless of access mode (one token both ways)."""
        check, adv = [], []

        def allow_ro(base: str, ro: bool) -> None:
            adv.append(base + "#any")
            if not ro:
                adv.append(base + "#rw")
            check.append(base + ("#rw" if ro else "#any"))

        for v in pod.spec.volumes:
            if "gcePersistentDisk" in v:
                g = v["gcePersistentDisk"]
                allow_ro("gce/" + g.get("pdName", ""), bool(g.get("readOnly")))
            elif "awsElasticBlockStore" in v:
                t = "ebs/" + v["awsElasticBlockStore"].get("volumeID", "")
                check.append(t)
                adv.append(t)
            elif "rbd" in v:
                # monitor OVERLAP + pool + image (haveOverlap, :264-272):
                # one token per monitor
                r = v["rbd"]
                # no monitors -> no tokens (haveOverlap([], x) is false)
                for mon in r.get("monitors", []) or ():
                    allow_ro(
                        "rbd/%s/%s/%s" % (mon, r.get("pool", "rbd"), r.get("image", "")),
                        bool(r.get("readOnly")),
                    )
            elif "iscsi" in v:
                # IQN alone (:253-262 — multi-path portals, same LUNs)
                r = v["iscsi"]
                allow_ro("iscsi/%s" % r.get("iqn", ""),
                         bool(r.get("readOnly")))
        return check, adv

    def no_disk_conflict(self, pod: Pod, node: Node) -> bool:
        mine = set(self._disk_vols(pod)[0])
        if not mine:
            return True
        for p in self.by_node[node.name]:
            if mine & set(self._disk_vols(p)[1]):
                return False
        return True

    def max_volume_counts(self, pod: Pod, node: Node) -> bool:
        return all(self.max_volume_counts_full(pod, node))

    # ---- volume predicates (object-level, independent of the encoder) ----

    def _pod_pvcs(self, pod: Pod):
        for v in pod.spec.volumes:
            claim = v.get("persistentVolumeClaim")
            if claim:
                yield self.pvcs.get((pod.namespace, claim.get("claimName", "")))

    @staticmethod
    def _pv_zone_ok(pv, node: Node) -> bool:
        for key in (
            "kubernetes.io/hostname",
            ZONE_KEY,
            REGION_KEY,
        ):
            val = pv.labels.get(key)
            if val is not None and node.labels.get(key) not in set(val.split("__")):
                return False
        return True

    @staticmethod
    def _pv_affinity_ok(pv, node: Node) -> bool:
        if pv.node_affinity is None:
            return True
        return any(
            match_node_selector_term(t, node) for t in pv.node_affinity.terms
        )

    def _pv_candidates(self, pvc):
        for pv in self.pvs.values():
            if pv.phase != "Available":
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if pvc.request is not None and pv.capacity is not None and pv.capacity < pvc.request:
                continue
            if pvc.access_modes and not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            yield pv

    def no_volume_zone_conflict(self, pod: Pod, node: Node) -> bool:
        """ref predicates.go:616-741."""
        for pvc in self._pod_pvcs(pod):
            if pvc is None or not pvc.volume_name:
                continue
            pv = self.pvs.get(pvc.volume_name)
            if pv is not None and not self._pv_zone_ok(pv, node):
                return False
        return True

    def check_volume_binding(self, pod: Pod, node: Node) -> bool:
        """ref predicates.go:1651-1700 via the volume binder semantics."""
        for pvc in self._pod_pvcs(pod):
            if pvc is None:
                return False  # ErrMissingPVC
            if pvc.volume_name:
                pv = self.pvs.get(pvc.volume_name)
                if pv is None:
                    return False
                if not self._pv_affinity_ok(pv, node):
                    return False
            else:
                ok = any(
                    self._pv_affinity_ok(pv, node) and self._pv_zone_ok(pv, node)
                    for pv in self._pv_candidates(pvc)
                )
                if not ok:
                    sc = self.storage_classes.get(pvc.storage_class)
                    if sc is None or not sc.provisioner:
                        return False
        return True

    def _vol_cols_count(self) -> int:
        """5 base columns + one per distinct CSI driver across the PV set
        (csi_volume_predicate.go accounts per driver)."""
        return NUM_VOL_TYPES + len(self._csi_driver_cols())

    def _csi_driver_cols(self) -> Dict[str, int]:
        drivers = sorted({
            pv.csi_driver for pv in self.pvs.values()
            if pv.source_kind == "csi" and pv.csi_driver
        })
        return {d: NUM_VOL_TYPES + i for i, d in enumerate(drivers)}

    def _vol_ids_with_pvc(self, pod: Pod, driver_cols=None) -> List[set]:
        """Per-column UNIQUE volume identities (direct + PVC-resolved) — the
        filterVolumes map keys (predicates.go:330-430); columns past the
        base types are per-CSI-driver.  driver_cols may be precomputed by
        the caller (one scan per verdict, not per pod)."""
        if driver_cols is None:
            driver_cols = self._csi_driver_cols()
        ids: List[set] = [
            set() for _ in range(NUM_VOL_TYPES + len(driver_cols))
        ]
        for v in pod.spec.volumes:
            if "awsElasticBlockStore" in v:
                ids[0].add("ebs/" + v["awsElasticBlockStore"].get("volumeID", ""))
            elif "gcePersistentDisk" in v:
                ids[1].add("gce/" + v["gcePersistentDisk"].get("pdName", ""))
            elif "azureDisk" in v:
                ids[3].add("azd/" + v["azureDisk"].get("diskName", ""))
            elif "cinder" in v:
                ids[4].add("cinder/" + v["cinder"].get("volumeID", ""))
        kind_col = {
            "awsElasticBlockStore": 0,
            "gcePersistentDisk": 1,
            "csi": 2,
            "azureDisk": 3,
            "cinder": 4,
        }
        prefix = ["ebs/", "gce/", "csi/", "azd/", "cinder/"]
        for pvc in self._pod_pvcs(pod):
            if pvc is not None and pvc.volume_name:
                pv = self.pvs.get(pvc.volume_name)
                if pv is not None and pv.source_kind in kind_col:
                    col = kind_col[pv.source_kind]
                    if pv.source_kind == "csi" and pv.csi_driver:
                        col = driver_cols[pv.csi_driver]
                    ident = getattr(pv, "source_id", "") or ("pvname/" + pv.name)
                    ids[col].add(
                        ("csi/" if col >= NUM_VOL_TYPES else prefix[col])
                        + ident
                    )
        return ids

    def max_volume_counts_full(self, pod: Pod, node: Node) -> List[bool]:
        """Per-filter-type verdicts [EBS, GCE, CSI, Azure, Cinder]: used is
        the node's DISTINCT attached set, and pod volumes already mounted
        there attach nothing new (the already-mounted subtraction,
        predicates.go:349-363)."""
        driver_cols = self._csi_driver_cols()
        VT = NUM_VOL_TYPES + len(driver_cols)
        pod_ids = self._vol_ids_with_pvc(pod, driver_cols)
        node_ids: List[set] = [set() for _ in range(VT)]
        for p in self.by_node[node.name]:
            for i, x in enumerate(self._vol_ids_with_pvc(p, driver_cols)):
                node_ids[i] |= x
        used = [float(len(x)) for x in node_ids]
        new = [float(len(pod_ids[i] - node_ids[i])) for i in range(VT)]
        # per-driver columns inherit the CSI default cap
        limits = list(self.max_vols) + [
            float(self.max_vols[VOL_CSI])
            for _ in range(VT - NUM_VOL_TYPES)
        ]
        limit_keys = {
            "attachable-volumes-aws-ebs": 0,
            "attachable-volumes-gce-pd": 1,
            "attachable-volumes-azure-disk": 3,
        }
        for k, q in node.status.allocatable.items():
            if k in limit_keys:
                limits[limit_keys[k]] = min(limits[limit_keys[k]], float(q))
            elif k.startswith("attachable-volumes-csi-"):
                # a per-driver cap applies ONLY to that driver's column;
                # a cap for a driver with no volumes constrains nothing
                driver = k[len("attachable-volumes-csi-"):]
                col = driver_cols.get(driver)
                if col is not None:
                    limits[col] = min(limits[col], float(q))
            elif k.startswith("attachable-volumes-") and "csi" in k:
                limits[2] = min(limits[2], float(q))
        return [
            not (new[i] > 0 and used[i] + new[i] > limits[i])
            for i in range(VT)
        ]

    def match_inter_pod_affinity(self, pod: Pod, node: Node) -> bool:
        """ref predicates.go InterPodAffinityMatches (:1196-1509)."""
        # 1. existing pods' required anti-affinity
        for other in self.pods:
            onode = self.node_by_name.get(other.spec.node_name)
            if onode is None:
                continue
            aff = other.spec.affinity
            if not (aff and aff.pod_anti_affinity):
                continue
            for term in aff.pod_anti_affinity.required:
                if not _term_matches_pod(term, other, pod):
                    continue
                tv = _topo_value(onode, term.topology_key)
                if tv is not None and _topo_value(node, term.topology_key) == tv:
                    return False
        aff = pod.spec.affinity
        if aff is None:
            return True
        # 2. own anti-affinity
        if aff.pod_anti_affinity:
            for term in aff.pod_anti_affinity.required:
                for other in self.pods:
                    onode = self.node_by_name.get(other.spec.node_name)
                    if onode is None:
                        continue
                    if not _term_matches_pod(term, pod, other):
                        continue
                    tv = _topo_value(onode, term.topology_key)
                    if tv is not None and _topo_value(node, term.topology_key) == tv:
                        return False
        # 3. own required affinity
        if aff.pod_affinity:
            for term in aff.pod_affinity.required:
                matches_any = False
                satisfied = False
                for other in self.pods:
                    onode = self.node_by_name.get(other.spec.node_name)
                    if onode is None or not _term_matches_pod(term, pod, other):
                        continue
                    matches_any = True
                    tv = _topo_value(onode, term.topology_key)
                    if tv is not None and _topo_value(node, term.topology_key) == tv:
                        satisfied = True
                        break
                if satisfied:
                    continue
                # first-pod bootstrap: no matching pod anywhere and the term
                # matches the incoming pod itself, on nodes having the key
                if (
                    not matches_any
                    and _term_matches_pod(term, pod, pod)
                    and _topo_value(node, term.topology_key) is not None
                ):
                    continue
                return False
        return True

    # ---- combined filter, reference ordering ----

    def predicates(self, pod: Pod, node: Node) -> Dict[str, bool]:
        res = self.pod_fits_resources(pod, node)
        host = self.pod_fits_host(pod, node)
        ports = self.pod_fits_host_ports(pod, node)
        sel = self.pod_match_node_selector(pod, node)
        vols = self.max_volume_counts_full(pod, node)
        return {
            "CheckNodeCondition": self.check_node_condition(pod, node),
            "CheckNodeUnschedulable": self.check_node_unschedulable(pod, node),
            "GeneralPredicates": res and host and ports and sel,
            "PodFitsHost": host,
            "PodFitsHostPorts": ports,
            "PodMatchNodeSelector": sel,
            "PodFitsResources": res,
            "NoDiskConflict": self.no_disk_conflict(pod, node),
            "PodToleratesNodeTaints": self.pod_tolerates_node_taints(pod, node),
            "PodToleratesNodeNoExecuteTaints": self.pod_tolerates_node_taints(
                pod, node, effects=(TAINT_NO_EXECUTE,)
            ),
            "CheckNodeLabelPresence": True,
            "CheckServiceAffinity": self.check_service_affinity(pod, node),
            "MaxEBSVolumeCount": vols[0],
            "MaxGCEPDVolumeCount": vols[1],
            # the named CSI predicate folds the generic column and every
            # per-driver column
            "MaxCSIVolumeCount": (
                vols[VOL_CSI] and all(vols[NUM_VOL_TYPES:])
            ),
            "MaxAzureDiskVolumeCount": vols[3],
            "MaxCinderVolumeCount": vols[4],
            "CheckVolumeBinding": self.check_volume_binding(pod, node),
            "NoVolumeZoneConflict": self.no_volume_zone_conflict(pod, node),
            "CheckNodeMemoryPressure": self.check_node_memory_pressure(pod, node),
            "CheckNodePIDPressure": self.check_node_pid_pressure(pod, node),
            "CheckNodeDiskPressure": self.check_node_disk_pressure(pod, node),
            "MatchInterPodAffinity": self.match_inter_pod_affinity(pod, node),
        }

    def fits(self, pod: Pod, node: Node) -> bool:
        return all(self.predicates(pod, node).values())

    # ------------------------------------------------------------ priorities

    def _used_nonzero(self, node: Node) -> Tuple[float, float]:
        cpu = mem = 0.0
        for p in self.by_node[node.name]:
            c, m = nonzero_requests(p)
            cpu += c
            mem += m
        return cpu, mem

    @staticmethod
    def _least_score(requested: float, capacity: float) -> int:
        if capacity == 0 or requested > capacity:
            return 0
        return int((capacity - requested) * MAX_PRIORITY // capacity)

    @staticmethod
    def _most_score(requested: float, capacity: float) -> int:
        if capacity == 0 or requested > capacity:
            return 0
        return int(requested * MAX_PRIORITY // capacity)

    def least_requested(self, pod: Pod, node: Node) -> int:
        pc, pm = nonzero_requests(pod)
        uc, um = self._used_nonzero(node)
        alloc = node_allocatable(node)
        return (
            self._least_score(pc + uc, alloc.get(RESOURCE_CPU, 0.0))
            + self._least_score(pm + um, alloc.get(RESOURCE_MEMORY, 0.0))
        ) // 2

    def most_requested(self, pod: Pod, node: Node) -> int:
        pc, pm = nonzero_requests(pod)
        uc, um = self._used_nonzero(node)
        alloc = node_allocatable(node)
        return (
            self._most_score(pc + uc, alloc.get(RESOURCE_CPU, 0.0))
            + self._most_score(pm + um, alloc.get(RESOURCE_MEMORY, 0.0))
        ) // 2

    def balanced_allocation(self, pod: Pod, node: Node) -> int:
        pc, pm = nonzero_requests(pod)
        uc, um = self._used_nonzero(node)
        alloc = node_allocatable(node)
        ccap = alloc.get(RESOURCE_CPU, 0.0)
        mcap = alloc.get(RESOURCE_MEMORY, 0.0)
        if ccap == 0 or mcap == 0:
            return 0
        cf = (pc + uc) / ccap
        mf = (pm + um) / mcap
        if cf >= 1 or mf >= 1:
            return 0
        return int((1 - abs(cf - mf)) * MAX_PRIORITY)

    def node_affinity_counts(self, pod: Pod) -> Dict[str, int]:
        counts = {}
        aff = pod.spec.affinity
        na = aff.node_affinity if aff else None
        for node in self.nodes:
            c = 0
            if na:
                for pt in na.preferred:
                    term = pt.preference
                    # an unbuildable requirement voids the term (device
                    # encodes it as match-nothing; the Go map function
                    # would error the whole priority)
                    ok = all(
                        not klabels.requirement_is_unbuildable(
                            e.key, e.operator, e.values
                        )
                        and klabels.Requirement(
                            e.key, e.operator, tuple(e.values)
                        ).matches(node.labels)
                        for e in term.match_expressions
                    ) and bool(term.match_expressions)
                    if ok:
                        c += pt.weight
            counts[node.name] = c
        return counts

    def taint_tol_counts(self, pod: Pod) -> Dict[str, int]:
        counts = {}
        for node in self.nodes:
            c = 0
            for t in node.spec.taints:
                if t.effect == TAINT_PREFER_NO_SCHEDULE and not tolerations_tolerate(pod, t):
                    c += 1
            counts[node.name] = c
        return counts

    @staticmethod
    def _normalize(counts: Dict[str, int], reverse: bool) -> Dict[str, int]:
        maxc = max(counts.values()) if counts else 0
        if maxc == 0:
            return {k: (MAX_PRIORITY if reverse else 0) for k in counts}
        out = {}
        for k, v in counts.items():
            s = MAX_PRIORITY * v // maxc
            out[k] = MAX_PRIORITY - s if reverse else s
        return out

    def selector_spread(self, pod: Pod) -> Dict[str, int]:
        """ref priorities/selector_spreading.go CalculateSpreadPriorityMap/Reduce."""
        selectors = [
            klabels.selector_from_match_labels(sel)
            for ns, sel in self.services
            if ns == pod.namespace and klabels.selector_from_match_labels(sel).matches(pod.labels)
        ]
        counts: Dict[str, int] = {}
        for node in self.nodes:
            c = 0
            if selectors:
                for p in self.by_node[node.name]:
                    if p.namespace != pod.namespace:
                        continue
                    # countMatchingPods (selector_spreading.go:165-187): the
                    # existing pod counts once iff it matches ALL selectors
                    if all(sel.matches(p.labels) for sel in selectors):
                        c += 1
            counts[node.name] = c
        max_node = max(counts.values()) if counts else 0
        zone_counts: Dict[str, int] = defaultdict(int)
        have_zones = False
        for node in self.nodes:
            z = get_zone_key(node)
            if z is not None:
                have_zones = True
                zone_counts[z] += counts[node.name]
        max_zone = max(zone_counts.values()) if zone_counts else 0
        out = {}
        for node in self.nodes:
            if max_node > 0:
                f = MAX_PRIORITY * (max_node - counts[node.name]) / max_node
            else:
                f = MAX_PRIORITY
            z = get_zone_key(node)
            if have_zones and z is not None:
                if max_zone > 0:
                    zs = MAX_PRIORITY * (max_zone - zone_counts[z]) / max_zone
                else:
                    zs = MAX_PRIORITY
                f = (1 - ZONE_WEIGHTING) * f + ZONE_WEIGHTING * zs
            out[node.name] = int(f)
        return out

    @staticmethod
    def _normalized_image(name: str) -> str:
        """image_locality.go:99-109 normalizedImageName."""
        if name.rfind(":") <= name.rfind("/"):
            return name + ":latest"
        return name

    def image_locality(self, pod: Pod) -> Dict[str, int]:
        mb = 1024 * 1024
        min_t, max_t = 23 * mb, 1000 * mb
        total = max(len(self.nodes), 1)
        num_nodes: Dict[str, int] = defaultdict(int)
        for node in self.nodes:
            for img in node.status.images:
                for nm in img.names:  # every name keys the same state
                    num_nodes[nm] += 1
        out = {}
        for node in self.nodes:
            sizes = {}
            for img in node.status.images:
                for nm in img.names:
                    sizes[nm] = img.size_bytes
            s = 0
            for c in pod.spec.containers:
                key = self._normalized_image(c.image)
                if key in sizes:
                    s += int(sizes[key] * (num_nodes[key] / total))
            s = min(max(s, min_t), max_t)
            out[node.name] = int(MAX_PRIORITY * (s - min_t) // (max_t - min_t))
        return out

    def node_prefer_avoid(self, pod: Pod) -> Dict[str, int]:
        out = {}
        owner = pod.metadata.owner_uid
        applies = pod.metadata.owner_kind in ("ReplicationController", "ReplicaSet")
        for node in self.nodes:
            score = MAX_PRIORITY
            ann = node.metadata.annotations.get(
                "scheduler.alpha.kubernetes.io/preferAvoidPods"
            )
            if ann and applies and owner:
                try:
                    avoid = json.loads(ann)
                    for e in avoid.get("preferAvoidPods", []):
                        uid = e.get("podSignature", {}).get("podController", {}).get("uid", "")
                        if uid == owner:
                            score = 0
                except ValueError:
                    pass
            out[node.name] = score
        return out

    def inter_pod_affinity_score(self, pod: Pod, hard_weight: float = 1.0) -> Dict[str, int]:
        """ref priorities/interpod_affinity.go CalculateInterPodAffinityPriority."""
        sums: Dict[str, float] = {n.name: 0.0 for n in self.nodes}

        def bump(topo_key: str, anchor_node: Node, w: float):
            tv = _topo_value(anchor_node, topo_key)
            if tv is None:
                return
            for node in self.nodes:
                if _topo_value(node, topo_key) == tv:
                    sums[node.name] += w

        aff = pod.spec.affinity
        for other in self.pods:
            onode = self.node_by_name.get(other.spec.node_name)
            if onode is None:
                continue
            # incoming pod's preferred terms matching the existing pod
            if aff and aff.pod_affinity:
                for wt in aff.pod_affinity.preferred:
                    if _term_matches_pod(wt.term, pod, other):
                        bump(wt.term.topology_key, onode, float(wt.weight))
            if aff and aff.pod_anti_affinity:
                for wt in aff.pod_anti_affinity.preferred:
                    if _term_matches_pod(wt.term, pod, other):
                        bump(wt.term.topology_key, onode, -float(wt.weight))
            oaff = other.spec.affinity
            # existing pods' preferred terms matching the incoming pod
            if oaff and oaff.pod_affinity:
                for wt in oaff.pod_affinity.preferred:
                    if _term_matches_pod(wt.term, other, pod):
                        bump(wt.term.topology_key, onode, float(wt.weight))
                if hard_weight > 0:
                    for term in oaff.pod_affinity.required:
                        if _term_matches_pod(term, other, pod):
                            bump(term.topology_key, onode, hard_weight)
            if oaff and oaff.pod_anti_affinity:
                for wt in oaff.pod_anti_affinity.preferred:
                    if _term_matches_pod(wt.term, other, pod):
                        bump(wt.term.topology_key, onode, -float(wt.weight))
        mx = max(sums.values()) if sums else 0.0
        mn = min(sums.values()) if sums else 0.0
        out = {}
        for name, s in sums.items():
            if mx - mn > 0:
                out[name] = int(MAX_PRIORITY * (s - mn) / (mx - mn))
            else:
                out[name] = 0
        return out

    def node_label_priority(self, pod: Pod, label_prefs=()) -> Dict[str, float]:
        out = {}
        for node in self.nodes:
            s = 0.0
            for key, presence, weight in label_prefs:
                present = key in node.labels
                s += weight * (MAX_PRIORITY if present == bool(presence) else 0)
            out[node.name] = s
        return out

    def requested_to_capacity_ratio(
        self, pod: Pod, shape=((0.0, 10.0), (100.0, 0.0))
    ) -> Dict[str, int]:
        """priorities/requested_to_capacity_ratio.go piecewise-linear curve."""

        def curve(u: float) -> float:
            pts = list(shape)
            if u <= pts[0][0]:
                return pts[0][1]
            for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
                if u <= x1:
                    return y0 + (y1 - y0) * (u - x0) / (x1 - x0)
            return pts[-1][1]

        pc, pm = nonzero_requests(pod)
        out = {}
        for node in self.nodes:
            uc, um = self._used_nonzero(node)
            alloc = node_allocatable(node)
            ccap = alloc.get(RESOURCE_CPU, 0.0)
            mcap = alloc.get(RESOURCE_MEMORY, 0.0)
            cu = (pc + uc) * 100.0 / ccap if ccap > 0 else 100.0
            mu = (pm + um) * 100.0 / mcap if mcap > 0 else 100.0
            out[node.name] = int((curve(cu) + curve(mu)) // 2)
        return out

    def resource_limits(self, pod: Pod) -> Dict[str, int]:
        """priorities/resource_limits.go (feature-gated)."""
        lim_cpu = lim_mem = 0.0
        for c in pod.spec.containers:
            if RESOURCE_CPU in c.limits:
                lim_cpu += c.limits[RESOURCE_CPU].milli
            if RESOURCE_MEMORY in c.limits:
                lim_mem += float(c.limits[RESOURCE_MEMORY])
        out = {}
        for node in self.nodes:
            alloc = node_allocatable(node)
            ok = (lim_cpu == 0 or alloc.get(RESOURCE_CPU, 0.0) >= lim_cpu) and (
                lim_mem == 0 or alloc.get(RESOURCE_MEMORY, 0.0) >= lim_mem
            )
            out[node.name] = 1 if ok and (lim_cpu > 0 or lim_mem > 0) else 0
        return out

    def priorities(
        self, pod: Pod, label_prefs=(), rtc_shape=((0.0, 10.0), (100.0, 0.0))
    ) -> Dict[str, Dict[str, int]]:
        na = self._normalize(self.node_affinity_counts(pod), reverse=False)
        tt = self._normalize(self.taint_tol_counts(pod), reverse=True)
        out = {
            "SelectorSpreadPriority": self.selector_spread(pod),
            "InterPodAffinityPriority": self.inter_pod_affinity_score(pod),
            "LeastRequestedPriority": {
                n.name: self.least_requested(pod, n) for n in self.nodes
            },
            "BalancedResourceAllocation": {
                n.name: self.balanced_allocation(pod, n) for n in self.nodes
            },
            "NodePreferAvoidPodsPriority": self.node_prefer_avoid(pod),
            "NodeAffinityPriority": na,
            "TaintTolerationPriority": tt,
            "ImageLocalityPriority": self.image_locality(pod),
            "MostRequestedPriority": {
                n.name: self.most_requested(pod, n) for n in self.nodes
            },
            "NodeLabelPriority": self.node_label_priority(pod, label_prefs),
            "RequestedToCapacityRatioPriority": self.requested_to_capacity_ratio(
                pod, rtc_shape
            ),
            "ResourceLimitsPriority": self.resource_limits(pod),
        }
        return out

    def total_scores(self, pod: Pod, weights: Optional[Dict[str, float]] = None) -> Dict[str, float]:
        from kubernetes_tpu.codec.schema import DEFAULT_PRIORITY_WEIGHTS, PRIORITY_ORDER

        if weights is None:
            weights = dict(zip(PRIORITY_ORDER, DEFAULT_PRIORITY_WEIGHTS))
        per = self.priorities(pod)
        totals: Dict[str, float] = defaultdict(float)
        for pname, scores in per.items():
            for node, s in scores.items():
                totals[node] += s * weights.get(pname, 1.0)
        return dict(totals)

    # ------------------------------------------------------------ preemption

    def _clone_without(self, removed) -> "CPUScheduler":
        """A what-if copy with a victim set removed (nodeInfoCopy +
        meta.RemovePod analog: the clone re-derives ALL state, so ports,
        disk volumes, volume counts, and affinity pair maps reflect the
        removal)."""
        return CPUScheduler(
            self.nodes,
            [p for p in self.pods if (p.namespace, p.name) not in removed],
            self.services,
            self.max_vols,
            list(self.pvs.values()),
            list(self.pvcs.values()),
            list(self.storage_classes.values()),
            service_affinity_labels=self.service_affinity_labels,
        )

    def _fits_minus(self, pod: Pod, node: Node, removed) -> bool:
        """podFitsOnNode with a victim set removed: the full predicate set
        (selectVictimsOnNode re-runs every predicate, not just resources)."""
        return self._clone_without(removed).fits(pod, node)

    @staticmethod
    def _pdb_violating(pod: Pod, pdbs) -> bool:
        """filterPodsWithPDBViolation: evicting `pod` violates a PDB if any
        matching PDB has disruptionsAllowed <= 0."""
        return any(pdb.matches(pod) and pdb.disruptions_allowed <= 0 for pdb in pdbs)

    def select_victims_on_node(self, pod: Pod, node: Node, pdbs=()):
        """selectVictimsOnNode (generic_scheduler.go:1054-1128): evict all
        lower-priority pods, then reprieve — PDB-violating victims first,
        then non-violating, highest priority first (ties: earliest start) —
        while the preemptor still fits.  Returns (victim key set,
        num PDB violations) or (None, 0) if impossible."""
        potential = [
            p
            for p in self.by_node[node.name]
            if p.spec.priority < pod.spec.priority
        ]
        removed = {(p.namespace, p.name) for p in potential}
        if not self._fits_minus(pod, node, removed):
            return None, 0
        # MoreImportantPod order: priority desc, then earlier start
        order = sorted(
            potential, key=lambda q: (-q.spec.priority, q.status.start_time)
        )
        violating = [p for p in order if self._pdb_violating(p, pdbs)]
        non_violating = [p for p in order if not self._pdb_violating(p, pdbs)]
        n_viol = 0
        for group, count_violations in ((violating, True), (non_violating, False)):
            for p in group:
                key = (p.namespace, p.name)
                removed.discard(key)
                if not self._fits_minus(pod, node, removed):
                    removed.add(key)
                    if count_violations:
                        n_viol += 1
        return removed, n_viol

    # ErrPodAffinityRulesNotMatch analog: required affinity rules alone
    def _affinity_rules_ok(self, pod: Pod, node: Node) -> bool:
        aff = pod.spec.affinity
        pa = aff.pod_affinity if aff else None
        if pa is None or not pa.required:
            return True
        for term in pa.required:
            matches_somewhere = False
            domain_ok = False
            tval = _topo_value(node, term.topology_key)
            for p in self.pods:
                if not p.spec.node_name:
                    continue
                if _term_matches_pod(term, pod, p):
                    matches_somewhere = True
                    pnode = self.node_by_name.get(p.spec.node_name)
                    if (
                        tval is not None
                        and _topo_value(pnode, term.topology_key) == tval
                    ):
                        domain_ok = True
            if not domain_ok:
                # first-pod bootstrap: no matching pod anywhere and the term
                # matches the incoming pod itself on a node carrying the key
                if not (
                    not matches_somewhere
                    and _term_matches_pod(term, pod, pod)
                    and tval is not None
                ):
                    return False
        return True

    UNRESOLVABLE = (
        "CheckNodeCondition", "CheckNodeUnschedulable", "PodFitsHost",
        "PodMatchNodeSelector", "PodToleratesNodeTaints",
        "PodToleratesNodeNoExecuteTaints", "CheckNodeLabelPresence",
        "CheckNodeMemoryPressure", "CheckNodePIDPressure",
        "CheckNodeDiskPressure", "NoVolumeZoneConflict", "CheckVolumeBinding",
    )

    def preempt(self, pod: Pod, pdbs=()):
        """Preempt (:310-369) + pickOneNodeForPreemption criteria 1-6
        (generic_scheduler.go:837-962)."""
        best = None
        for i, node in enumerate(self.nodes):
            preds = self.predicates(pod, node)
            if all(preds.values()):
                continue
            # nodesWherePreemptionMightHelp: no unresolvable failure
            if not all(preds[p] for p in self.UNRESOLVABLE if p in preds):
                continue
            if not self._affinity_rules_ok(pod, node):
                continue
            victims, n_viol = self.select_victims_on_node(pod, node, pdbs)
            if victims is None:
                continue
            vic_pods = [
                p for p in self.by_node[node.name] if (p.namespace, p.name) in victims
            ]
            max_p = max((p.spec.priority for p in vic_pods), default=-(2**31))
            sum_p = sum(p.spec.priority + 2**31 for p in vic_pods)
            top = [p for p in vic_pods if p.spec.priority == max_p]
            earliest_top = min(
                (p.status.start_time for p in top), default=float("inf")
            )
            # criteria: min violations, min max prio, min sum, min count,
            # LATEST earliest-start (negate), first index
            key = (n_viol, max_p, sum_p, len(vic_pods), -earliest_top, i)
            if best is None or key < best[0]:
                best = (key, node.name, victims, n_viol)
        if best is None:
            return None, set()
        return best[1], best[2]

    # ------------------------------------------------------------- schedule

    def schedule(self, pod: Pod, last_index: int = 0) -> Tuple[Optional[str], int]:
        """Full schedule cycle: filter + score + selectHost round-robin
        (generic_scheduler.go:184-296).  Returns (node name or None, ties)."""
        feasible = [n for n in self.nodes if self.fits(pod, n)]
        if not feasible:
            return None, 0
        totals = self.total_scores(pod)
        best = max(totals[n.name] for n in feasible)
        ties = [n.name for n in feasible if totals[n.name] == best]
        return ties[last_index % len(ties)], len(ties)


def run_predicates(pod: Pod, nodes, pods=(), services=()) -> Dict[str, Dict[str, bool]]:
    s = CPUScheduler(nodes, pods, services)
    return {n.name: s.predicates(pod, n) for n in nodes}


def run_priorities(pod: Pod, nodes, pods=(), services=()) -> Dict[str, Dict[str, int]]:
    return CPUScheduler(nodes, pods, services).priorities(pod)
