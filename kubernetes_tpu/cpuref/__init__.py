"""Pure-Python/numpy golden implementation of the scheduling pipeline.

This is the analog of the reference's table-driven predicate/priority unit
tests (e.g. algorithm/predicates/predicates_test.go): an independent,
object-level implementation of the same semantics, used to differential-test
the TPU kernels on randomized cluster states.  It is also the CPU fallback
path (the north star's "graceful fallback").
"""

from kubernetes_tpu.cpuref.adapter import CpuEngineAdapter  # noqa: F401
from kubernetes_tpu.cpuref.reference import (
    CPUScheduler,
    run_predicates,
    run_priorities,
)
