"""Filter predicates as batched tensor kernels.

Each predicate mirrors one reference FitPredicate
(pkg/scheduler/algorithm/predicates/predicates.go) but evaluates the whole
pods x nodes grid at once: `(ClusterTensors, PodBatch) -> bool[B, N]`.
The combined `filter_batch` stacks all predicates in the reference's mandatory
ordering (predicates.go:142-151) so the first-failing predicate per (pod,
node) can be attributed for FitError parity, even though — unlike the
reference's short-circuiting per-node loop (generic_scheduler.go:598-664) —
everything is computed in one launch.

Shapes: B pods, N nodes, and smallish padded inner dims; everything stays in
integer/bool/f32 tensor math, XLA fuses the lot into a handful of kernels.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.codec.schema import (
    ClusterTensors,
    FilterConfig,
    FIELD_NODE_NAME_ID,
    NUM_PREDICATES,
    PAD,
    PodBatch,
    NUM_VOL_TYPES,
    PRED_INDEX,
    RES_PODS,
    VOL_CSI,
)

# taint effect codes
_NO_SCHEDULE, _PREFER_NO_SCHEDULE, _NO_EXECUTE = 0, 1, 2
# toleration ops
_TOL_EQUAL, _TOL_EXISTS = 0, 1
# selector ops
_IN, _NOT_IN, _EXISTS, _DOES_NOT_EXIST, _GT, _LT = 0, 1, 2, 3, 4, 5


def node_label_value(cluster: ClusterTensors, keys):
    """Look up node label values for interned keys.

    keys: i32[...]; returns (val i32[..., N], num f32[..., N]) with PAD/nan for
    absent keys.  The pseudo-key FIELD_NODE_NAME_ID resolves to the node name
    (NodeSelectorTerm.matchFields support).
    """
    lk = cluster.label_keys            # [N, L]
    lv = cluster.label_vals
    ln = cluster.label_nums
    k = keys[..., None, None]          # [..., 1, 1]
    hit = lk == k                      # [..., N, L]
    val = jnp.max(jnp.where(hit, lv, PAD), axis=-1)
    num = jnp.max(jnp.where(hit & ~jnp.isnan(ln), ln, -jnp.inf), axis=-1)
    num = jnp.where(jnp.isfinite(num), num, jnp.nan)
    is_field = keys[..., None] == FIELD_NODE_NAME_ID
    val = jnp.where(is_field, cluster.node_name_id[None], val)
    return val, num


def _eval_exprs(cluster, key, op, vals, nval, num, valid):
    """Evaluate selector expressions against all nodes.

    key/op/num: i32/f32[..., E]; vals i32[..., E, V]; returns match
    bool[..., E, N] (invalid expressions evaluate True so they AND away).
    ref v1helper.MatchNodeSelectorTerms / labels.Requirement.Matches.
    """
    node_val, node_num = node_label_value(cluster, key)   # [..., E, N]
    has = node_val != PAD
    V = vals.shape[-1]
    slot = jnp.arange(V)
    vvalid = slot < nval[..., None]                        # [..., E, V]
    eq = (node_val[..., None, :] == vals[..., :, None]) & vvalid[..., None]
    in_set = jnp.any(eq, axis=-2)                          # [..., E, N]
    gt = ~jnp.isnan(num[..., None]) & ~jnp.isnan(node_num) & (node_num > num[..., None])
    lt = ~jnp.isnan(num[..., None]) & ~jnp.isnan(node_num) & (node_num < num[..., None])
    opx = op[..., None]
    match = jnp.where(
        opx == _IN, has & in_set,
        jnp.where(
            opx == _NOT_IN, ~(has & in_set),
            jnp.where(
                opx == _EXISTS, has,
                jnp.where(
                    opx == _DOES_NOT_EXIST, ~has,
                    jnp.where(opx == _GT, has & gt, has & lt),
                ),
            ),
        ),
    )
    return match | ~valid[..., None]


# --------------------------------------------------------------- predicates


def pod_fits_resources(cluster: ClusterTensors, pods: PodBatch):
    """PodFitsResources (predicates.go:764-857): for every resource the pod
    requests, requested + podRequest <= allocatable; the pod-count column
    encodes allowedPodNumber."""
    req = pods.req[:, None, :]                  # [B, 1, R]
    used = cluster.requested[None]              # [1, N, R]
    alloc = cluster.allocatable[None]
    over = (req > 0) & (used + req > alloc)
    return ~jnp.any(over, axis=-1)


def pod_fits_host(cluster: ClusterTensors, pods: PodBatch):
    """PodFitsHost (predicates.go:901-921): spec.nodeName pinning."""
    want = pods.node_name_req[:, None]
    return (want == PAD) | (want == cluster.node_name_id[None])


def pod_fits_host_ports(cluster: ClusterTensors, pods: PodBatch):
    """PodFitsHostPorts (predicates.go:1069-1110) with the hostIP/wildcard
    conflict rule of nodeinfo/host_ports.go CheckConflict."""
    pp = pods.port_pp[:, :, None, None]         # [B, Q, 1, 1]
    ip = pods.port_ip[:, :, None, None]
    pv = pods.port_valid[:, :, None, None]
    npp = cluster.port_pp[None, None]           # [1, 1, N, P]
    nip = cluster.port_ip[None, None]
    nused = cluster.port_used[None, None]
    same = pp == npp
    ip_clash = (ip == nip) | (ip == 0) | (nip == 0)
    conflict = pv & nused & same & ip_clash
    return ~jnp.any(conflict, axis=(1, 3))


def pod_match_node_selector(cluster: ClusterTensors, pods: PodBatch):
    """PodMatchNodeSelector (predicates.go:889-899): spec.nodeSelector AND
    nodeAffinity.requiredDuringScheduling (OR of terms)."""
    # plain nodeSelector map: every entry key==value
    val, _ = node_label_value(cluster, pods.ns_keys)       # [B, NS, N]
    ok = (val == pods.ns_vals[..., None]) | ~pods.ns_valid[..., None]
    sel_ok = jnp.all(ok, axis=1)                            # [B, N]
    if pods.expr_key.shape[1] == 0:
        # affinity-lean batch (no pod carries required nodeAffinity): the
        # encoder emitted zero-width term tensors, skip the expr grid
        return sel_ok
    # required node affinity
    m = _eval_exprs(
        cluster,
        pods.expr_key,
        pods.expr_op,
        pods.expr_vals,
        pods.expr_nval,
        pods.expr_num,
        pods.expr_valid,
    )                                                       # [B, S, E, N]
    # a term with ZERO requirements matches nothing (v1helper semantics:
    # nodeSelectorTerms entries with empty matchExpressions+matchFields are
    # skipped, i.e. never satisfy the OR)
    term_nonempty = jnp.any(pods.expr_valid, axis=2)        # [B, S]
    term_ok = (
        jnp.all(m, axis=2)
        & pods.term_valid[..., None]
        & term_nonempty[..., None]
    )
    any_term = jnp.any(term_ok, axis=1)                     # [B, N]
    aff_ok = jnp.where(pods.has_req_affinity[:, None], any_term, True)
    return sel_ok & aff_ok


def _tolerates(pods: PodBatch, taint_key, taint_val, taint_effect, considered):
    """bool[B, N]: every considered taint is tolerated by some toleration.
    ref v1/toleration.go ToleratesTaint + TolerationsTolerateTaintsWithFilter."""
    tk = pods.tol_key[:, :, None, None]         # [B, TT, 1, 1]
    to = pods.tol_op[:, :, None, None]
    tv = pods.tol_val[:, :, None, None]
    te = pods.tol_effect[:, :, None, None]
    tvalid = pods.tol_valid[:, :, None, None]
    ntk = taint_key[None, None]                 # [1, 1, N, T]
    ntv = taint_val[None, None]
    nte = taint_effect[None, None]
    eff_ok = (te == PAD) | (te == nte)
    key_ok = (tk == 0) | (tk == ntk)
    op_ok = (to == _TOL_EXISTS) | (tv == ntv)
    tol = tvalid & eff_ok & key_ok & op_ok      # [B, TT, N, T]
    tolerated = jnp.any(tol, axis=1)            # [B, N, T]
    return ~jnp.any(considered[None] & ~tolerated, axis=-1)


def pod_tolerates_node_taints(cluster: ClusterTensors, pods: PodBatch):
    """PodToleratesNodeTaints (predicates.go:1531-1540): NoSchedule+NoExecute."""
    eff = cluster.taint_effect
    considered = (eff == _NO_SCHEDULE) | (eff == _NO_EXECUTE)
    return _tolerates(pods, cluster.taint_key, cluster.taint_val, eff, considered)


def pod_tolerates_no_execute_taints(cluster: ClusterTensors, pods: PodBatch):
    """PodToleratesNodeNoExecuteTaints (predicates.go:1543-1547)."""
    eff = cluster.taint_effect
    return _tolerates(pods, cluster.taint_key, cluster.taint_val, eff, eff == _NO_EXECUTE)


def check_node_unschedulable(cluster: ClusterTensors, pods: PodBatch, unsched_taint_key):
    """CheckNodeUnschedulablePredicate (predicates.go:1511-1529): fails on
    .spec.unschedulable unless the pod tolerates the unschedulable taint."""
    tk = pods.tol_key
    te = pods.tol_effect
    to = pods.tol_op
    tv = pods.tol_val
    tol = (
        pods.tol_valid
        & ((te == PAD) | (te == _NO_SCHEDULE))
        & ((tk == 0) | (tk == unsched_taint_key))
        & ((to == _TOL_EXISTS) | (tv == 0))
    )
    tolerates = jnp.any(tol, axis=1)            # [B]
    return ~(cluster.unschedulable[None] & ~tolerates[:, None])


def check_node_condition(cluster: ClusterTensors, pods: PodBatch):
    """CheckNodeConditionPredicate (predicates.go:1610-1649)."""
    return ~cluster.not_ready[None] | jnp.zeros((pods.n_pods, 1), bool)


def check_node_memory_pressure(cluster: ClusterTensors, pods: PodBatch):
    """CheckNodeMemoryPressurePredicate (predicates.go:1568-1588): only
    BestEffort pods are repelled."""
    return ~(pods.best_effort[:, None] & cluster.mem_pressure[None])


def check_node_disk_pressure(cluster: ClusterTensors, pods: PodBatch):
    return ~cluster.disk_pressure[None] | jnp.zeros((pods.n_pods, 1), bool)


def check_node_pid_pressure(cluster: ClusterTensors, pods: PodBatch):
    return ~cluster.pid_pressure[None] | jnp.zeros((pods.n_pods, 1), bool)


def no_disk_conflict(cluster: ClusterTensors, pods: PodBatch):
    """NoDiskConflict (predicates.go:288-328): exclusive GCE-PD/EBS/RBD/ISCSI
    volume ids must not collide with volumes in use on the node."""
    pv = pods.disk_vol_ids[:, :, None, None]    # [B, DV, 1, 1]
    nv = cluster.disk_vol_ids[None, None]       # [1, 1, N, DVN]
    clash = (pv != PAD) & (pv == nv)
    return ~jnp.any(clash, axis=(1, 3))


def max_volume_counts(cluster: ClusterTensors, pods: PodBatch, max_vols):
    """MaxEBS/GCE/CSI/Azure/Cinder volume-count filters (predicates.go:330-614)
    -> bool[B, 5, N], one slice per filter type.  Counting dedupes by volume
    identity on BOTH sides: `used` is the node's distinct attached set and a
    pod volume already mounted there attaches nothing new (the
    already-mounted subtraction, predicate lines 355-361).  Per-node
    attachable limits (AttachVolumeLimit allocatable keys) override the
    static defaults."""
    new = pods.new_vol_counts[:, :, None]       # [B, VT, 1]
    if pods.vol_overlap.shape[-1] == cluster.n_nodes:
        new = jnp.maximum(new - pods.vol_overlap, 0.0)
    used = cluster.vol_counts.T[None]           # [1, VT, N]
    base = jnp.asarray(max_vols, jnp.float32)
    VT = new.shape[1]
    if VT > base.shape[0]:
        # columns past the base types are per-CSI-driver: each inherits
        # the CSI default limit (csi_volume_predicate.go per-driver caps
        # come from node allocatable; the static default is shared)
        base = jnp.concatenate([
            base,
            jnp.full((VT - base.shape[0],), float(max_vols[VOL_CSI]),
                     jnp.float32),
        ])
    default = base[None, :, None]
    node_lim = cluster.vol_limits.T[None]       # [1, VT, N] (inf = unset)
    limit = jnp.minimum(default, node_lim)
    return ~((new > 0) & (used + new > limit))


def _is_lean(pair_tensor, cluster: ClusterTensors) -> bool:
    """True when the encoder emitted a width-1 placeholder instead of the
    TP-wide pair tensor: the batch provably carries none of these terms, so
    the kernel is skipped (shape is static at trace time — two compiled
    variants, lean and full)."""
    return pair_tensor.shape[-1] != cluster.topo_pairs.shape[-1]


def _pair_terms_ok(cluster: ClusterTensors, term_pairs, term_valid):
    """AND over terms of 'node belongs to one of the term's allowed pairs'.
    term_pairs bool[B, K, TP], term_valid bool[B, K] -> bool[B, N]."""
    if _is_lean(term_pairs, cluster):
        B, N = term_pairs.shape[0], cluster.n_nodes
        return jnp.ones((B, N), bool)
    topo = cluster.topo_pairs.astype(jnp.float32)            # [N, TP]
    hit = jnp.einsum("btp,np->btn", term_pairs.astype(jnp.float32), topo) > 0
    return jnp.all(hit | ~term_valid[..., None], axis=1)


def no_volume_zone_conflict(cluster: ClusterTensors, pods: PodBatch):
    """NoVolumeZoneConflict (predicates.go:616-741): the node must carry the
    zone/region labels of every bound PV the pod claims (precomputed as
    allowed hostname-pair sets by the encoder)."""
    return _pair_terms_ok(cluster, pods.vol_zone_pairs, pods.vol_zone_valid)


def check_volume_binding(cluster: ClusterTensors, pods: PodBatch):
    """CheckVolumeBinding (predicates.go:1651-1700): bound PVs' node affinity
    must match; unbound claims need a reachable candidate PV (or deferred
    provisioning); a claim with no PVC/PV at all fails everywhere."""
    ok = _pair_terms_ok(cluster, pods.vol_bind_pairs, pods.vol_bind_valid)
    return ok & ~pods.vol_fail_all[:, None]


def _node_label_value(cluster: ClusterTensors, key_id: int):
    """i32[N]: the node's value id for label `key_id` (PAD when absent)."""
    hit = cluster.label_keys == key_id                       # [N, L]
    val = jnp.max(jnp.where(hit, cluster.label_vals, PAD), axis=1)
    return jnp.where(jnp.any(hit, axis=1), val, PAD)


def check_service_affinity(cluster: ClusterTensors, pods: PodBatch,
                           cfg: FilterConfig):
    """CheckServiceAffinity (predicates.go:993-1067): for each configured
    label L the pod must land on a node whose L-value matches either (a) the
    pod's own nodeSelector pin, or (b) the L-value of the node hosting the
    first same-service pod — excluding pods on the evaluated node itself
    (FilterOutPods), which reduces to "first candidate node d0 unless d0 IS
    the evaluated node, then d1" (encoder svc_aff_d0/d1).  Unpinned labels
    with no candidate (or a candidate node lacking L) constrain nothing
    (AddUnsetLabelsToMap adds only present labels)."""
    B, N = pods.n_pods, cluster.n_nodes
    ok = jnp.ones((B, N), bool)
    if not cfg.service_affinity_labels:
        return ok
    narange = jnp.arange(N, dtype=jnp.int32)[None]           # [1, N]
    d0 = pods.svc_aff_d0[:, None]
    d1 = pods.svc_aff_d1[:, None]
    src = jnp.where(d0 == narange, d1, d0)                   # [B, N]
    has_src = src >= 0
    src_c = jnp.clip(src, 0)
    for j, key_id in enumerate(cfg.service_affinity_labels):
        vals = _node_label_value(cluster, key_id)            # [N]
        fixed = pods.svc_aff_fixed[:, j][:, None]            # [B, 1]
        v_src = jnp.where(has_src, vals[src_c], PAD)         # [B, N]
        ok_fixed = vals[None] == fixed
        ok_backfill = ~has_src | (v_src == PAD) | (vals[None] == v_src)
        ok = ok & jnp.where(fixed != PAD, ok_fixed, ok_backfill)
    return ok


def check_node_label_presence(cluster: ClusterTensors, pods: PodBatch, cfg: FilterConfig):
    """CheckNodeLabelPresence (predicates.go:923-967), policy-configured."""
    B = pods.n_pods
    N = cluster.n_nodes
    ok = jnp.ones((B, N), bool)
    for key_id in cfg.label_presence_keys:
        present = jnp.any(cluster.label_keys == key_id, axis=-1)  # [N]
        ok = ok & (present[None] == cfg.label_presence_present)
    return ok


def required_affinity_ok(cluster: ClusterTensors, pods: PodBatch):
    """bool[B, N]: the pod's *required affinity rules* alone hold on the node
    (component 3 of MatchInterPodAffinity).  Preemption needs this split:
    ErrPodAffinityRulesNotMatch is unresolvable (evicting pods can only lose
    matches), while the anti-affinity components ARE resolvable
    (generic_scheduler.go:65-123 unresolvablePredicateFailureErrors)."""
    if _is_lean(pods.aff_term_pairs, cluster):
        return jnp.ones((pods.n_pods, cluster.n_nodes), bool)
    topo = cluster.topo_pairs.astype(jnp.float32)            # [N, TP]
    aff_hit = jnp.einsum(
        "btp,np->btn", pods.aff_term_pairs.astype(jnp.float32), topo
    ) > 0                                                    # [B, PT, N]
    any_match = jnp.any(pods.aff_term_pairs, axis=-1)        # [B, PT]
    key_pairs = (
        pods.aff_term_topo_key[:, :, None] == cluster.pair_topo_key[None, None]
    )                                                        # [B, PT, TP]
    node_has_key = jnp.einsum(
        "btp,np->btn", key_pairs.astype(jnp.float32), topo
    ) > 0                                                    # [B, PT, N]
    bootstrap = (
        ~any_match[..., None] & pods.aff_term_self[..., None] & node_has_key
    )
    term_ok = aff_hit | bootstrap | ~pods.aff_term_valid[..., None]
    return jnp.all(term_ok, axis=1)


def match_inter_pod_affinity(cluster: ClusterTensors, pods: PodBatch):
    """MatchInterPodAffinity (predicates.go:1196-1509) via topology-pair
    incidence tensors (the tensorization of metadata.go:64-94):

      1. existing pods' anti-affinity: node fails if it belongs to any
         forbidden pair;
      2. the pod's own anti-affinity terms: node fails if a matching existing
         pod shares the term's topology domain;
      3. the pod's required affinity terms: node must share a topology domain
         with a matching existing pod — unless no such pod exists anywhere and
         the term matches the incoming pod itself (first-pod bootstrap rule,
         predicates.go podMatchesPodAffinityTerms path).
    """
    if _is_lean(pods.aff_term_pairs, cluster):
        return jnp.ones((pods.n_pods, cluster.n_nodes), bool)
    topo = cluster.topo_pairs.astype(jnp.float32)            # [N, TP]
    # 1. existing anti-affinity
    viol1 = (pods.forbidden_pairs.astype(jnp.float32) @ topo.T) > 0   # [B, N]
    # 2. own anti-affinity
    anti_hit = jnp.einsum(
        "btp,np->btn", pods.anti_term_pairs.astype(jnp.float32), topo
    ) > 0                                                    # [B, AT, N]
    viol2 = jnp.any(anti_hit & pods.anti_term_valid[..., None], axis=1)
    # 3. own required affinity
    aff_ok = required_affinity_ok(cluster, pods)
    return ~viol1 & ~viol2 & aff_ok


# ------------------------------------------------------------ the full stack


def filter_batch(cluster: ClusterTensors, pods: PodBatch, cfg: FilterConfig,
                 unsched_taint_key: int = 0, need_per: bool = True):
    """Run every predicate; returns (mask bool[B, N], per_pred bool[B, K, N]).

    per_pred rows follow PREDICATE_ORDER; predicates without device state yet
    (volume binding, zone conflict, service affinity) pass unconditionally and
    are tracked in PARITY.md.  With need_per=False, per_pred is None and the
    stack is never materialized (the engines' hot path).
    """
    B, N = pods.n_pods, cluster.n_nodes
    ones = jnp.ones((B, N), bool)
    res = pod_fits_resources(cluster, pods)
    host = pod_fits_host(cluster, pods)
    ports = pod_fits_host_ports(cluster, pods)
    sel = pod_match_node_selector(cluster, pods)
    vols = max_volume_counts(cluster, pods, cfg.max_vols)
    per = {
        "CheckNodeCondition": check_node_condition(cluster, pods),
        "CheckNodeUnschedulable": check_node_unschedulable(cluster, pods, unsched_taint_key),
        "GeneralPredicates": res & host & ports & sel,
        "PodFitsHost": host,
        "PodFitsHostPorts": ports,
        "PodMatchNodeSelector": sel,
        "PodFitsResources": res,
        "NoDiskConflict": no_disk_conflict(cluster, pods),
        "PodToleratesNodeTaints": pod_tolerates_node_taints(cluster, pods),
        "PodToleratesNodeNoExecuteTaints": pod_tolerates_no_execute_taints(cluster, pods),
        "CheckNodeLabelPresence": check_node_label_presence(cluster, pods, cfg),
        "CheckServiceAffinity": check_service_affinity(cluster, pods, cfg),
        "MaxEBSVolumeCount": vols[:, 0],
        "MaxGCEPDVolumeCount": vols[:, 1],
        # the named CSI predicate folds the generic column AND every
        # per-driver column (one verdict, per-driver accounting)
        "MaxCSIVolumeCount": (
            vols[:, VOL_CSI] & jnp.all(vols[:, NUM_VOL_TYPES:], axis=1)
            if vols.shape[1] > NUM_VOL_TYPES else vols[:, VOL_CSI]
        ),
        "MaxAzureDiskVolumeCount": vols[:, 3],
        "MaxCinderVolumeCount": vols[:, 4],
        "CheckVolumeBinding": check_volume_binding(cluster, pods),
        "NoVolumeZoneConflict": no_volume_zone_conflict(cluster, pods),
        "CheckNodeMemoryPressure": check_node_memory_pressure(cluster, pods),
        "CheckNodePIDPressure": check_node_pid_pressure(cluster, pods),
        "CheckNodeDiskPressure": check_node_disk_pressure(cluster, pods),
        "MatchInterPodAffinity": match_inter_pod_affinity(cluster, pods),
    }
    rows = []
    enabled = set(cfg.enabled) if cfg.enabled is not None else None
    for name, _ in sorted(PRED_INDEX.items(), key=lambda kv: kv[1]):
        if enabled is not None and name not in enabled:
            # disabled by the provider/Policy profile: never filters, never
            # appears in failure attribution (factory predicate registry)
            rows.append(ones)
        else:
            rows.append(per[name])
    alive = cluster.valid[None] & pods.valid[:, None]
    if need_per:
        stack = jnp.stack(rows, axis=1)
        mask = jnp.all(stack, axis=1) & alive
        return mask, stack
    # hot path: fold the AND pairwise instead of materializing the
    # [B, K, N] stack (~70MB at bench scale) just to reduce over it —
    # callers that only consume the verdict (the engines' per-round
    # filter) skip that memory traffic entirely
    mask = alive
    for r in rows:
        mask = mask & r
    return mask, None


def first_failure(per_pred):
    """i32[B, N]: index (in PREDICATE_ORDER) of the first failing predicate,
    or NUM_PREDICATES if the node fits — FitError attribution parity with the
    reference's in-order short-circuit (generic_scheduler.go:598-664)."""
    failed = ~per_pred                               # [B, K, N]
    idx = jnp.argmax(failed, axis=1)                 # first True along K
    any_fail = jnp.any(failed, axis=1)
    return jnp.where(any_fail, idx, NUM_PREDICATES)
