"""Device kernels: the Filter/Score pipeline as pure tensor functions.

Every op is a pure function `(ClusterTensors, PodBatch) -> [B, N] array`, so
the whole pipeline — 23 predicates, 8 priorities, weighted sum, host pick —
compiles to ONE XLA launch, replacing the reference's 16-goroutine per-node
scan (ref pkg/scheduler/core/generic_scheduler.go:518,725).
"""

from kubernetes_tpu.ops.predicates import filter_batch, first_failure
from kubernetes_tpu.ops.priorities import score_batch
from kubernetes_tpu.ops.select import (
    select_host,
    select_hosts_batch,
    num_feasible_nodes_to_find,
)
