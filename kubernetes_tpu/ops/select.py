"""Host selection and the node-sampling knob.

select_host reproduces the reference's argmax-with-round-robin-tie-break
(core/generic_scheduler.go:268-296 selectHost/findMaxScores): among the
feasible nodes with the maximum score, pick the (lastIndex % numTies)-th in
node order, and advance lastIndex each cycle so repeated ties rotate.

num_feasible_nodes_to_find reproduces the adaptive sampling formula
(generic_scheduler.go:434-453).  The TPU path always scores every node in one
launch, so the knob exists for semantic parity (and for the CPU fallback),
not as a performance necessity.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp

MIN_FEASIBLE_NODES_TO_FIND = 100          # generic_scheduler.go:52-57
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5  # generic_scheduler.go:58-63
DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 50  # api/types.go:40


def num_feasible_nodes_to_find(num_all_nodes: int, percentage: int = 0) -> int:
    """generic_scheduler.go:434-453 numFeasibleNodesToFind."""
    if num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND or percentage >= 100:
        return num_all_nodes
    adaptive = percentage
    if adaptive == 0:
        adaptive = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE - num_all_nodes // 125
        if adaptive < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            adaptive = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    num_nodes = num_all_nodes * adaptive // 100
    if num_nodes < MIN_FEASIBLE_NODES_TO_FIND:
        return MIN_FEASIBLE_NODES_TO_FIND
    return num_nodes


def num_feasible_nodes_device(num_all, percentage: int):
    """num_feasible_nodes_to_find with a traced node count (the device-side
    twin; generic_scheduler.go:434-453)."""
    adaptive = (
        jnp.maximum(
            DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE - num_all // 125,
            MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND,
        )
        if percentage == 0 else jnp.int32(percentage)
    )
    num = jnp.maximum(num_all * adaptive // 100, MIN_FEASIBLE_NODES_TO_FIND)
    return jnp.where(num_all < MIN_FEASIBLE_NODES_TO_FIND, num_all, num)


def limit_feasible(mask, limit, start):
    """Keep only the first `limit` feasible nodes in round-robin order from
    `start` — the device form of findNodesThatFit's adaptive early exit
    (generic_scheduler.go:457-556 with numFeasibleNodesToFind + the
    lastIndex offset :486,519).  The reference neither checks nor scores
    nodes beyond the sample; masking them off is equivalent.

    mask bool[N], limit i32 (traced ok), start i32 -> bool[N]."""
    n = mask.shape[-1]
    idx = jnp.arange(n, dtype=jnp.int32)
    rot = (idx - start) % n                   # position in scan order
    order = jnp.argsort(rot)                  # node ids in scan order
    feas_sorted = mask[..., order]
    rank = jnp.cumsum(feas_sorted.astype(jnp.int32), axis=-1) - 1
    keep_sorted = feas_sorted & (rank < limit)
    inv = jnp.argsort(order)
    return keep_sorted[..., inv]


def select_host(scores, mask, last_index):
    """(scores f32[N], mask bool[N], last_index i32) -> (host i32, feasible bool).

    host is the winning node index (or 0 when nothing is feasible — check
    `feasible`).  Pass last_index + 1 on the next cycle for the round-robin
    rotation (the caller owns the counter, as generic_scheduler owns
    lastNodeIndex).
    """
    neg = jnp.float32(-3.4e38)
    s = jnp.where(mask, scores, neg)
    best = jnp.max(s)
    feasible = jnp.any(mask)
    is_tie = mask & (s == best)
    num_ties = jnp.sum(is_tie.astype(jnp.int32))
    k = jnp.where(num_ties > 0, last_index % jnp.maximum(num_ties, 1), 0)
    # index of the (k+1)-th tie in node order
    rank = jnp.cumsum(is_tie.astype(jnp.int32)) - 1          # rank among ties
    host = jnp.argmax(is_tie & (rank == k))
    return host.astype(jnp.int32), feasible


class TopKQuality(NamedTuple):
    """Per-pod decision-quality outputs of the engines' `quality_topk`
    static-flag variant (the placement-quality observatory's raw signal,
    runtime/quality.py).

    top_nodes[..., K]: the K best-scoring feasible node rows with the
    WINNER PINNED AT COLUMN 0 (select_host's argmax-with-rotating-tie-
    break winner, not top_k's first-occurrence tie order — so column 0
    always equals the committed placement); -1 where fewer than K nodes
    were feasible (and the whole row when the pod was unschedulable).
    top_scores[..., K]: those rows' total scores (0 in -1 slots).
    feasible[...]: how many candidate nodes the selector actually
    considered for the pod — the post-predicate, post-sampling mask
    population select_host argmaxed over."""

    top_nodes: Any   # i32[..., K]
    top_scores: Any  # f32[..., K]
    feasible: Any    # i32[...]


def select_topk(scores, mask, host, feasible, k: int) -> TopKQuality:
    """Winner-pinned top-k companion to select_host: given the SAME
    (scores, mask) the selector saw plus its (host, feasible) verdict,
    return the top-k rows with the winner first and the runner-ups in
    descending score order.  Read-only — composing this alongside
    select_host cannot perturb the placement (the flag-on/off
    bit-identity the quality observatory pins).

    Only the ranking generalizes beyond the argmax: on a node-sharded
    mesh XLA lowers the masked top_k exactly like the argmax reduction
    (per-shard candidates, one cross-shard combine), so the sharded
    engines return the same rows as single-chip."""
    import jax

    neg = jnp.float32(-3.4e38)
    n = scores.shape[-1]
    s = jnp.where(mask, scores, neg)
    win_score = jnp.where(feasible, s[host], neg)
    win_node = jnp.where(feasible, host, -1).astype(jnp.int32)
    if k > 1:
        # mask the winner out so the remaining k-1 slots are the true
        # runner-ups even when ties rotated the winner off top_k's
        # first-occurrence order
        s2 = jnp.where((jnp.arange(n) == host) & feasible, neg, s)
        rv, ri = jax.lax.top_k(s2, k - 1)
        vals = jnp.concatenate([win_score[None], rv])
        idx = jnp.concatenate([win_node[None], ri.astype(jnp.int32)])
    else:
        vals = win_score[None]
        idx = win_node[None]
    ok = vals > neg / 2
    return TopKQuality(
        top_nodes=jnp.where(ok, idx, -1).astype(jnp.int32),
        top_scores=jnp.where(ok, vals, jnp.float32(0.0)),
        feasible=jnp.sum(mask.astype(jnp.int32), axis=-1),
    )


def select_topk_batch(scores, mask, hosts, feasible, k: int) -> TopKQuality:
    """Vectorized winner-pinned top-k over a [B, N] grid (the
    speculative engine's per-round companion to select_hosts_batch)."""
    import jax

    return jax.vmap(
        lambda s, mk, h, f: select_topk(s, mk, h, f, k)
    )(scores, mask, hosts, feasible)


def select_hosts_batch(scores, mask, last_index0):
    """Vectorized independent selection for a [B, N] grid (no sequential
    commit): pod b uses rotation counter last_index0 + b."""
    import jax

    B = scores.shape[0]
    idxs = last_index0 + jnp.arange(B, dtype=jnp.int32)
    hosts, feas = jax.vmap(select_host)(scores, mask, idxs)
    return hosts, feas
