"""Host selection and the node-sampling knob.

select_host reproduces the reference's argmax-with-round-robin-tie-break
(core/generic_scheduler.go:268-296 selectHost/findMaxScores): among the
feasible nodes with the maximum score, pick the (lastIndex % numTies)-th in
node order, and advance lastIndex each cycle so repeated ties rotate.

num_feasible_nodes_to_find reproduces the adaptive sampling formula
(generic_scheduler.go:434-453).  The TPU path always scores every node in one
launch, so the knob exists for semantic parity (and for the CPU fallback),
not as a performance necessity.
"""

from __future__ import annotations

import jax.numpy as jnp

MIN_FEASIBLE_NODES_TO_FIND = 100          # generic_scheduler.go:52-57
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5  # generic_scheduler.go:58-63
DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 50  # api/types.go:40


def num_feasible_nodes_to_find(num_all_nodes: int, percentage: int = 0) -> int:
    """generic_scheduler.go:434-453 numFeasibleNodesToFind."""
    if num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND or percentage >= 100:
        return num_all_nodes
    adaptive = percentage
    if adaptive == 0:
        adaptive = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE - num_all_nodes // 125
        if adaptive < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            adaptive = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    num_nodes = num_all_nodes * adaptive // 100
    if num_nodes < MIN_FEASIBLE_NODES_TO_FIND:
        return MIN_FEASIBLE_NODES_TO_FIND
    return num_nodes


def num_feasible_nodes_device(num_all, percentage: int):
    """num_feasible_nodes_to_find with a traced node count (the device-side
    twin; generic_scheduler.go:434-453)."""
    adaptive = (
        jnp.maximum(
            DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE - num_all // 125,
            MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND,
        )
        if percentage == 0 else jnp.int32(percentage)
    )
    num = jnp.maximum(num_all * adaptive // 100, MIN_FEASIBLE_NODES_TO_FIND)
    return jnp.where(num_all < MIN_FEASIBLE_NODES_TO_FIND, num_all, num)


def limit_feasible(mask, limit, start):
    """Keep only the first `limit` feasible nodes in round-robin order from
    `start` — the device form of findNodesThatFit's adaptive early exit
    (generic_scheduler.go:457-556 with numFeasibleNodesToFind + the
    lastIndex offset :486,519).  The reference neither checks nor scores
    nodes beyond the sample; masking them off is equivalent.

    mask bool[N], limit i32 (traced ok), start i32 -> bool[N]."""
    n = mask.shape[-1]
    idx = jnp.arange(n, dtype=jnp.int32)
    rot = (idx - start) % n                   # position in scan order
    order = jnp.argsort(rot)                  # node ids in scan order
    feas_sorted = mask[..., order]
    rank = jnp.cumsum(feas_sorted.astype(jnp.int32), axis=-1) - 1
    keep_sorted = feas_sorted & (rank < limit)
    inv = jnp.argsort(order)
    return keep_sorted[..., inv]


def select_host(scores, mask, last_index):
    """(scores f32[N], mask bool[N], last_index i32) -> (host i32, feasible bool).

    host is the winning node index (or 0 when nothing is feasible — check
    `feasible`).  Pass last_index + 1 on the next cycle for the round-robin
    rotation (the caller owns the counter, as generic_scheduler owns
    lastNodeIndex).
    """
    neg = jnp.float32(-3.4e38)
    s = jnp.where(mask, scores, neg)
    best = jnp.max(s)
    feasible = jnp.any(mask)
    is_tie = mask & (s == best)
    num_ties = jnp.sum(is_tie.astype(jnp.int32))
    k = jnp.where(num_ties > 0, last_index % jnp.maximum(num_ties, 1), 0)
    # index of the (k+1)-th tie in node order
    rank = jnp.cumsum(is_tie.astype(jnp.int32)) - 1          # rank among ties
    host = jnp.argmax(is_tie & (rank == k))
    return host.astype(jnp.int32), feasible


def select_hosts_batch(scores, mask, last_index0):
    """Vectorized independent selection for a [B, N] grid (no sequential
    commit): pod b uses rotation counter last_index0 + b."""
    import jax

    B = scores.shape[0]
    idxs = last_index0 + jnp.arange(B, dtype=jnp.int32)
    hosts, feas = jax.vmap(select_host)(scores, mask, idxs)
    return hosts, feas
