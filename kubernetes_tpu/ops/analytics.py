"""Device-resident cluster analytics: one fused launch over the snapshot.

The cluster snapshot already lives on device (codec/transfer.py
DeviceSnapshotCache keeps `allocatable`/`requested`/`valid` resident and
scatter-refreshed every cycle), so fleet-level analytics — utilization
percentiles, fragmentation, imbalance, occupancy — are one cheap fused
reduction away instead of a host-side O(N·R) pass.  `cluster_analytics`
is that reduction: a single jitted side-launch the telemetry hub
(runtime/telemetry.py) dispatches every `telemetryIntervalCycles`,
returning a handful of scalars/tiny vectors (one small D2H copy).

These metrics double as the packing-quality evaluation function ROADMAP
items 2 (what-if binpack recommendations) and 4 (learned-scoring replay
harness) score against — the same utilization/fragmentation criteria the
constraint-based-packing and Gavel papers (PAPERS.md) judge policies by —
so the math must be REPRODUCIBLE, not just fast:

Bit-exactness contract (pinned by tests/test_telemetry.py): the jitted
kernel and `cluster_analytics_np` (plain numpy, same source) produce
bit-identical outputs on any backend.  Achieved by construction, not by
tolerance: every floating-point reduction is an explicit pairwise TREE
FOLD (zero-padded to a pow2 length, halves added until one row remains —
the identical sequence of IEEE adds whichever library executes it),
percentiles are sort+gather (comparison-based, no accumulation), and the
remaining ops (divide, sqrt, round, elementwise max) are correctly
rounded by IEEE 754 everywhere.  XLA's native `reduce` makes no such
ordering promise, which is exactly why it is not used here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.codec.schema import (
    RES_EPHEMERAL,
    RES_MEMORY,
    RES_MILLICPU,
    RES_PODS,
    _dc_pytree,
)

# the core resource columns the analytics reduce over, in output order
RESOURCE_NAMES = ("cpu", "memory", "ephemeral", "pods")
_RES_COLS = (RES_MILLICPU, RES_MEMORY, RES_EPHEMERAL, RES_PODS)
# per-resource utilization statistics, in output order
STAT_NAMES = ("mean", "max", "p50", "p90", "p99")
_QUANTILES = (0.5, 0.9, 0.99)
# pods-per-node occupancy histogram bins: fraction of the node's pod
# capacity in use, [i/10, (i+1)/10) with the last bin catching 100%
OCC_BINS = 10


@_dc_pytree
@dataclass
class ClusterAnalytics:
    """One telemetry sample's device outputs (a tiny pytree: ~50 floats).

    utilization[r, s]: resource RESOURCE_NAMES[r] x stat STAT_NAMES[s],
    where a node's utilization is requested/allocatable (0 when the node
    allocates none of that resource); invalid (padding/recycled) rows are
    excluded from every statistic."""

    utilization: Any      # f32[4, 5]
    largest_free: Any     # f32[4]  max free capacity on any single node
    #                       per resource — the largest pod request that
    #                       still fits SOMEWHERE, per dimension
    stranded: Any         # f32[2]  (cpu stranded by memory, memory
    #                       stranded by cpu): free units on nodes whose
    #                       OTHER resource is exhausted — capacity no
    #                       cpu+memory pod can use
    fragmentation: Any    # f32[]   stranded fraction of total free
    #                       (mean of the two directions), in [0, 1]
    imbalance: Any        # f32[]   stddev of per-node dominant-resource
    #                       share (0 = perfectly even packing)
    occupancy: Any        # i32[OCC_BINS] nodes per pod-occupancy decile
    nodes: Any            # i32[]   valid nodes in the snapshot
    pods_running: Any     # f32[]   committed pods (sum of the pods col)


def _fold_sum(x, xp):
    """Order-pinned pairwise sum over axis 0: zero-pad to a pow2 length,
    add halves until one row remains.  The SAME sequence of IEEE adds in
    numpy and in the jitted kernel — the whole bit-exactness contract
    rests on this helper."""
    n = x.shape[0]
    if n == 0:
        return xp.zeros(x.shape[1:], x.dtype)
    k = 1 << (n - 1).bit_length()
    if k != n:
        x = xp.concatenate(
            [x, xp.zeros((k - n,) + x.shape[1:], x.dtype)], axis=0
        )
    while x.shape[0] > 1:
        h = x.shape[0] // 2
        x = x[:h] + x[h:]
    return x[0]


def _analytics(allocatable, requested, valid, xp):
    """The shared implementation: xp is jax.numpy inside the jitted
    kernel and numpy in the reference — every op below exists in both
    with IEEE-identical elementwise semantics.

    Structured for LAUNCH CHEAPNESS as much as exactness: every float
    sum rides ONE packed [N, 23] fold chain (column packing changes
    nothing about each column's add sequence, so bit-exactness holds),
    the two max reductions fuse into one [N, 8] op, and all three
    quantiles gather in one indexed load — the whole kernel is ~a dozen
    XLA ops plus log2(N) fold adds, cheap enough to dispatch every
    cycle from the scheduling thread."""
    # the core four resource columns are the leading ones by schema
    # construction (_RES_COLS == (0, 1, 2, 3)); a plain slice keeps the
    # gather out of the kernel
    assert _RES_COLS == (0, 1, 2, 3)
    alloc = allocatable[:, :4].astype(np.float32)          # [N, 4]
    used = requested[:, :4].astype(np.float32)             # [N, 4]
    vmask = valid.astype(bool)                             # [N]
    zero, one = np.float32(0.0), np.float32(1.0)

    # per-node utilization per resource: requested/allocatable where the
    # node allocates any, else 0 (a capacity-less node is idle, not 100%)
    cap_ok = alloc > zero
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = used / alloc
    util = xp.where(vmask[:, None] & cap_ok, ratio, zero)
    # free capacity; stranded = free units on nodes whose complementary
    # resource is exhausted (no cpu+memory pod can land there)
    free = xp.where(
        vmask[:, None], xp.maximum(alloc - used, zero), zero
    )
    free_cpu, free_mem = free[:, 0], free[:, 1]
    no_mem = vmask & ~(free_mem > zero)
    no_cpu = vmask & ~(free_cpu > zero)
    # dominant-resource share (elementwise max over the 4 columns)
    dom = xp.max(util, axis=1)                             # [N]
    # pods-per-node occupancy deciles as 0/1 f32 columns (counts stay
    # exact in f32 far past any real node count)
    occ = util[:, 3]
    bin_idx = xp.clip(
        xp.floor(occ * np.float32(OCC_BINS)).astype(np.int32),
        0, OCC_BINS - 1,
    )
    counted = vmask & cap_ok[:, 3]
    onehot = (
        (bin_idx[:, None] == xp.arange(OCC_BINS, dtype=np.int32)[None, :])
        & counted[:, None]
    ).astype(np.float32)                                   # [N, OCC_BINS]

    # ---- ONE packed fold for every float sum.  Column layout:
    # 0:4 util | 4:8 free | 8 valid | 9 cpu-stranded | 10 mem-stranded
    # | 11 dom | 12 pods used | 13:23 occupancy one-hot
    packed = xp.concatenate(
        [
            util,
            free,
            vmask.astype(np.float32)[:, None],
            xp.where(no_mem, free_cpu, zero)[:, None],
            xp.where(no_cpu, free_mem, zero)[:, None],
            xp.where(vmask, dom, zero)[:, None],
            xp.where(vmask, used[:, 3], zero)[:, None],
            onehot,
        ],
        axis=1,
    )
    S = _fold_sum(packed, xp)                              # [23]
    sum_util, sum_free = S[0:4], S[4:8]
    countf = S[8]
    stranded = S[9:11]
    sum_dom, pods_running = S[11], S[12]
    occupancy = S[13:23].astype(np.int32)
    count_i = countf.astype(np.int32)
    has_nodes = count_i > 0
    denom = xp.maximum(countf, one)

    # fused masked max over util + free columns ([N, 8] -> [8])
    neg_inf = np.float32(-np.inf)
    maxes = (
        xp.max(
            xp.where(
                vmask[:, None], xp.concatenate([util, free], axis=1),
                neg_inf,
            ),
            axis=0,
        )
        if util.shape[0] else xp.full((8,), neg_inf, np.float32)
    )
    maxes = xp.where(maxes == neg_inf, zero, maxes)
    max_util, largest_free = maxes[0:4], maxes[4:8]

    # sort+gather percentiles: one sort, one gather for all quantiles
    # (nearest-rank, round-half-even — no accumulation anywhere)
    mean = xp.where(has_nodes, sum_util / denom, zero)
    if util.shape[0]:
        sorted_util = xp.sort(
            xp.where(vmask[:, None], util, np.float32(np.inf)), axis=0
        )
        qs = np.asarray(_QUANTILES, np.float32)
        idx = xp.round(qs * (countf - one)).astype(np.int32)
        idx = xp.clip(idx, 0, sorted_util.shape[0] - 1)
        quants = xp.where(has_nodes, sorted_util[idx], zero)  # [3, 4]
    else:
        quants = xp.zeros((len(_QUANTILES), 4), np.float32)
    utilization = xp.concatenate(
        [mean[None, :], max_util[None, :], quants], axis=0
    ).T                                                    # [4, 5]

    # fragmentation: stranded fraction of total free, per direction
    frag_dir = xp.where(
        sum_free[0:2] > zero,
        stranded / xp.maximum(sum_free[0:2], one),
        zero,
    )
    fragmentation = (
        np.float32(0.5) * frag_dir[0] + np.float32(0.5) * frag_dir[1]
    )

    # imbalance: stddev of dom across valid nodes (second small fold for
    # the centered squares — the mean must come from the first pass)
    mean_dom = xp.where(has_nodes, sum_dom / denom, zero)
    diff = xp.where(vmask, dom - mean_dom, zero)
    var = xp.where(has_nodes, _fold_sum(diff * diff, xp) / denom, zero)
    imbalance = xp.sqrt(var)

    return ClusterAnalytics(
        utilization=utilization,
        largest_free=largest_free,
        stranded=stranded,
        fragmentation=fragmentation,
        imbalance=imbalance,
        occupancy=occupancy,
        nodes=count_i,
        pods_running=pods_running,
    )


def _analytics_jax(allocatable, requested, valid):
    return _analytics(allocatable, requested, valid, jnp)


# THE kernel: one fused launch per snapshot shape (re-traced only when N
# changes, like every engine executable).  Inputs may be device-resident
# buffers (the telemetry hub hands DeviceSnapshotCache.resident()) or
# host arrays (jit uploads them — the CPU-fallback path).
cluster_analytics = jax.jit(_analytics_jax)


# one kernel per distinct input-sharding triple — bounded by the handful
# of mesh layouts a process ever runs (1D node mesh, dcn x ici)
from functools import lru_cache


@lru_cache(maxsize=8)
def _mesh_kernel(shardings):
    return jax.jit(_analytics_jax, in_shardings=shardings)


def cluster_analytics_auto(allocatable, requested, valid):
    """Mesh-aware dispatch over the resident snapshot buffers.

    When the inputs carry NamedShardings (a mesh-backed
    DeviceSnapshotCache — the multi-chip live path), the kernel compiles
    with those shardings PINNED as in_shardings: the per-node elementwise
    pass (utilization/free/occupancy one-hots, the packed [N, 23] matrix)
    stays on the shard that owns each row, the pairwise fold's first
    log2(N/S) levels are shard-local adds, and only the last log2(S)
    fold levels plus the percentile sort cross shards — a per-shard
    reduce with a cross-shard fold, NOT a gather of the full node tensor
    to one chip (which an unpinned jit could silently re-layout into).
    Bit-exact vs cluster_analytics_np either way: sharding moves data,
    never reassociates the order-pinned fold (pinned by
    tests/test_sharded_live.py).  Unsharded inputs take the classic
    single-device kernel unchanged."""
    from jax.sharding import NamedSharding

    shs = tuple(
        getattr(x, "sharding", None)
        for x in (allocatable, requested, valid)
    )
    if all(isinstance(s, NamedSharding) for s in shs) and any(
        not s.is_fully_replicated for s in shs
    ):
        return _mesh_kernel(shs)(allocatable, requested, valid)
    return cluster_analytics(allocatable, requested, valid)


def cluster_analytics_np(allocatable, requested, valid) -> ClusterAnalytics:
    """The bit-exact numpy reference (and the degraded-mode fallback the
    telemetry hub uses while the device breaker is open)."""
    return _analytics(
        np.asarray(allocatable), np.asarray(requested),
        np.asarray(valid), np,
    )


def analytics_to_dict(a: ClusterAnalytics) -> dict:
    """Host-materialized sample -> the plain-JSON shape served by
    GET /debug/cluster and recorded in the telemetry ring."""
    util = np.asarray(a.utilization, np.float32)
    return {
        "utilization": {
            RESOURCE_NAMES[r]: {
                STAT_NAMES[s]: float(util[r, s])
                for s in range(len(STAT_NAMES))
            }
            for r in range(len(RESOURCE_NAMES))
        },
        "largest_free": {
            RESOURCE_NAMES[r]: float(np.asarray(a.largest_free)[r])
            for r in range(len(RESOURCE_NAMES))
        },
        "stranded": {
            "cpu": float(np.asarray(a.stranded)[0]),
            "memory": float(np.asarray(a.stranded)[1]),
        },
        "fragmentation": float(np.asarray(a.fragmentation)),
        "imbalance": float(np.asarray(a.imbalance)),
        "occupancy": [int(x) for x in np.asarray(a.occupancy)],
        "nodes": int(np.asarray(a.nodes)),
        "pods_running": float(np.asarray(a.pods_running)),
    }
