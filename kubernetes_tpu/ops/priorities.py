"""Score priorities as batched tensor kernels.

Each mirrors one reference priority (pkg/scheduler/algorithm/priorities/*) on
the whole pods x nodes grid, including the Map/Reduce normalization semantics
(priorities/types.go:28-34, reduce.go NormalizeReduce) and the weighted sum
(core/generic_scheduler.go:767-772).  Reference scores are int64 on a 0..10
scale with integer truncation; we reproduce the truncation with floor() so the
parity suite can compare exactly.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.codec.schema import (
    ClusterTensors,
    NUM_PRIORITIES,
    PAD,
    PodBatch,
    PRIO_INDEX,
    RES_MEMORY,
    RES_MILLICPU,
)
from kubernetes_tpu.ops.predicates import _eval_exprs

MAX_PRIORITY = 10.0
_PREFER_NO_SCHEDULE = 1
_TOL_EXISTS = 1

# ImageLocality thresholds (priorities/image_locality.go:33-36)
_IMG_MIN = 23.0 * 1024 * 1024
_IMG_MAX = 1000.0 * 1024 * 1024

# SelectorSpread zone weighting (priorities/selector_spreading.go:34)
_ZONE_WEIGHT = 2.0 / 3.0


def _fdiv_floor(a, b):
    """Integer-division semantics of the reference's int64 math (operands are
    non-negative here, so trunc == floor)."""
    return jnp.floor(a / jnp.maximum(b, 1e-30))


def _normalize_reduce(counts, max_priority=MAX_PRIORITY, reverse=False):
    """reduce.go NormalizeReduce over the node axis: score = max_priority *
    count / maxCount (floored), reversed if asked; all-max when maxCount==0
    and reverse."""
    maxc = jnp.max(counts, axis=-1, keepdims=True)
    score = _fdiv_floor(max_priority * counts, maxc)
    if reverse:
        score = max_priority - score
    return jnp.where(maxc > 0, score, max_priority if reverse else 0.0)


# ----------------------------------------------------------------- resources
# State-parameterized cores, shared with the sequential-commit scan
# (models/batched.py) where `requested` is the in-scan mutable state.


def node_capacity2(cluster: ClusterTensors):
    """(milliCPU, memory) allocatable -> f32[N, 2]."""
    return jnp.stack(
        [cluster.allocatable[:, RES_MILLICPU], cluster.allocatable[:, RES_MEMORY]],
        axis=-1,
    )


def least_requested_score(req2, cap2):
    """least_requested.go leastRequestedScore over (cpu, mem) pairs:
    ((cap-req)*10/cap + ...)/2, int-floored at each step.
    req2 [..., N, 2], cap2 [N, 2] -> [..., N]."""
    per = _fdiv_floor((cap2 - req2) * MAX_PRIORITY, cap2)
    per = jnp.where((cap2 == 0) | (req2 > cap2), 0.0, per)
    return jnp.floor(jnp.sum(per, axis=-1) / 2.0)


def most_requested_score(req2, cap2):
    per = _fdiv_floor(req2 * MAX_PRIORITY, cap2)
    per = jnp.where((cap2 == 0) | (req2 > cap2), 0.0, per)
    return jnp.floor(jnp.sum(per, axis=-1) / 2.0)


def balanced_allocation_score(req2, cap2):
    """balanced_resource_allocation.go:41-67:
    int64((1 - |cpuFraction - memFraction|) * 10); 0 if either fraction >= 1."""
    frac = req2 / jnp.maximum(cap2, 1e-30)
    over = jnp.any((frac >= 1.0) | (cap2 == 0), axis=-1)
    diff = jnp.abs(frac[..., 0] - frac[..., 1])
    return jnp.where(over, 0.0, jnp.floor((1.0 - diff) * MAX_PRIORITY))


def _requested_with_pod(cluster: ClusterTensors, pods: PodBatch):
    """nonzero-request (cpu, mem) per (pod, node) if the pod were placed
    (resource_allocation.go:49-58)."""
    return pods.nonzero_req[:, None, :] + cluster.nonzero_req[None]   # [B, N, 2]


def least_requested(cluster: ClusterTensors, pods: PodBatch):
    """LeastRequestedPriority (priorities/least_requested.go)."""
    return least_requested_score(
        _requested_with_pod(cluster, pods), node_capacity2(cluster)[None]
    )


def most_requested(cluster: ClusterTensors, pods: PodBatch):
    """MostRequestedPriority (priorities/most_requested.go) — used by the
    ClusterAutoscalerProvider profile (defaults.go registerAlgorithmProvider)."""
    return most_requested_score(
        _requested_with_pod(cluster, pods), node_capacity2(cluster)[None]
    )


def balanced_allocation(cluster: ClusterTensors, pods: PodBatch):
    """BalancedResourceAllocation (balanced_resource_allocation.go:41-67)."""
    return balanced_allocation_score(
        _requested_with_pod(cluster, pods), node_capacity2(cluster)[None]
    )


# ------------------------------------------------------------ node affinity


def node_affinity(cluster: ClusterTensors, pods: PodBatch):
    """NodeAffinityPriority (priorities/node_affinity.go): sum the weights of
    matching preferredDuringScheduling terms, then NormalizeReduce(10, false)."""
    if pods.pref_weight.shape[1] == 0:
        # affinity-lean batch: no preferred terms anywhere -> all-zero counts
        return jnp.zeros((pods.n_pods, cluster.n_nodes), jnp.float32)
    m = _eval_exprs(
        cluster,
        pods.pref_expr_key,
        pods.pref_expr_op,
        pods.pref_expr_vals,
        pods.pref_expr_nval,
        pods.pref_expr_num,
        pods.pref_expr_valid,
    )                                                        # [B, PS, E, N]
    term_ok = jnp.all(m, axis=2) & pods.pref_term_valid[..., None]
    counts = jnp.sum(jnp.where(term_ok, pods.pref_weight[..., None], 0.0), axis=1)
    return _normalize_reduce(counts)


# ---------------------------------------------------------- taint toleration


def taint_toleration(cluster: ClusterTensors, pods: PodBatch):
    """TaintTolerationPriority (priorities/taint_toleration.go): count
    intolerable PreferNoSchedule taints, NormalizeReduce(10, true)."""
    tk = pods.tol_key[:, :, None, None]
    to = pods.tol_op[:, :, None, None]
    tv = pods.tol_val[:, :, None, None]
    te = pods.tol_effect[:, :, None, None]
    tvalid = pods.tol_valid[:, :, None, None]
    ntk = cluster.taint_key[None, None]
    ntv = cluster.taint_val[None, None]
    nte = cluster.taint_effect[None, None]
    tol = (
        tvalid
        & ((te == PAD) | (te == nte))
        & ((tk == 0) | (tk == ntk))
        & ((to == _TOL_EXISTS) | (tv == ntv))
    )
    tolerated = jnp.any(tol, axis=1)                         # [B, N, T]
    prefer = cluster.taint_effect == _PREFER_NO_SCHEDULE     # [N, T]
    counts = jnp.sum((prefer[None] & ~tolerated).astype(jnp.float32), axis=-1)
    return _normalize_reduce(counts, reverse=True)


# ------------------------------------------------------------- image locality


def image_locality(cluster: ClusterTensors, pods: PodBatch):
    """ImageLocalityPriority (priorities/image_locality.go): sum spread-scaled
    sizes of the pod's images present on the node, clamp to [23MB, 1000MB],
    scale to 0..10.  Spread scaling is folded into cluster.image_size at
    snapshot time."""
    pid = pods.image_ids[:, :, None, None]                   # [B, C, 1, 1]
    nid = cluster.image_id[None, None]                       # [1, 1, N, I]
    hit = (pid != PAD) & (pid == nid)
    summed = jnp.sum(
        jnp.where(hit, cluster.image_size[None, None], 0.0), axis=(1, 3)
    )                                                        # [B, N]
    clamped = jnp.clip(summed, _IMG_MIN, _IMG_MAX)
    return jnp.floor(MAX_PRIORITY * (clamped - _IMG_MIN) / (_IMG_MAX - _IMG_MIN))


# -------------------------------------------------------- prefer-avoid-pods


def node_prefer_avoid_pods(cluster: ClusterTensors, pods: PodBatch):
    """NodePreferAvoidPodsPriority (priorities/node_prefer_avoid_pods.go):
    0 if the node's preferAvoidPods annotation names the pod's RC/RS
    controller, else 10.  Registered with weight 10000."""
    owner = pods.owner_uid[:, None, None]                    # [B, 1, 1]
    avoid = (owner != PAD) & (owner == cluster.avoid_owner[None])   # [B, N, A]
    return jnp.where(jnp.any(avoid, axis=-1), 0.0, MAX_PRIORITY)


# ------------------------------------------------------------ selector spread


def spread_score_from_counts(counts, cluster: ClusterTensors, zone_key_id: int):
    """The SelectorSpread reduce (selector_spreading.go:95-140) given per-node
    matching-pod counts [..., N]: fScore = (1-2/3)*nodeScore + 2/3*zoneScore,
    int-truncated.  Zone aggregation is a segment-sum over each node's zone
    pair id (scatter + gather, O(B*N))."""
    max_node = jnp.max(counts, axis=-1, keepdims=True)
    # guarded denominator: the zero branch is selected away, but dividing
    # by 0 first would trip the checkify float guards (tests/test_checkify)
    node_score = jnp.where(
        max_node > 0,
        MAX_PRIORITY * (max_node - counts) / jnp.maximum(max_node, 1.0),
        MAX_PRIORITY,
    )
    # zone aggregation as a segment-sum over each node's zone pair id:
    # O(B*N) scatter+gather instead of two [.., N] x [N, TP] matmuls over
    # the WHOLE pair vocabulary (hostname pairs make TP ~ N, so the matmul
    # form costs B*N*TP flops — negligible on the MXU, seconds on the CPU
    # fallback).  GetZoneKey gives each node at most ONE zone pair, so the
    # argmax column is exact.
    zmask = cluster.pair_topo_key == zone_key_id             # [TP]
    zpairs_b = cluster.topo_pairs & zmask[None]              # [N, TP] bool
    node_in_zone = jnp.any(zpairs_b, axis=-1)                # [N]
    zone_of_node = jnp.argmax(zpairs_b, axis=-1)             # [N] pair id
    TP = zpairs_b.shape[1]
    lead = counts.shape[:-1]
    n = counts.shape[-1]
    flat = counts.reshape((-1, n))
    contrib = jnp.where(node_in_zone[None, :], flat, 0.0)
    zsums = jnp.zeros((flat.shape[0], TP), flat.dtype)
    zsums = zsums.at[:, zone_of_node].add(contrib)           # [M, TP]
    zcount_per_node = zsums[:, zone_of_node].reshape(lead + (n,))
    max_zone = jnp.max(zsums, axis=-1).reshape(lead + (1,))
    zone_score = jnp.where(
        max_zone > 0,
        MAX_PRIORITY * (max_zone - zcount_per_node)
        / jnp.maximum(max_zone, 1.0),
        MAX_PRIORITY,
    )
    have_zones = jnp.any(node_in_zone)
    blended = jnp.where(
        have_zones & node_in_zone,
        (1.0 - _ZONE_WEIGHT) * node_score + _ZONE_WEIGHT * zone_score,
        node_score,
    )
    return jnp.floor(blended)


def pod_group_onehot(pods: PodBatch, n_groups: int):
    """[B, G] one-hot of each pod's spread groups."""
    return (
        (pods.group_ids[:, :, None] == jnp.arange(n_groups)[None, None])
        & pods.group_valid[..., None]
    ).astype(jnp.float32).sum(axis=1)


def pod_spread_match(pods: PodBatch, n_groups: int):
    """f32[B, B] [i, j]: committing pod j raises pod i's spread count at
    j's node — i.e. j matches ALL of i's selectors, expressed as "i's
    group set is a subset of j's" over the one-hots (groups are
    namespace-scoped, so the ns check rides along).  countMatchingPods
    AND semantics (selector_spreading.go:95-140); shared by BOTH engines
    so their in-batch bookkeeping can never desync."""
    from jax import lax as _lax

    onehot = pod_group_onehot(pods, n_groups)                # [B, G]
    has_groups = jnp.any(pods.group_valid, axis=1)           # [B]
    return (
        has_groups[:, None]
        & (jnp.matmul(onehot, (1.0 - onehot).T,
                      precision=_lax.Precision.HIGHEST) == 0)
    ).astype(jnp.float32)


def selector_spread(cluster: ClusterTensors, pods: PodBatch, zone_key_id: int = 5):
    """SelectorSpreadPriority (priorities/selector_spreading.go:77-140):
    per-node counts of existing pods matching ALL the pod's selectors
    (countMatchingPods AND semantics), then the zone-weighted reduce.
    zone_key_id is the interned id of the encoder's synthetic GetZoneKey
    topology key (region+zone grouping).

    Counts source: spread-lean batches (every pod in <= 1 group — the
    common shape) derive counts on device from the snapshot's per-group
    columns; multi-group batches ship exact host-computed AND counts."""
    counts = spread_counts(cluster, pods)
    return spread_score_from_counts(counts, cluster, zone_key_id)


def spread_counts(cluster: ClusterTensors, pods: PodBatch):
    """f32[B, N] matching-pod counts (see selector_spread)."""
    if pods.spread_counts.shape[-1] != cluster.n_nodes:
        onehot = pod_group_onehot(pods, cluster.group_counts.shape[1])
        return onehot @ cluster.group_counts.T               # [B, N]
    return pods.spread_counts


# --------------------------------------------------------- inter-pod affinity


def inter_pod_affinity_score(cluster: ClusterTensors, pods: PodBatch):
    """InterPodAffinityPriority (priorities/interpod_affinity.go): signed
    weight sums over topology pairs (preferred affinity/anti-affinity of the
    incoming pod, preferred+hard-symmetric terms of existing pods — all folded
    into pref_pair_weights by the encoder), then the min/max normalize
    fScore = 10 * (sum - min) / (max - min)."""
    if pods.pref_pair_weights.shape[-1] != cluster.topo_pairs.shape[-1]:
        # lean batch: no affinity exposure anywhere -> all sums identical
        # (zero) -> score 0 on every node, computed for free
        return jnp.zeros((pods.n_pods, cluster.n_nodes), jnp.float32)
    sums = pods.pref_pair_weights @ cluster.topo_pairs.astype(jnp.float32).T
    valid = cluster.valid[None]
    big = jnp.float32(3.4e38)
    mn = jnp.min(jnp.where(valid, sums, big), axis=-1, keepdims=True)
    mx = jnp.max(jnp.where(valid, sums, -big), axis=-1, keepdims=True)
    spread = mx - mn
    score = jnp.where(
        spread > 0,
        jnp.floor(MAX_PRIORITY * (sums - mn) / jnp.maximum(spread, 1e-30)),
        0.0,
    )
    return jnp.where(valid, score, 0.0)


# --------------------------------------------------- policy-driven priorities


def node_label_priority(cluster: ClusterTensors, pods: PodBatch, score_cfg):
    """NodeLabelPriority (priorities/node_label.go): per configured
    (key, presence) pref: 10 when presence matches, else 0; weighted sum of
    prefs, then NOT normalized (each pref is its own PriorityConfig in the
    reference — we fold them with their weights here)."""
    B, N = pods.n_pods, cluster.n_nodes
    total = jnp.zeros((B, N), jnp.float32)
    for key_id, presence, weight in score_cfg.label_prefs:
        present = jnp.any(cluster.label_keys == key_id, axis=-1)  # [N]
        score = jnp.where(present == bool(presence), MAX_PRIORITY, 0.0)
        total = total + weight * score[None, :]
    return total


def requested_to_capacity_ratio(cluster: ClusterTensors, pods: PodBatch, score_cfg):
    """RequestedToCapacityRatioPriority (priorities/
    requested_to_capacity_ratio.go): per-resource utilization% mapped through
    the configured piecewise-linear curve, averaged over (cpu, mem)."""
    req = _requested_with_pod(cluster, pods)                 # [B, N, 2]
    cap = node_capacity2(cluster)[None]
    util = jnp.where(cap > 0, req * 100.0 / jnp.maximum(cap, 1e-30), 100.0)
    pts = score_cfg.rtc_shape
    xs = jnp.asarray([p[0] for p in pts], jnp.float32)
    ys = jnp.asarray([p[1] for p in pts], jnp.float32)
    score = jnp.interp(util, xs, ys)                         # clamps at ends
    return jnp.floor(jnp.sum(score, axis=-1) / 2.0)


def resource_limits(cluster: ClusterTensors, pods: PodBatch):
    """ResourceLimitsPriority (priorities/resource_limits.go, feature-gated):
    1 if the node's allocatable satisfies the pod's cpu+mem limits and at
    least one limit is set, else 0."""
    cap = node_capacity2(cluster)[None]                      # [1, N, 2]
    lim = pods.limits2[:, None, :]                           # [B, 1, 2]
    ok = jnp.all((lim == 0) | (cap >= lim), axis=-1)
    any_lim = jnp.any(pods.limits2 > 0, axis=-1)[:, None]
    return jnp.where(ok & any_lim, 1.0, 0.0)


# ------------------------------------------------------------------ combined


def score_batch(cluster: ClusterTensors, pods: PodBatch, weights=None,
                score_cfg=None, zone_key_id: int = 5,
                skip_zero_weight: bool = False, need_per: bool = True):
    """All priorities + weighted sum -> (total f32[B, N], per f32[B, P, N]).

    weights follows PRIORITY_ORDER; defaults to the stock weights
    (default provider set at 1 / 10000, policy-only functions at 0)."""
    if score_cfg is None:
        from kubernetes_tpu.codec.schema import ScoreConfig

        score_cfg = ScoreConfig()
    if weights is None:
        from kubernetes_tpu.codec.schema import DEFAULT_PRIORITY_WEIGHTS

        weights = DEFAULT_PRIORITY_WEIGHTS
    w_host = np.asarray(weights, np.float32)
    makers = {
        "SelectorSpreadPriority":
            lambda: selector_spread(cluster, pods, zone_key_id),
        "InterPodAffinityPriority":
            lambda: inter_pod_affinity_score(cluster, pods),
        "LeastRequestedPriority": lambda: least_requested(cluster, pods),
        "BalancedResourceAllocation":
            lambda: balanced_allocation(cluster, pods),
        "NodePreferAvoidPodsPriority":
            lambda: node_prefer_avoid_pods(cluster, pods),
        "NodeAffinityPriority": lambda: node_affinity(cluster, pods),
        "TaintTolerationPriority": lambda: taint_toleration(cluster, pods),
        "ImageLocalityPriority": lambda: image_locality(cluster, pods),
        "MostRequestedPriority": lambda: most_requested(cluster, pods),
        "NodeLabelPriority":
            lambda: node_label_priority(cluster, pods, score_cfg),
        "RequestedToCapacityRatioPriority":
            lambda: requested_to_capacity_ratio(cluster, pods, score_cfg),
        "ResourceLimitsPriority": lambda: resource_limits(cluster, pods),
    }
    # with skip_zero_weight (the engines' hot path), zero-weight
    # priorities contribute nothing to the total — skip their kernels
    # entirely (weights are trace-time constants; the stock set zeroes
    # the 4 policy-only functions, and RTC alone is ~20% of a
    # CPU-fallback round).  Their stack rows become zeros, so callers
    # needing the full per-priority breakdown (parity/golden tests, the
    # one-launch generic path) keep the default full computation.
    zero = None
    if not need_per:
        # total-only hot path (the engines): accumulate the weighted sum
        # without materializing the [B, P, N] stack (~0.5GB at batch
        # 2048 x 5k nodes)
        total = jnp.zeros((pods.n_pods, cluster.n_nodes), jnp.float32)
        for name, _ in sorted(PRIO_INDEX.items(), key=lambda kv: kv[1]):
            w_i = float(w_host[PRIO_INDEX[name]])
            if w_i != 0.0:
                total = total + w_i * makers[name]()
        return total, None
    per = []
    for name, _ in sorted(PRIO_INDEX.items(), key=lambda kv: kv[1]):
        if not skip_zero_weight or w_host[PRIO_INDEX[name]] != 0.0:
            per.append(makers[name]())
        else:
            if zero is None:
                zero = jnp.zeros((pods.n_pods, cluster.n_nodes),
                                 jnp.float32)
            per.append(zero)
    stack = jnp.stack(per, axis=1)                           # [B, P, N]
    w = jnp.asarray(w_host, jnp.float32)
    total = jnp.einsum("bpn,p->bn", stack, w)
    return total, stack


def static_score_components(cluster: ClusterTensors, pods: PodBatch,
                            weights, score_cfg, include_ipa: bool = True,
                            extra_score=None):
    """f32[B, C, N] WEIGHTED static score addends on the attribution
    component axis (schema.SCORE_COMPONENTS = PRIORITY_ORDER + "Extra").

    The state-dependent priorities (least/most/balanced/spread/RTC — and
    InterPodAffinity when the in-batch scan owns it) stay zero here; the
    sequential-commit scan fills them per step against the current
    committed state, so the per-plugin breakdown sums to the exact score
    selectHost saw.  Only built under the engines' attribution flag — the
    default executable never materializes the stack."""
    from kubernetes_tpu.codec.schema import NUM_SCORE_COMPONENTS

    w = np.asarray(weights, np.float32)
    B, N = pods.n_pods, cluster.n_nodes
    comp = jnp.zeros((B, NUM_SCORE_COMPONENTS, N), jnp.float32)

    def put(name, fn):
        w_i = float(w[PRIO_INDEX[name]])
        if w_i != 0.0:
            return comp.at[:, PRIO_INDEX[name]].set(w_i * fn())
        return comp

    comp = put("NodePreferAvoidPodsPriority",
               lambda: node_prefer_avoid_pods(cluster, pods))
    comp = put("NodeAffinityPriority", lambda: node_affinity(cluster, pods))
    comp = put("TaintTolerationPriority",
               lambda: taint_toleration(cluster, pods))
    comp = put("ImageLocalityPriority", lambda: image_locality(cluster, pods))
    comp = put("NodeLabelPriority",
               lambda: node_label_priority(cluster, pods, score_cfg))
    comp = put("ResourceLimitsPriority",
               lambda: resource_limits(cluster, pods))
    if include_ipa:
        comp = put("InterPodAffinityPriority",
                   lambda: inter_pod_affinity_score(cluster, pods))
    if extra_score is not None:
        comp = comp.at[:, NUM_SCORE_COMPONENTS - 1].set(extra_score)
    return comp
