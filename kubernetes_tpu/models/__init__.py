"""Scheduling algorithms composed from ops/ kernels.

  generic.py  — independent Filter/Score over a pod batch in one launch
                (the ScheduleAlgorithm.Schedule analog,
                ref core/generic_scheduler.go:184-254)
  batched.py  — sequential-commit batch scheduling under lax.scan: B pods
                placed in ONE device launch with on-device state updates
                between pods (the >=10k pods/s path; no reference analog —
                the reference schedules strictly one pod at a time)
  preemption.py — vectorized preemption what-if (ref Preempt :310-369)
"""

from kubernetes_tpu.models.generic import schedule_batch_independent
from kubernetes_tpu.models.batched import (
    BatchPortState,
    encode_batch_ports,
    make_sequential_scheduler,
)
from kubernetes_tpu.models.preemption import (
    preempt_one,
    preemption_candidates,
    sorted_victim_slots,
)
from kubernetes_tpu.models.gang import GangScheduler, PodGroup
from kubernetes_tpu.models.binpack import binpack_ffd, binpack_shapes, what_if
