"""Gang / coscheduling: all-or-nothing batched assignment.

Not in the reference tree (PodGroup coscheduling lives in the sibling
scheduler-plugins project; BASELINE.md lists it as a new capability —
"Gang/coscheduling PodGroup: 1k gangs x 32 pods").  The TPU design makes it
almost free: the sequential-commit scan (models/batched.py) is *functional* —
it returns the committed cluster state as a new value — so an all-or-nothing
gang is one scan plus a host-side decision of WHICH state to keep:

    hosts, new_state = seq_schedule(state, gang_pods, ...)
    placed = all(hosts >= 0)
    state  = new_state if placed else state      # rollback = keep the old pytree

No unwind pass, no victim bookkeeping: immutability gives transactional
semantics.  minMember < len(gang) keeps the first minMember placements only
if at least minMember fit (PodGroup.spec.minMember semantics).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import Pod


@dataclass
class PodGroup:
    """PodGroup CRD analog (scheduler-plugins coscheduling API)."""

    name: str
    namespace: str = "default"
    min_member: int = 0  # 0 => all pods required


class GangScheduler:
    """Schedules pod groups transactionally against an encoder + device fn.

    Reuses the Scheduler's sequential-commit program; `schedule_gang` either
    commits every placement to the cache (assume) or none.
    """

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def schedule_gang(
        self, group: PodGroup, pods: Sequence[Pod]
    ) -> Tuple[Optional[List[str]], int]:
        """Returns (node names per pod, n_placed) — names is None if the gang
        did not reach min_member and nothing was committed."""
        from kubernetes_tpu.models.batched import (
            batch_has_pod_affinity,
            encode_batch_affinity,
            encode_batch_ports,
        )

        sched = self.scheduler
        enc = sched.cache.encoder
        need = group.min_member or len(pods)
        with sched.cache._lock:
            # affinity state first: novel term topology keys must register
            # before the TP-wide batch tensors are cut (vocab growth retiles)
            aff_state = (
                encode_batch_affinity(enc, pods)
                if len(pods) > 1 and batch_has_pod_affinity(pods)
                else None
            )
            batch = enc.encode_pods(pods)
            ports = encode_batch_ports(enc, pods)
            cluster, _ = sched.cache.snapshot()
        hosts, _new_state = sched._schedule_fn(
            cluster, batch, ports, np.int32(sched._last_index), None, None, None,
            aff_state,
        )
        sched._last_index += len(pods)
        hosts = np.asarray(hosts)[: len(pods)]
        placed = int((hosts >= 0).sum())
        if placed < need:
            return None, placed
        out: List[str] = []
        import dataclasses

        committed: List = []  # (assumed pod, node) pairs, for rollback
        failed = False
        for i, pod in enumerate(pods):
            if len(committed) >= need and group.min_member:
                out.append("")
                continue
            r = int(hosts[i])
            if r < 0:
                out.append("")
                continue
            node = enc.row_name(r)
            assumed = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec, node_name=node)
            )
            sched.cache.assume_pod(assumed)
            try:
                ok = sched.binder(assumed, node)
            except Exception:
                ok = False
            if not ok:
                sched.cache.forget_pod(assumed)
                failed = True
                break
            committed.append((assumed, node))
            out.append(node)
        if failed or len(committed) < need:
            # all-or-nothing: unwind every bind of this gang
            for assumed, _node in committed:
                sched.cache.forget_pod(assumed)
                unbinder = getattr(sched, "unbinder", None)
                if unbinder is not None:
                    unbinder(assumed)
            return None, len(committed)
        return out, len(committed)
