"""Gang / coscheduling: all-or-nothing batched assignment.

Not in the reference tree (PodGroup coscheduling lives in the sibling
scheduler-plugins project; BASELINE.md lists it as a new capability —
"Gang/coscheduling PodGroup: 1k gangs x 32 pods").  The TPU design makes it
almost free: the sequential-commit scan (models/batched.py) is *functional* —
it returns the committed cluster state as a new value — so an all-or-nothing
gang is one scan plus a host-side decision of WHICH state to keep:

    hosts, new_state = seq_schedule(state, gang_pods, ...)
    placed = all(hosts >= 0)
    state  = new_state if placed else state      # rollback = keep the old pytree

No unwind pass, no victim bookkeeping: immutability gives transactional
semantics.  minMember < len(gang) keeps the first minMember placements only
if at least minMember fit (PodGroup.spec.minMember semantics).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import Pod


@dataclass
class PodGroup:
    """PodGroup CRD analog (scheduler-plugins coscheduling API)."""

    name: str
    namespace: str = "default"
    min_member: int = 0  # 0 => all pods required


class GangScheduler:
    """Schedules pod groups transactionally against an encoder + device fn.

    Reuses the Scheduler's sequential-commit program; `schedule_gang` either
    commits every placement to the cache (assume) or none.
    """

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def _launch(self, pods: Sequence[Pod]) -> np.ndarray:
        """One engine launch over `pods` against a fresh snapshot; returns
        hosts i32[len(pods)] (-1 = unplaced).  Shared by the per-gang and
        co-batched paths.

        ENGINE DEPENDENCY: this must run the strictly SEQUENTIAL scan.
        schedule_gangs' cross-gang required-affinity drop guard (redoing
        only LATER gangs when an earlier gang drops) is sound only because
        a sequentially-committed pod's placement can depend solely on
        earlier flat indices; under the speculative engine (multi-round
        placement, any index order) an already-committed earlier gang
        could have anchored its required affinity on a later gang's
        dropped pods.  The Scheduler always builds _schedule_fn from
        make_sequential_scheduler (the speculative engine lives in
        _speculative_fn), and the assert below keeps a future engine swap
        from silently breaking the all-or-nothing affinity guarantee."""
        from kubernetes_tpu.models.batched import (
            batch_has_pod_affinity,
            encode_batch_affinity,
            encode_batch_ports,
        )

        sched = self.scheduler
        # fail CLOSED: an engine that doesn't declare its commit order
        # (engine_kind unset) must be rejected too — defaulting it to
        # "sequential" would wave through exactly the future engine swap
        # this assert exists to catch
        engine_kind = getattr(sched._schedule_fn, "engine_kind", None)
        if engine_kind != "sequential":  # not assert: survives python -O
            raise RuntimeError(
                "GangScheduler requires the sequential-commit engine; got "
                f"{engine_kind!r} — the cross-gang required-affinity drop "
                "guard is unsound under any other (or undeclared) commit "
                "order"
            )
        enc = sched.cache.encoder
        with sched.cache._lock:
            # affinity state first: novel term topology keys must register
            # before the TP-wide batch tensors are cut (vocab growth retiles)
            aff_state = (
                encode_batch_affinity(enc, pods)
                if len(pods) > 1 and batch_has_pod_affinity(pods)
                else None
            )
            batch = enc.encode_pods(pods)
            ports = encode_batch_ports(enc, pods)
            cluster, _ = sched.cache.snapshot()
        # index instead of unpack: the attribution variant returns a
        # third output (Attribution) the gang verdict doesn't consume
        out = sched._schedule_fn(
            cluster, batch, ports, np.int32(sched._last_index), None, None,
            None, aff_state,
        )
        hosts = out[0]
        sched._last_index += len(pods)
        # gang launches are synchronous by design (the all-or-nothing
        # verdict gates the commit), but the fetch still goes through the
        # instrumented fence so per-cycle sync budgets stay observable
        from kubernetes_tpu.codec.transfer import host_fetch

        return host_fetch(hosts, tag="gang")[: len(pods)]

    def schedule_gang(
        self, group: PodGroup, pods: Sequence[Pod]
    ) -> Tuple[Optional[List[str]], int]:
        """Returns (node names per pod, n_placed) — names is None if the gang
        did not reach min_member and nothing was committed."""
        need = group.min_member or len(pods)
        hosts = self._launch(pods)
        placed = int((hosts >= 0).sum())
        if placed < need:
            return None, placed
        return self._commit_gang(group, pods, hosts)

    def schedule_gangs(
        self, gangs: Sequence[Tuple[PodGroup, Sequence[Pod]]]
    ) -> List[Tuple[Optional[List[str]], int]]:
        """Many gangs, ONE device launch per co-batch: the per-gang
        transaction costs one snapshot + launch + fetch (~100ms through a
        remote-attached chip), so 1k PodGroups pay 1k launches; co-batching
        amortizes the launch across every gang that fits in the engine's
        batch width.

        Per-gang all-or-nothing survives co-batching because dropping a
        failed gang's placements only FREES constraints for the committed
        ones: resources/ports/anti-affinity stay satisfied (fewer pods
        can't add conflicts).  Two conservative rules keep it exact:

        * a gang the co-batch could NOT complete is retried through the
          per-gang path on a FRESH snapshot (a failed gang's partial
          in-scan placements inflate the scan state for later co-batched
          gangs, so in-batch incompleteness can be spurious);
        * when the co-batch carries ANY required pod-affinity terms and
          any gang fails (in-scan or at bind time), the affected gangs
          re-run per-gang — a dropped gang's pods could have been what
          satisfied a committed gang's required affinity."""
        results: List[Tuple[Optional[List[str]], int]] = [
            (None, 0) for _ in gangs
        ]

        def _has_required_pod_affinity(pods) -> bool:
            # the cross-gang drop hazard exists ONLY for required
            # pod-affinity: dropping pods cannot violate anti-affinity
            # (removal only removes matches) and preferred terms are
            # score-only — so anti/preferred terms must not trigger the
            # per-gang redo that defeats co-batch amortization
            for p in pods:
                a = p.spec.affinity
                if (
                    a is not None
                    and a.pod_affinity is not None
                    and a.pod_affinity.required
                ):
                    return True
            return False

        sched = self.scheduler
        cap = max(1, int(getattr(sched.config, "batch_size", 2048)))
        i = 0
        while i < len(gangs):
            # greedy co-batch: whole gangs up to the engine batch width
            batch_slice: List[int] = []
            width = 0
            while i < len(gangs):
                n = len(gangs[i][1])
                if batch_slice and width + n > cap:
                    break
                batch_slice.append(i)
                width += n
                i += 1
            if len(batch_slice) == 1:
                g = batch_slice[0]
                results[g] = self.schedule_gang(*gangs[g])
                continue
            flat: List[Pod] = []
            spans: List[Tuple[int, int]] = []
            for g in batch_slice:
                spans.append((len(flat), len(flat) + len(gangs[g][1])))
                flat.extend(gangs[g][1])
            has_aff = _has_required_pod_affinity(flat)
            hosts = self._launch(flat)
            complete = []
            for j, g in enumerate(batch_slice):
                lo, hi = spans[j]
                need = gangs[g][0].min_member or (hi - lo)
                complete.append(int((hosts[lo:hi] >= 0).sum()) >= need)
            if not all(complete) and has_aff:
                # a dropped gang could have satisfied a committed gang's
                # required affinity — redo the whole co-batch per-gang
                for g in batch_slice:
                    results[g] = self.schedule_gang(*gangs[g])
                continue
            # commit every COMPLETE gang from the batch placements FIRST
            # (valid: the batch world is a superset of what commits, so
            # dropped gangs only free constraints); retries run AFTER on
            # fresh snapshots — a retried gang taking new capacity must
            # not race placements assumed from the stale batch world
            retry: List[int] = []
            dropped = False  # any pod placed in-scan but not committed
            for j, g in enumerate(batch_slice):
                lo, hi = spans[j]
                group, pods = gangs[g]
                if not complete[j] or (dropped and has_aff):
                    # in-batch incompleteness can be SPURIOUS (earlier
                    # failed gangs' partials inflated the scan state),
                    # and an earlier DROP — a rolled-back gang OR a
                    # min_member truncation discarding beyond-need
                    # placements — could strand a later gang's required
                    # affinity: exact per-gang redo on a fresh snapshot
                    retry.append(g)
                    continue
                # commit through the exact per-pod assume/bind path
                # (rollback on binder failure, min_member semantics)
                results[g] = self._commit_gang(group, pods, hosts[lo:hi])
                in_scan = int((hosts[lo:hi] >= 0).sum())
                if results[g][0] is None or results[g][1] < in_scan:
                    dropped = True
            for g in retry:
                results[g] = self.schedule_gang(*gangs[g])
        return results

    def _commit_gang(self, group, pods, hosts):
        """assume+bind one gang's precomputed placements; all-or-nothing."""
        import dataclasses

        sched = self.scheduler
        enc = sched.cache.encoder
        need = group.min_member or len(pods)
        out: List[str] = []
        committed: List = []
        failed = False
        for i, pod in enumerate(pods):
            if len(committed) >= need and group.min_member:
                out.append("")
                continue
            r = int(hosts[i])
            if r < 0:
                out.append("")
                continue
            node = enc.row_name(r)
            assumed = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec, node_name=node)
            )
            sched.cache.assume_pod(assumed)
            try:
                ok = sched.binder(assumed, node)
            except Exception:
                ok = False
            if not ok:
                sched.cache.forget_pod(assumed)
                failed = True
                break
            committed.append((assumed, node))
            out.append(node)
        if failed or len(committed) < need:
            for assumed, _node in committed:
                sched.cache.forget_pod(assumed)
                unbinder = getattr(sched, "unbinder", None)
                if unbinder is not None:
                    unbinder(assumed)
            return None, len(committed)
        return out, len(committed)
