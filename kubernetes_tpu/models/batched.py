"""Sequential-commit batch scheduling: B pods in ONE device launch.

The reference schedules strictly one pod per cycle (scheduler.go:438
scheduleOne); at 5k nodes that caps throughput at the per-cycle host latency.
Here the host loop drains B pods from the queue, encodes them once, and a
single jitted program places them *sequentially* under `lax.scan`: each step
filters+scores pod i against the *current* on-device cluster state, picks a
host (argmax + round-robin tie-break), and commits the placement by updating
the dynamic state columns — so pod i+1 sees pod i's resources, ports, and
spreading counts exactly as if the reference had scheduled them one by one.

Dynamic state inside the scan (everything else is precomputed static):
  requested[N, R], nonzero[N, 2]        — PodFitsResources + resource scores
  spread_extra[B, N]                    — SelectorSpreadPriority in-batch
                                          increments (AND-match cross matrix)
  port_used[N, PV]                      — PodFitsHostPorts within the batch,
                                          over a batch-local port vocabulary
                                          with a precomputed conflict matrix
                                          (wildcard-IP semantics preserved)
  extra_aff/anti/forb/pref              — in-batch inter-pod affinity pair
                                          state (predicateMetadata.AddPod
                                          analog) when aff_state is given
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.codec import transfer
from kubernetes_tpu.codec.schema import (
    ClusterTensors,
    FilterConfig,
    PAD,
    PodBatch,
    _pow2,
)
from kubernetes_tpu.ops.predicates import filter_batch
from kubernetes_tpu.ops.priorities import (
    MAX_PRIORITY,
    balanced_allocation_score,
    inter_pod_affinity_score,
    image_locality,
    least_requested_score,
    most_requested_score,
    node_affinity,
    node_capacity2,
    node_label_priority,
    node_prefer_avoid_pods,
    pod_group_onehot,
    pod_spread_match,
    resource_limits,
    spread_counts,
    spread_score_from_counts,
    taint_toleration,
)
from kubernetes_tpu.ops.select import (
    TopKQuality,
    limit_feasible,
    num_feasible_nodes_device,
    select_host,
    select_topk,
)
from kubernetes_tpu.codec.schema import (
    DEFAULT_PRIORITY_WEIGHTS,
    NUM_REASONS,
    PRIO_INDEX,
    REASON_EXTENDER,
    ScoreConfig,
)


class Attribution(NamedTuple):
    """Per-pod decision attribution, emitted only by the engine's
    attribution variant (make_sequential_scheduler(attribution=True)) so
    the default executable is byte-identical to before.

    reason_counts[b, k]: how many live nodes rejected pod b with reason k
    as the FIRST failure in PREDICATE_ORDER (the reference podFitsOnNode
    short-circuit attribution; the aggregate GeneralPredicates row never
    attributes — its constituents do); the last column (REASON_EXTENDER)
    counts nodes every predicate passed but the extra mask vetoed
    (extender filter / tensor Filter plugin / nominated-pod block).
    Evaluated at the pod's OWN scan step, so in-batch commits (resources,
    ports, affinity) are reflected exactly as selectHost saw them.

    top_nodes/top_scores: the k best-scoring feasible node rows for the
    pod (-1 where fewer than k are feasible); top_components: the
    weighted per-plugin score addends of those rows on the
    schema.SCORE_COMPONENTS axis (PRIORITY_ORDER + "Extra")."""

    reason_counts: Any   # i32[B, NUM_REASONS]
    top_nodes: Any       # i32[B, TK]
    top_scores: Any      # f32[B, TK]
    top_components: Any  # f32[B, TK, NUM_SCORE_COMPONENTS]


@dataclass
class BatchPortState:
    """Batch-local host-port vocabulary (see module docstring)."""

    pod_ports: Any      # bool[B, PV]  ports requested by each pod
    conflict: Any       # bool[PV, PV] do two batch ports conflict


jax.tree_util.register_dataclass(
    BatchPortState,
    data_fields=["pod_ports", "conflict"],
    meta_fields=[],
)


@dataclass
class NominatedState:
    """Nominated pods (preemptors awaiting their victims' graceful exit).

    The two-pass fit evaluation (ref generic_scheduler.go:598-664
    podFitsOnNode) adds nominated pods with priority >= the scheduled pod's
    to their nominated node before filtering, so a preempted-for claim is
    visible to later cycles; the pod must ALSO fit without them (pass two).
    Resource claims live here (they interact with the scan's running
    state); port claims and anti-affinity contributions are host-computed
    per cycle into the extra_mask (encode_nominated_block)."""

    node: Any   # i32[K] nominated node row (-1 = unused slot)
    prio: Any   # i32[K]
    req: Any    # f32[K, R]


jax.tree_util.register_dataclass(
    NominatedState, data_fields=["node", "prio", "req"], meta_fields=[]
)


@dataclass
class BatchAffinityState:
    """In-batch inter-pod-affinity cross-match tensors.

    The per-pod pair tensors in PodBatch are computed against the PRE-batch
    snapshot; these matrices let the sequential-commit scan update affinity
    state as co-batched pods land (the tensorization of predicateMetadata's
    incremental AddPod, ref algorithm/predicates/metadata.go:64-94), so pod
    i+1's MatchInterPodAffinity sees pod i's placement.

    Orientation: step axis first.  aff_match[j, i, t] = "batch pod j matches
    pod i's required-affinity term t" (namespaces + selector); anti_match
    likewise for pod i's anti terms; anti_own[j, t, i] = "pod i matches pod
    j's anti term t" (the committed pod's anti-affinity forbids later
    matching pods from its topology domains)."""

    aff_match: Any   # bool[B, B, PT]
    anti_match: Any  # bool[B, B, AT]
    anti_own: Any    # bool[B, AT, B]
    aff_own: Any     # bool[B, PT, B]  [j, t, i]: i matches j's aff term t
                     # (hard-affinity symmetric score, encoder K_AFF_REQ)
    # preferred (soft) terms — both directions of the IPA score:
    pref_topo_key: Any  # i32[B, PP]  topology key id of each preferred term
    pref_weight: Any    # f32[B, PP]  signed weight (+affinity / -anti)
    pref_match: Any     # bool[B, B, PP]  [j, i, t]: j matches i's pref term t
    pref_own: Any       # bool[B, PP, B]  [j, t, i]: i matches j's pref term t


jax.tree_util.register_dataclass(
    BatchAffinityState,
    data_fields=["aff_match", "anti_match", "anti_own", "aff_own",
                 "pref_topo_key", "pref_weight", "pref_match", "pref_own"],
    meta_fields=[],
)


class LeanBatchAffinity(NamedTuple):
    """Factored form of BatchAffinityState — what actually crosses the
    host->device link.

    Controller-stamped batches repeat a handful of (namespace, labels)
    shapes, so every dense [B, ., B] cross-match tensor is low-rank:
    match[owner i, term t, candidate j] = gm[i, t, group(j)].  Shipping the
    factors (G = distinct label groups, padded to a power of two; the last
    pad column is all-False and absorbs padding pods) is ~KBs where the
    dense tensors are ~40MB at batch 2048 — which matters because a
    remote-attached accelerator bills per byte moved.  densify() rebuilds
    the dense tensors ON DEVICE with one gather per family."""

    gid: Any            # i32[B]      candidate j -> label-group id
    aff_gm: Any         # bool[B, PT, G]  owner i's aff term t matches group g
    anti_gm: Any        # bool[B, AT, G]
    pref_gm: Any        # bool[B, PP, G]
    pref_topo_key: Any  # i32[B, PP]
    pref_weight: Any    # f32[B, PP]


def densify_batch_affinity(lean: LeanBatchAffinity) -> BatchAffinityState:
    """Rebuild the dense cross-match tensors from the factored form —
    called INSIDE jit so only the factors cross the link."""
    gid = lean.gid
    aff_own = jnp.take(lean.aff_gm, gid, axis=2)    # [owner i, t, cand j]
    anti_own = jnp.take(lean.anti_gm, gid, axis=2)
    pref_own = jnp.take(lean.pref_gm, gid, axis=2)
    return BatchAffinityState(
        aff_match=jnp.transpose(aff_own, (2, 0, 1)),   # [step j, i, t]
        anti_match=jnp.transpose(anti_own, (2, 0, 1)),
        anti_own=anti_own,
        aff_own=aff_own,
        pref_topo_key=lean.pref_topo_key,
        pref_weight=lean.pref_weight,
        pref_match=jnp.transpose(pref_own, (2, 0, 1)),
        pref_own=pref_own,
    )


def batch_has_pod_affinity(pods: Sequence) -> bool:
    """True if any pod carries ANY pod-(anti-)affinity terms (required or
    preferred) — the signal to run the affinity-aware scan variant so
    co-batched pods see each other in both the filter and the IPA score."""
    for p in pods:
        a = p.spec.affinity
        if a is not None and (
            a.pod_affinity is not None or a.pod_anti_affinity is not None
        ):
            return True
    return False


def encode_batch_affinity(encoder, pods: Sequence) -> LeanBatchAffinity:
    """Host-side precompute of the in-batch cross-match FACTORS (the
    engines densify on device — see LeanBatchAffinity); term slot order
    matches SnapshotEncoder._encode_pod_affinity (required[:PT] /
    required[:AT] in spec order)."""
    from kubernetes_tpu.api import labels as klabels

    d = encoder.dims
    B = encoder.batch_pad(len(pods))
    nb = len(pods)

    # Controller-stamped batches repeat a handful of (namespace, labels)
    # shapes and an equally small set of terms, so the dense owner x term x
    # candidate tensors are low-rank: group candidates by (namespace, label
    # signature), memoize each distinct (selector, namespaces) term's
    # GROUP-match vector, and ship only the factors (LeanBatchAffinity) —
    # the engines densify on device with one gather per tensor family.
    gid_of: dict = {}
    pod_gid = np.empty(max(nb, 1), np.int32)
    reps: list = []  # one (namespace, labels) representative per group
    for j, p in enumerate(pods):
        sig = (p.namespace, tuple(sorted(p.labels.items())))
        g = gid_of.get(sig)
        if g is None:
            g = gid_of[sig] = len(reps)
            reps.append((p.namespace, p.labels))
        pod_gid[j] = g
    # pad the group axis to a power of two; the LAST column stays all-False
    # in every gm tensor and absorbs batch-padding pods, so they can never
    # match a term
    G = _pow2(len(reps) + 1)
    gid = np.full(B, G - 1, np.int32)
    if nb:
        gid[:nb] = pod_gid[:nb]
    _match_memo: dict = {}

    def _term_gvec(term, owner_ns):
        """bool[G] group-match vector for one term, memoized across the
        batch by (requirements, namespaces)."""
        sel = klabels.selector_from_label_selector(term.label_selector)
        if sel is None:
            return None
        nss = term.namespaces or (owner_ns,)
        key = (tuple(sel.requirements), frozenset(nss))
        vec = _match_memo.get(key)
        if vec is None:
            vec = np.zeros(G, bool)
            vec[: len(reps)] = np.fromiter(
                ((ns in nss) and sel.matches(lbls) for ns, lbls in reps),
                bool, count=len(reps),
            )
            vec.setflags(write=False)  # rows are shared across owners
            _match_memo[key] = vec
        return vec

    A = np.zeros((B, d.PT, G), bool)   # [owner i, term t, group g]
    N = np.zeros((B, d.AT, G), bool)

    def _fill(out, terms, i, owner, slot=None):
        for t, term in enumerate(terms):
            vec = _term_gvec(term, owner.namespace)
            if vec is None:
                continue
            out[i, slot if slot is not None else t, :] = vec

    # preferred terms: owner-major lists (signed weights), then the same
    # cross-match fill as required terms
    pref_lists = []
    for pod in pods:
        terms = []
        a = pod.spec.affinity
        if a is not None:
            if a.pod_affinity is not None:
                terms += [(+float(w.weight), w.term)
                          for w in a.pod_affinity.preferred]
            if a.pod_anti_affinity is not None:
                terms += [(-float(w.weight), w.term)
                          for w in a.pod_anti_affinity.preferred]
        pref_lists.append(terms)
    PP = _pow2(max([len(t) for t in pref_lists] + [1]))
    P = np.zeros((B, PP, G), bool)       # [owner i, term t, group g]
    p_key = np.zeros((B, PP), np.int32)
    p_w = np.zeros((B, PP), np.float32)

    for i, pod in enumerate(pods):
        a = pod.spec.affinity
        if a is None:
            continue
        if a.pod_affinity is not None:
            _fill(A, a.pod_affinity.required[: d.PT], i, pod)
        if a.pod_anti_affinity is not None:
            _fill(N, a.pod_anti_affinity.required[: d.AT], i, pod)
        for t, (w, term) in enumerate(pref_lists[i][:PP]):
            p_w[i, t] = w
            p_key[i, t] = encoder.register_topology_key(term.topology_key)
            _fill(P, [term], i, pod, slot=t)
    return LeanBatchAffinity(
        gid=gid, aff_gm=A, anti_gm=N, pref_gm=P,
        pref_topo_key=p_key, pref_weight=p_w,
    )


def encode_nominated(encoder, nominated_pairs, k_min: int = 8):
    """Host helper: (pod, node_name) pairs -> NominatedState (power-of-two
    padded), or None when empty."""
    pairs = [
        (p, encoder.node_rows.get(n, -1)) for p, n in nominated_pairs
    ]
    pairs = [(p, r) for p, r in pairs if r >= 0]
    if not pairs:
        return None
    K = _pow2(len(pairs), k_min)
    node = np.full(K, -1, np.int32)
    prio = np.zeros(K, np.int32)
    req = np.zeros((K, encoder.dims.R), np.float32)
    for i, (p, r) in enumerate(pairs):
        node[i] = r
        prio[i] = p.spec.priority
        v = encoder._req_vector(p.resource_request())
        req[i, : v.shape[0]] = v
    return NominatedState(node=node, prio=prio, req=req)


def encode_nominated_block(encoder, nominated_pairs, pods: Sequence,
                           n_pods: int, n_nodes: int):
    """Host precompute of the pass-one effects nominated pods have BEYOND
    resources: host-port claims and required anti-affinity (both
    directions) — closing the NominatedState parity gap (VERDICT r2).

    Returns bool[n_pods, n_nodes] with True = infeasible in pass one, or
    None when no nominated pod contributes.  Folded into the engines'
    extra_mask, so both engines see it without new device plumbing.

    Required AFFINITY that only a nominated pod satisfies needs no
    tensor: podFitsOnNode's second pass (WITHOUT nominated pods,
    generic_scheduler.go:598-664) must also succeed, so a nominated pod
    can never flip an affinity-infeasible node feasible.  What CAN flip
    feasible->infeasible — port conflicts and anti-affinity — is exactly
    what this mask carries."""
    from kubernetes_tpu.cpuref.reference import _term_matches_pod

    pairs = [
        (p, encoder.node_rows.get(n, -1)) for p, n in nominated_pairs
    ]
    pairs = [(p, r) for p, r in pairs if 0 <= r < n_nodes]
    if not pairs:
        return None

    def anti_terms(pod):
        aff = pod.spec.affinity
        if aff is None or aff.pod_anti_affinity is None:
            return ()
        return aff.pod_anti_affinity.required

    # rows sharing a topology (key, value) — the domain an anti term blocks
    def rows_in_domain(key: str, value):
        if value is None:
            return []
        return [
            row for row, node in encoder._row_node.items()
            if row < n_nodes and node.labels.get(key) == value
        ]

    block = np.zeros((n_pods, n_nodes), bool)
    any_block = False
    domain_cache: dict = {}

    def domain_rows(key: str, value):
        # hoisted per (key, value): the blocked rows depend only on the
        # nominated pod's node + term key, not on the batch pod
        if value is None:
            return []
        ck = (key, value)
        if ck not in domain_cache:
            domain_cache[ck] = rows_in_domain(key, value)
        return domain_cache[ck]

    for k_pod, r in pairs:
        k_node = encoder._row_node.get(r)
        if k_node is None:
            continue
        k_prio = k_pod.spec.priority
        k_ports = list(encoder._pod_ports(k_pod))
        k_anti = anti_terms(k_pod)
        for b, pod in enumerate(pods):
            if b >= n_pods:
                break
            if k_prio < pod.spec.priority:
                continue  # only >=-priority nominated pods join pass one
            # host-port claim on the nominated node (host_ports.go
            # CheckConflict: same port and same-or-wildcard IP)
            for pp1, ip1 in encoder._pod_ports(pod):
                if any(pp1 == pp2 and (ip1 == ip2 or ip1 == 0 or ip2 == 0)
                       for pp2, ip2 in k_ports):
                    block[b, r] = True
                    any_block = True
                    break
            # nominated pod's anti terms reject this pod across the domain
            for t in k_anti:
                if _term_matches_pod(t, k_pod, pod):
                    for row in domain_rows(
                            t.topology_key, k_node.labels.get(t.topology_key)):
                        block[b, row] = True
                        any_block = True
            # this pod's anti terms reject nodes whose domain now holds
            # a matching nominated pod
            for t in anti_terms(pod):
                if _term_matches_pod(t, pod, k_pod):
                    for row in domain_rows(
                            t.topology_key, k_node.labels.get(t.topology_key)):
                        block[b, row] = True
                        any_block = True
    return block if any_block else None


def encode_batch_ports(encoder, pods: Sequence) -> BatchPortState:
    """Host-side precompute of the batch port vocabulary.

    Conflict semantics mirror nodeinfo/host_ports.go CheckConflict:
    same protocol+port and (same IP or either wildcard)."""
    vocab = {}
    plist = []
    for pod in pods:
        for pp, ip in encoder._pod_ports(pod):
            if (pp, ip) not in vocab:
                vocab[(pp, ip)] = len(plist)
                plist.append((pp, ip))
    PV = _pow2(max(len(plist), 1))
    B = encoder.batch_pad(len(pods))
    pod_ports = np.zeros((B, PV), bool)
    for b, pod in enumerate(pods):
        for pp, ip in encoder._pod_ports(pod):
            pod_ports[b, vocab[(pp, ip)]] = True
    conflict = np.zeros((PV, PV), bool)
    for i, (pp1, ip1) in enumerate(plist):
        for j, (pp2, ip2) in enumerate(plist):
            conflict[i, j] = pp1 == pp2 and (ip1 == ip2 or ip1 == 0 or ip2 == 0)
    # NB: conflicts vs EXISTING node occupancy are the static
    # PodFitsHostPorts predicate's job; only in-batch claims live here
    return BatchPortState(pod_ports=pod_ports, conflict=conflict)


def _dynamic_scores(cluster, req_cpu_mem, requested2, zone_key_id, counts,
                    rtc_xs, rtc_ys):
    """The state-dependent priorities, recomputed per scan step from the
    shared scoring cores in ops/priorities.py.

    req_cpu_mem: f32[2] nonzero request of the current pod;
    requested2: f32[N, 2] current nonzero usage;
    counts: f32[N] pods matching ALL the pod's spread selectors per node
    (pre-batch base + in-batch commits)."""
    cap = node_capacity2(cluster)                            # [N, 2]
    req = requested2 + req_cpu_mem[None, :]
    least = least_requested_score(req, cap)                  # [N]
    most = most_requested_score(req, cap)
    balanced = balanced_allocation_score(req, cap)
    spread = spread_score_from_counts(counts, cluster, zone_key_id)
    util = jnp.where(cap > 0, req * 100.0 / jnp.maximum(cap, 1e-30), 100.0)
    rtc = jnp.floor(jnp.sum(jnp.interp(util, rtc_xs, rtc_ys), axis=-1) / 2.0)
    return least, most, balanced, spread, rtc


def _replicated_on_cluster_mesh(cluster):
    # lives in parallel/mesh.py with the rest of the mesh placement
    # logic; lazy import keeps this module importable without jax.sharding
    from kubernetes_tpu.parallel.mesh import replicated_on_cluster_mesh

    return replicated_on_cluster_mesh(cluster)


from collections import OrderedDict

_SEQ_CACHE: "OrderedDict" = OrderedDict()
_SEQ_CACHE_CAP = 32  # bounds pinned executables (autoscaler what-if scale)


def make_sequential_scheduler(
    cfg: FilterConfig = FilterConfig(),
    weights=None,
    unsched_taint_key: int = 0,
    zone_key_id: int = 5,
    score_cfg: Optional[ScoreConfig] = None,
    percentage_of_nodes_to_score: int = 100,
    donate_cluster: bool = False,
    attribution: bool = False,
    attribution_topk: int = 3,
    quality_topk: int = 0,
):
    """Build (or fetch the memoized) jitted sequential-commit scheduler.

    Returns fn(cluster, pods, ports: BatchPortState, last_index0) ->
      (hosts i32[B] (-1 = unschedulable), new_cluster) where new_cluster has
      the committed requested/nonzero columns.

    With attribution=True (a STATIC flag: a separate executable, the
    default one unchanged) the launch additionally returns an Attribution
    pytree — per-pod first-failing-predicate node counts plus a top-k
    per-plugin score breakdown — computed inside the same scan against
    the exact per-step state, so winners are bit-identical either way
    (pinned by tests/test_ledger.py).

    With quality_topk=K > 0 (another STATIC output-only flag — the
    placement-quality observatory, runtime/quality.py) the launch ALSO
    returns an ops/select.TopKQuality pytree: per pod, the K best
    feasible node rows with the winner pinned at column 0, their total
    scores, and the feasible-candidate count the selector argmaxed
    over — all read off the same per-step (mask, total, host) the
    placement used, so winners stay bit-identical flag-on/off (pinned
    by tests/test_quality.py).  Output order when both flags are on:
    (hosts, new_cluster, Attribution, TopKQuality).

    Buffer donation (accelerator backends only; XLA:CPU has no donation):
    the PER-BATCH argument buffers — pods/ports/nominated/extra mask+score/
    affinity state, freshly device_put by schedule_entry every call — are
    donated, so XLA reuses their HBM for scan carries and outputs instead
    of holding both live across the launch.  `donate_cluster=True`
    additionally donates the cluster argument itself: the returned
    new_cluster then updates requested/nonzero IN PLACE (the static leaves
    alias straight through), which is correct ONLY for callers that chain
    the returned state and never reuse the input (bench.py's raw loop) —
    the live Scheduler keeps its snapshot resident in DeviceSnapshotCache
    across cycles and must NOT donate it."""
    if score_cfg is None:
        score_cfg = ScoreConfig()
    donate_batch = jax.default_backend() != "cpu"
    key = (
        cfg,
        tuple(np.asarray(weights, np.float32)) if weights is not None else None,
        unsched_taint_key,
        zone_key_id,
        score_cfg,
        percentage_of_nodes_to_score,
        donate_cluster and donate_batch,
        attribution,
        attribution_topk,
        quality_topk,
    )
    hit = _SEQ_CACHE.get(key)
    if hit is not None:
        _SEQ_CACHE.move_to_end(key)
        return hit
    w = np.asarray(
        DEFAULT_PRIORITY_WEIGHTS if weights is None else weights, np.float32
    )
    w_least = float(w[PRIO_INDEX["LeastRequestedPriority"]])
    w_most = float(w[PRIO_INDEX["MostRequestedPriority"]])
    w_bal = float(w[PRIO_INDEX["BalancedResourceAllocation"]])
    w_spread = float(w[PRIO_INDEX["SelectorSpreadPriority"]])
    w_rtc = float(w[PRIO_INDEX["RequestedToCapacityRatioPriority"]])
    rtc_xs = np.asarray([p[0] for p in score_cfg.rtc_shape], np.float32)
    rtc_ys = np.asarray([p[1] for p in score_cfg.rtc_shape], np.float32)

    def schedule_impl(cluster: ClusterTensors, pods: PodBatch, ports: BatchPortState,
                      last_index0: jnp.ndarray, nominated: Optional[NominatedState] = None,
                      extra_mask=None, extra_score=None,
                      aff_state: Optional[BatchAffinityState] = None):
        """extra_mask bool[B, N] / extra_score f32[B, N]: the framework's
        tensor-level Filter/Score plugin outputs, folded into the static
        pass (one launch total — the TPU-shaped plugin seam).

        aff_state: in-batch affinity cross-matches; when given,
        MatchInterPodAffinity moves from the static pass into the scan with
        carried per-topology-pair extras, so co-batched pods see each
        other's placements (kills the batch>1 affinity-blindness gap)."""
        if isinstance(aff_state, LeanBatchAffinity):
            aff_state = densify_batch_affinity(aff_state)  # on device
        B = pods.n_pods
        G = cluster.group_counts.shape[1]
        # ---- static pass: every predicate except the dynamic ones, plus the
        # static score components, in one batched launch
        mask_static, per_pred = filter_batch(cluster, pods, cfg, unsched_taint_key)
        # static mask must EXCLUDE resources (recomputed in-scan); keep the
        # initial ports check (vs pre-batch occupancy) — in-scan adds claims.
        from kubernetes_tpu.codec.schema import PRED_INDEX

        res_idx = PRED_INDEX["PodFitsResources"]
        gen_idx = PRED_INDEX["GeneralPredicates"]
        non_resource = jnp.ones((per_pred.shape[1],), bool)
        non_resource = non_resource.at[res_idx].set(False)
        non_resource = non_resource.at[gen_idx].set(False)
        if aff_state is not None:
            # affinity is re-evaluated per step against (static | in-batch)
            # pair state instead of statically
            non_resource = non_resource.at[PRED_INDEX["MatchInterPodAffinity"]].set(
                False
            )
        static_mask = jnp.all(per_pred | ~non_resource[None, :, None], axis=1)
        # GeneralPredicates minus resources = host+ports+selector
        host_idx = PRED_INDEX["PodFitsHost"]
        ports_idx = PRED_INDEX["PodFitsHostPorts"]
        sel_idx = PRED_INDEX["PodMatchNodeSelector"]
        static_mask = (
            static_mask
            & per_pred[:, host_idx]
            & per_pred[:, ports_idx]
            & per_pred[:, sel_idx]
            & cluster.valid[None]
            & pods.valid[:, None]
        )
        if extra_mask is not None:
            static_mask = static_mask & extra_mask
        # static score components (state-independent priorities); with
        # in-batch affinity the IPA score moves into the scan (its raw pair
        # weights gain in-batch contributions and must renormalize)
        static_score = (
            (
                0.0
                if aff_state is not None
                else w[PRIO_INDEX["InterPodAffinityPriority"]]
                * inter_pod_affinity_score(cluster, pods)
            )
            + w[PRIO_INDEX["NodePreferAvoidPodsPriority"]] * node_prefer_avoid_pods(cluster, pods)
            + w[PRIO_INDEX["NodeAffinityPriority"]] * node_affinity(cluster, pods)
            + w[PRIO_INDEX["TaintTolerationPriority"]] * taint_toleration(cluster, pods)
            + w[PRIO_INDEX["ImageLocalityPriority"]] * image_locality(cluster, pods)
        )
        if w[PRIO_INDEX["NodeLabelPriority"]]:
            static_score = static_score + w[PRIO_INDEX["NodeLabelPriority"]] * node_label_priority(
                cluster, pods, score_cfg
            )
        if w[PRIO_INDEX["ResourceLimitsPriority"]]:
            static_score = static_score + w[PRIO_INDEX["ResourceLimitsPriority"]] * resource_limits(
                cluster, pods
            )
        if extra_score is not None:
            static_score = static_score + extra_score
        if attribution:
            # per-plugin attribution inputs (static flag: the default
            # executable never materializes these): the per-predicate
            # stack (already computed above) and the weighted static
            # score components — threaded through the scan so the
            # per-step slices see the SAME state the placement math does
            from kubernetes_tpu.ops.priorities import static_score_components

            comp_static = static_score_components(
                cluster, pods, w, score_cfg,
                include_ipa=(aff_state is None), extra_score=extra_score,
            )
            tk = min(attribution_topk, cluster.n_nodes)
        else:
            comp_static = None
        # quality top-k width: static, clamped to the arena (a 2-node
        # toy cluster cannot rank 3 rows)
        tkq = min(quality_topk, cluster.n_nodes) if quality_topk else 0
        feas_limit = (
            num_feasible_nodes_device(
                jnp.sum(cluster.valid.astype(jnp.int32)),
                percentage_of_nodes_to_score,
            )
            if percentage_of_nodes_to_score < 100  # 0 = adaptive
            else None
        )
        # in-batch spread cross-matches (countMatchingPods AND semantics);
        # shared helper so the speculative engine's bookkeeping is
        # guaranteed identical
        spread_match = pod_spread_match(pods, G)              # [B, B] [i, j]

        topo = cluster.topo_pairs.astype(jnp.float32)         # [N, TP]
        TP = topo.shape[1]
        if aff_state is not None:
            aff_key_pairs = (
                pods.aff_term_topo_key[:, :, None] == cluster.pair_topo_key[None, None]
            )                                                 # [B, PT, TP]
            anti_key_pairs = (
                pods.anti_term_topo_key[:, :, None] == cluster.pair_topo_key[None, None]
            )                                                 # [B, AT, TP]
            pref_key_pairs = (
                aff_state.pref_topo_key[:, :, None]
                == cluster.pair_topo_key[None, None]
            )                                                 # [B, PP, TP]
            pref_w_all = aff_state.pref_weight                # [B, PP]

        w_ipa = float(w[PRIO_INDEX["InterPodAffinityPriority"]])
        hard_w = float(cfg.hard_pod_affinity_weight)

        def step(state, xs):
            (requested, nonzero2, spread_extra, port_used, last_idx,
             extra_aff, extra_anti, extra_forb, extra_pref) = state
            (smask, sscore, req, nz2, spread_base, pprio, pport, step_no,
             aff_xs, attr_xs) = xs
            # dynamic resource fit (PodFitsResources on current state)
            fit = ~jnp.any(
                (req[None, :] > 0)
                & (requested + req[None, :] > cluster.allocatable),
                axis=-1,
            )
            if nominated is not None:
                # two-pass nominated evaluation (podFitsOnNode,
                # generic_scheduler.go:598-664): pass one adds nominated pods
                # with priority >= this pod's to their nominated node; the
                # no-nominated pass is `fit` itself (resource fit is monotone,
                # so pass one implies pass two here)
                w = (
                    (nominated.prio >= pprio) & (nominated.node >= 0)
                ).astype(jnp.float32)                         # [K]
                onehot_nom = (
                    nominated.node[:, None]
                    == jnp.arange(requested.shape[0])[None, :]
                ).astype(jnp.float32)                         # [K, N]
                extra = jnp.einsum(
                    "k,kn,kr->nr", w, onehot_nom, nominated.req
                )                                             # [N, R]
                fit_nom = ~jnp.any(
                    (req[None, :] > 0)
                    & (requested + extra + req[None, :] > cluster.allocatable),
                    axis=-1,
                )
                fit = fit & fit_nom
            # in-batch port conflicts: used claims x conflict matrix
            claimed_conflict = (port_used.astype(jnp.float32) @ ports.conflict.astype(jnp.float32)) > 0
            port_bad = jnp.any(pport[None, :] & claimed_conflict, axis=-1)
            mask = smask & fit & ~port_bad
            if aff_state is not None:
                # MatchInterPodAffinity against (pre-batch | in-batch) state
                (aff_pairs_j, aff_valid_j, aff_self_j, aff_key_j,
                 anti_pairs_j, anti_valid_j, anti_key_j, forb_j,
                 pref_w_j, aff_match_j, anti_match_j, anti_own_j,
                 aff_own_j, prefm_j, pref_own_j, pref_wt_j,
                 pref_key_j) = aff_xs
                aff_pairs = aff_pairs_j | extra_aff[step_no]       # [PT, TP]
                aff_hit = (aff_pairs.astype(jnp.float32) @ topo.T) > 0   # [PT, N]
                any_match = jnp.any(aff_pairs, axis=-1)            # [PT]
                node_has_key = (aff_key_j.astype(jnp.float32) @ topo.T) > 0
                bootstrap = ~any_match[:, None] & aff_self_j[:, None] & node_has_key
                term_ok = aff_hit | bootstrap | ~aff_valid_j[:, None]
                aff_ok = jnp.all(term_ok, axis=0)                  # [N]
                anti_pairs = anti_pairs_j | extra_anti[step_no]
                anti_hit = (anti_pairs.astype(jnp.float32) @ topo.T) > 0
                viol2 = jnp.any(anti_hit & anti_valid_j[:, None], axis=0)
                forb = forb_j | extra_forb[step_no]
                viol1 = (forb.astype(jnp.float32) @ topo.T) > 0    # [N]
                mask = mask & aff_ok & ~viol1 & ~viol2
            least, most, balanced, spread, rtc = _dynamic_scores(
                cluster, nz2, nonzero2, zone_key_id,
                spread_base + spread_extra[step_no], rtc_xs, rtc_ys,
            )
            total = (
                sscore
                + w_least * least
                + w_most * most
                + w_bal * balanced
                + w_spread * spread
                + w_rtc * rtc
            )
            if aff_state is not None:
                # IPA score over (pre-batch | in-batch) raw pair weights,
                # renormalized per step (interpod_affinity.go fScore)
                raw = (pref_w_j + extra_pref[step_no]) @ topo.T    # [N]
                big = jnp.float32(3.4e38)
                mn = jnp.min(jnp.where(cluster.valid, raw, big))
                mx = jnp.max(jnp.where(cluster.valid, raw, -big))
                spread_r = mx - mn
                ipa = jnp.where(
                    spread_r > 0,
                    jnp.floor(MAX_PRIORITY * (raw - mn) / spread_r),
                    0.0,
                )
                ipa_term = w_ipa * jnp.where(cluster.valid, ipa, 0.0)
                total = total + ipa_term
            if attribution:
                pp_j, comp_j = attr_xs
                # re-point the dynamic predicates at their IN-SCAN
                # verdicts so the first-failure attribution matches what
                # the placement mask actually saw at this step
                ports_ok = pp_j[ports_idx] & ~port_bad
                rows = pp_j.at[res_idx].set(fit)
                rows = rows.at[ports_idx].set(ports_ok)
                # the aggregate row never attributes — its constituents
                # (host/ports/selector/resources) name the precise reason
                rows = rows.at[gen_idx].set(True)
                if aff_state is not None:
                    rows = rows.at[
                        PRED_INDEX["MatchInterPodAffinity"]
                    ].set(aff_ok & ~viol1 & ~viol2)
                failed = ~rows                                  # [K, N]
                ff = jnp.argmax(failed, axis=0)
                any_fail = jnp.any(failed, axis=0)
                rejected = ~mask & cluster.valid
                reason = jnp.where(
                    rejected,
                    jnp.where(any_fail, ff, REASON_EXTENDER),
                    NUM_REASONS,            # feasible (never counted)
                )
                counts = jnp.sum(
                    reason[:, None] == jnp.arange(NUM_REASONS)[None, :],
                    axis=0, dtype=jnp.int32,
                )                                               # [NUM_REASONS]
                comp_full = comp_j                              # [C, N]
                comp_full = comp_full.at[
                    PRIO_INDEX["LeastRequestedPriority"]].set(w_least * least)
                comp_full = comp_full.at[
                    PRIO_INDEX["MostRequestedPriority"]].set(w_most * most)
                comp_full = comp_full.at[
                    PRIO_INDEX["BalancedResourceAllocation"]].set(
                        w_bal * balanced)
                comp_full = comp_full.at[
                    PRIO_INDEX["SelectorSpreadPriority"]].set(
                        w_spread * spread)
                comp_full = comp_full.at[
                    PRIO_INDEX["RequestedToCapacityRatioPriority"]].set(
                        w_rtc * rtc)
                if aff_state is not None:
                    comp_full = comp_full.at[
                        PRIO_INDEX["InterPodAffinityPriority"]].set(ipa_term)
                neg = jnp.float32(-3.4e38)
                top_vals, top_idx = jax.lax.top_k(
                    jnp.where(mask, total, neg), tk
                )
                top_comp = jnp.transpose(comp_full[:, top_idx])  # [TK, C]
                attr_out = (
                    counts,
                    jnp.where(top_vals > neg / 2, top_idx, -1).astype(
                        jnp.int32),
                    top_vals,
                    top_comp,
                )
            else:
                attr_out = None
            if percentage_of_nodes_to_score < 100:  # 0 = adaptive
                # adaptive node sampling (numFeasibleNodesToFind) with the
                # reference's rotating start offset
                mask = limit_feasible(mask, feas_limit, last_idx)
            host, feasible = select_host(total, mask, last_idx)
            # quality top-k (static output-only flag): the winner-pinned
            # ranking + feasible count off the exact (mask, total, host)
            # the selection above used — including the adaptive-sampling
            # cut, so "feasible" means candidates actually considered
            qual_out = (
                select_topk(total, mask, host, feasible, tkq)
                if tkq else None
            )
            # commit
            commit = feasible
            onehot = (jnp.arange(requested.shape[0]) == host) & commit  # [N]
            requested = requested + onehot[:, None] * req[None, :]
            nonzero2 = nonzero2 + onehot[:, None] * nz2[None, :]
            # later pods whose selector set this pod covers see it at its node
            spread_extra = spread_extra + (
                spread_match[:, step_no][:, None] * onehot[None, :]
            )
            port_used = port_used | (onehot[:, None] & pport[None, :])
            if aff_state is not None:
                # predicateMetadata.AddPod analog: the committed pod's
                # topology pairs flow into later pods' affinity state
                node_pairs = (onehot.astype(jnp.float32) @ topo) > 0   # [TP]
                extra_aff = extra_aff | (
                    aff_match_j[:, :, None] & aff_key_pairs & node_pairs[None, None]
                )
                extra_anti = extra_anti | (
                    anti_match_j[:, :, None] & anti_key_pairs & node_pairs[None, None]
                )
                forb_contrib = jnp.einsum(
                    "tb,tp->bp",
                    anti_own_j.astype(jnp.float32),
                    (anti_key_j & node_pairs[None]).astype(jnp.float32),
                ) > 0
                extra_forb = extra_forb | forb_contrib
                # hard-affinity symmetry: the committed pod's required
                # affinity terms add hard_w per matching later pod per pair
                # (encoder K_AFF_REQ group semantics)
                extra_pref = extra_pref + hard_w * jnp.einsum(
                    "tb,tp->bp",
                    aff_own_j.astype(jnp.float32),
                    (aff_key_j & node_pairs[None]).astype(jnp.float32),
                )
                # preferred (soft) terms, both directions:
                # 1. LATER pods' own preferred terms the committed pod
                #    matches gain +-w at the committed node's domain
                kp = (
                    pref_key_pairs & node_pairs[None, None]
                ).astype(jnp.float32)                         # [B, PP, TP]
                extra_pref = extra_pref + jnp.einsum(
                    "it,itp->ip",
                    prefm_j.astype(jnp.float32) * pref_w_all, kp,
                )
                # 2. the committed pod's preferred terms add +-w_j for each
                #    later pod they match (existing-pod K_AFF_PREF/K_ANTI_PREF
                #    group semantics)
                extra_pref = extra_pref + jnp.einsum(
                    "ti,t,tp->ip",
                    pref_own_j.astype(jnp.float32),
                    pref_wt_j,
                    (pref_key_j & node_pairs[None]).astype(jnp.float32),
                )
            out_host = jnp.where(feasible, host, -1)
            return (
                (requested, nonzero2, spread_extra, port_used, last_idx + 1,
                 extra_aff, extra_anti, extra_forb, extra_pref),
                (out_host, attr_out, qual_out),
            )

        PV = ports.pod_ports.shape[1]
        PT = pods.aff_term_pairs.shape[1]
        AT = pods.anti_term_pairs.shape[1]
        if aff_state is not None:
            extras_init = (
                jnp.zeros((B, PT, TP), bool),
                jnp.zeros((B, AT, TP), bool),
                jnp.zeros((B, TP), bool),
                jnp.zeros((B, TP), jnp.float32),
            )
        else:  # unused: scalar placeholders keep the carry structure cheap
            extras_init = tuple(jnp.zeros(()) for _ in range(4))
        init = (
            cluster.requested,
            cluster.nonzero_req,
            jnp.zeros((B, cluster.n_nodes), jnp.float32),
            jnp.zeros((cluster.n_nodes, PV), bool),
            last_index0.astype(jnp.int32),
        ) + extras_init
        if aff_state is not None:
            aff_xs_in = (
                pods.aff_term_pairs,
                pods.aff_term_valid,
                pods.aff_term_self,
                aff_key_pairs,
                pods.anti_term_pairs,
                pods.anti_term_valid,
                anti_key_pairs,
                pods.forbidden_pairs,
                pods.pref_pair_weights,
                aff_state.aff_match,
                aff_state.anti_match,
                aff_state.anti_own,
                aff_state.aff_own,
                aff_state.pref_match,
                aff_state.pref_own,
                aff_state.pref_weight,
                pref_key_pairs,
            )
        else:
            aff_xs_in = None
        xs = (
            static_mask,
            static_score,
            pods.req,
            pods.nonzero_req,
            # device-derived for spread-lean batches (no [B, N] upload)
            spread_counts(cluster, pods),
            pods.priority,
            ports.pod_ports,
            jnp.arange(B, dtype=jnp.int32),
            aff_xs_in,
            # extra-mask vetoes need no tensor here: a node rejected with
            # every predicate passing can ONLY be an extra-mask veto
            (per_pred, comp_static) if attribution else None,
        )
        (requested, nonzero2, *_), (hosts, attr_ys, qual_ys) = jax.lax.scan(
            step, init, xs
        )
        import dataclasses as _dc

        new_cluster = _dc.replace(
            cluster,
            requested=requested,
            nonzero_req=nonzero2,
        )
        outs = (hosts, new_cluster)
        if attribution:
            outs = outs + (Attribution(*attr_ys),)
        if tkq:
            outs = outs + (TopKQuality(*qual_ys),)
        return outs

    # donation (see the maker docstring): batch buffers always on
    # accelerator backends, the cluster only for chained-state callers.
    # XLA:CPU implements no donation — plain jit there keeps warning
    # noise out of the tier-1 suite.
    donate: Tuple[int, ...] = ()
    if donate_batch:
        # argnums: 1=pods 2=ports 4=nominated 5=extra_mask 6=extra_score
        # 7=aff_state (3=last_index0 is a scalar, nothing to donate)
        donate = (1, 2, 4, 5, 6, 7)
        if donate_cluster:
            donate = (0,) + donate
    schedule = jax.jit(schedule_impl, donate_argnums=donate)

    def schedule_entry(cluster, pods, ports, last_index0, nominated=None,
                       extra_mask=None, extra_score=None, aff_state=None):
        """Host entry: on accelerator backends, move the batch pytrees to
        the device via explicit device_put first — host-numpy jit ARGUMENTS
        cross a remote-attached tunnel on a slow synchronous path (~55MB/s
        measured vs ~1.4GB/s async DMA), which matters for the [B, ., B]
        affinity cross-match tensors.  device_put is a no-op passthrough
        for leaves already on the device.  The freshly-transferred batch
        buffers are DONATED into the launch (dead after it by
        construction: every call re-transfers).  A mesh-sharded cluster
        (the multi-chip live path) pins the computation to its mesh, so
        the batch buffers replicate over the SAME devices — a plain
        device_put would commit them to device 0 and conflict."""
        if jax.default_backend() != "cpu":
            tree = (pods, ports, nominated, extra_mask, extra_score,
                    aff_state)
            transfer.note_transfer_tree("h2d", "batch_replicate", tree)
            dst = _replicated_on_cluster_mesh(cluster)
            pods, ports, nominated, extra_mask, extra_score, aff_state = (
                jax.device_put(tree, dst)
                if dst is not None else jax.device_put(tree)
            )
        return schedule(cluster, pods, ports, last_index0, nominated,
                        extra_mask, extra_score, aff_state)

    # the raw traceable fn for callers composing INSIDE jit (the
    # speculative engine's in-program lax.cond redo): the UNJITTED impl —
    # it inlines into the outer trace, where donation has no meaning
    schedule_entry.jitted = schedule_impl
    # engine identity tag: consumers whose correctness depends on the
    # strictly sequential one-at-a-time commit order (models/gang.py's
    # cross-gang required-affinity drop guard) assert on this
    schedule_entry.engine_kind = "sequential"
    # attribution variants return (hosts, new_cluster, Attribution);
    # callers handling either arity key off this
    schedule_entry.attribution = attribution
    # quality variants append a TopKQuality as the LAST output (after
    # Attribution when both flags are on); 0 = off
    schedule_entry.quality_topk = quality_topk

    _SEQ_CACHE[key] = schedule_entry
    while len(_SEQ_CACHE) > _SEQ_CACHE_CAP:
        _SEQ_CACHE.popitem(last=False)
    return schedule_entry
