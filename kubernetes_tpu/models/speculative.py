"""Speculative parallel placement: the high-throughput engine.

The sequential-commit scan (models/batched.py) reproduces one-pod-at-a-time
semantics exactly, but a `lax.scan` step is latency-bound (~ms on TPU), so B
pods cost B sequential steps.  This engine instead places the WHOLE batch in
one fully-parallel launch (filter + score over the pods x nodes grid — all
MXU work), then resolves conflicts host-side:

  round r:
    1. one launch: mask/score every remaining pod against the current
       cluster state, argmax with per-pod staggered tie-break
       (ops/select.select_hosts_batch — identical pods rotate across tied
       nodes, so collisions are rare by construction);
    2. host commit, in batch order: accept a pod iff its node still has
       capacity AND no host-port conflict with pods committed this cycle;
       rejected pods get extra_mask[b, node] = False (guaranteed progress:
       a pod never re-picks a node it was bounced from) and go to round r+1
       against the updated resource columns.

Every PREDICATE is enforced (device mask + host commit re-check); what
differs from the sequential scan is in-batch score freshness: same-round
pods don't see each other in the spreading/balance scores (they do between
rounds).  Workloads carrying required (anti-)affinity should use the
sequential scan (the scheduler's auto mode does), since in-batch affinity
state lives there.

Typical convergence: round 1 commits ~all pods (staggered ties), so the cost
is ~1 parallel launch per batch instead of B scan steps — the path to the
>=10k pods/s north star (BASELINE.json).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.codec.schema import (
    ClusterTensors,
    FilterConfig,
    PAD,
    PodBatch,
    WILDCARD,
)
from kubernetes_tpu.models.generic import schedule_batch_independent

MAX_ROUNDS = 16


def _ports_of(pods: PodBatch, b: int):
    """[(proto_port_id, ip_id)] requested by batch pod b (host-side)."""
    pp = np.asarray(pods.port_pp[b])
    ip = np.asarray(pods.port_ip[b])
    ok = np.asarray(pods.port_valid[b])
    return [(int(p), int(i)) for p, i, v in zip(pp, ip, ok) if v]


def _port_conflict(claimed, want) -> bool:
    """Wildcard-IP host-port semantics (nodeinfo/host_ports.go)."""
    for cp, ci in claimed:
        for wp, wi in want:
            if cp == wp and (ci == wi or ci == WILDCARD or wi == WILDCARD):
                return True
    return False


def make_speculative_scheduler(
    cfg: FilterConfig = FilterConfig(),
    weights=None,
    unsched_taint_key: int = 0,
    zone_key_id: int = 5,
    score_cfg=None,
):
    """Same call contract as make_sequential_scheduler:
    fn(cluster, pods, ports, last_index0, extra_mask=None, extra_score=None)
    -> (hosts i32[B] (-1 unschedulable), new_cluster with committed
    requested/nonzero columns)."""

    @jax.jit
    def one_round(cluster, pods, requested, nonzero, active, last_index0,
                  extra_mask, extra_score):
        cl = dataclasses.replace(
            cluster, requested=requested, nonzero_req=nonzero
        )
        out = schedule_batch_independent(
            cl, pods, 0, cfg, unsched_taint_key, zone_key_id
        )
        mask = out["mask"] & active[:, None] & extra_mask
        total = out["scores"] + extra_score
        from kubernetes_tpu.ops.select import select_hosts_batch

        hosts, feasible = select_hosts_batch(total, mask, last_index0)
        return hosts, feasible & jnp.any(mask, axis=1)

    def schedule(cluster: ClusterTensors, pods: PodBatch, ports,
                 last_index0, nominated=None, extra_mask=None,
                 extra_score=None, aff_state=None):
        B = pods.n_pods
        N = cluster.n_nodes
        assert aff_state is None and nominated is None, (
            "speculative engine handles the plain fast path; affinity/"
            "nominated batches take the sequential scan"
        )
        # host mirrors for the commit checks / inter-round updates
        req_host = np.array(cluster.requested, np.float32)
        nz_host = np.array(cluster.nonzero_req, np.float32)
        alloc = np.asarray(cluster.allocatable)
        pod_req = np.asarray(pods.req)
        pod_nz = np.asarray(pods.nonzero_req)
        valid = np.asarray(pods.valid)

        emask = (
            np.ones((B, N), bool) if extra_mask is None
            else np.array(extra_mask, bool)
        )
        escore = (
            np.zeros((B, N), np.float32) if extra_score is None
            else np.asarray(extra_score, np.float32)
        )
        hosts_out = np.full(B, -1, np.int32)
        active = valid.copy()
        claimed_ports: dict = {}
        li = int(last_index0)

        rounds = 0
        while active.any() and rounds < MAX_ROUNDS:
            rounds += 1
            hosts, feasible = one_round(
                cluster, pods, req_host, nz_host, active,
                np.int32(li), emask, escore,
            )
            hosts = np.asarray(hosts)
            feasible = np.asarray(feasible)
            li += B
            progressed = False
            for b in np.nonzero(active)[0]:
                if not feasible[b]:
                    active[b] = False  # truly unschedulable this cycle
                    continue
                n = int(hosts[b])
                req = pod_req[b]
                fits = not np.any(
                    (req > 0) & (req_host[n] + req > alloc[n])
                )
                want = _ports_of(pods, b)
                ok_ports = not _port_conflict(claimed_ports.get(n, ()), want)
                if fits and ok_ports:
                    hosts_out[b] = n
                    req_host[n] += req
                    nz_host[n] += pod_nz[b]
                    if want:
                        claimed_ports.setdefault(n, []).extend(want)
                    active[b] = False
                    progressed = True
                else:
                    # never re-pick the node that bounced you: progress
                    # guarantee for the next round
                    emask[b, n] = False
            if not progressed:
                break

        new_cluster = dataclasses.replace(
            cluster,
            requested=jnp.asarray(req_host),
            nonzero_req=jnp.asarray(nz_host),
        )
        return jnp.asarray(hosts_out), new_cluster

    return schedule
