"""Speculative parallel placement: the high-throughput engine.

The sequential-commit scan (models/batched.py) reproduces one-pod-at-a-time
semantics exactly, but a `lax.scan` step is latency-bound, so B pods cost B
sequential steps.  This engine places the WHOLE batch in one device launch:

  round r (all rounds run inside ONE jitted while_loop — no host round
  trips; on a tunnel-attached TPU a single device<->host sync costs ~50ms,
  so the round-1 design goal is zero syncs between upload and the final
  hosts fetch):
    1. mask/score every remaining pod against the current in-loop cluster
       state (filter_batch + score_batch over the pods x nodes grid — MXU
       work), argmax with per-pod staggered tie-break
       (ops/select.select_hosts_batch);
    2. commit on device, in batch order: pod b is accepted iff its proposed
       node still fits the resources of b PLUS every earlier same-node
       proposer this round, none of b's host ports conflict with ports
       already claimed on the node or wanted by an earlier same-node
       proposer, and (affinity batches) no earlier accepted proposer this
       round creates a required anti-affinity violation with b in a shared
       topology domain.  "Earlier same-node proposer" is a strictly-lower-
       triangle incidence product — the conflict-repair bookkeeping is a
       handful of small matmuls, not a host loop.  Rejected pods get
       emask[b, node] = False (progress: a pod never re-picks a node it was
       bounced from) and go to round r+1 against the updated columns.

In-batch REQUIRED (anti-)affinity (VERDICT r3 #3 — previously scan-only):
the carry holds the same per-topology-pair extras the sequential scan
threads through its steps (extra_aff/anti/forb/pref, the tensorization of
predicateMetadata.AddPod, ref algorithm/predicates/metadata.go:64-94),
batch-updated once per round from that round's accepted placements via
einsums over the BatchAffinityState cross-match tensors.  Two orderings
keep this faithful to the sequential semantics:
  * bootstrap gating: a pod whose required affinity term has no match
    anywhere may self-bootstrap ONLY if no earlier-in-batch pod that could
    satisfy the term is still pending — so one group founder places first
    and mates then co-locate in its domain, exactly as the one-at-a-time
    scan would, instead of the whole group scattering in round 1;
  * deferred retirement: a pod with no feasible node stays active while
    the round commits anything (its mates may land and open domains);
    retirement happens on the first commit-free round, which bounds the
    loop (every round commits >= 1 pod, clears >= 1 emask bit, or is the
    last).
Nominated pods (preemptors awaiting victims' exit) join the commit check:
claims from >=-priority nominated pods on the proposed node are added to
the fit test (podFitsOnNode pass one, ref generic_scheduler.go:598-664);
their port/anti-affinity pass-one effects arrive host-precomputed through
extra_mask (models/batched.py encode_nominated_block), shared with the
sequential engine.

The commit is slightly more conservative than a sequential host commit:
earlier proposers count against a node's budget even if they themselves end
up bounced on ports, so an accepted placement NEVER overcommits, but a pod
can be bounced a round earlier than strictly necessary (it simply re-picks
next round).  Every PREDICATE is enforced on the accepted state.  In-batch
score freshness: resource balance, spreading counts AND the inter-pod-
affinity score all refresh between rounds from the carry.

Transfer discipline (the tunnel bills per leaf AND per byte):
  * the PodBatch/port/affinity tensors are packed into three flat buffers
    (codec/transfer.py) — one RTT per dtype kind instead of ~60;
  * the cluster snapshot should be device-put ONCE by the caller and
    chained between batches (the returned new_cluster reuses the resident
    static leaves) — bench.py does; the scheduler runtime uploads through
    the encoder's incremental device-snapshot cache.

Termination: each round every active pod is accepted (retired), bounced
(clears one emask bit), or — on a commit-free round — retired infeasible;
bounded by B + B*N rounds.  Typical convergence: round 1 commits ~all pods
(staggered ties make collisions rare by construction) — ~1 parallel launch
per batch instead of B scan steps, the path to the >=10k pods/s north star
(BASELINE.json).

Reference for the semantics being reproduced at batch scale:
core/generic_scheduler.go Schedule (:184-254) / selectHost (:284-296);
the 16-goroutine scan it replaces is workqueue.ParallelizeUntil at :518.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubernetes_tpu.codec.schema import (
    ClusterTensors,
    DEFAULT_PRIORITY_WEIGHTS,
    FilterConfig,
    PodBatch,
    PRED_INDEX,
    PRIO_INDEX,
)
from kubernetes_tpu.codec.transfer import pack_tree, unpack_tree
from kubernetes_tpu.ops.predicates import filter_batch
from kubernetes_tpu.ops.priorities import (
    MAX_PRIORITY,
    pod_group_onehot,
    pod_spread_match,
    score_batch,
    spread_counts,
    spread_score_from_counts,
)
from kubernetes_tpu.ops.select import (
    TopKQuality,
    limit_feasible,
    num_feasible_nodes_device,
    select_hosts_batch,
    select_topk_batch,
)

_X = lax.Precision.HIGHEST  # exact f32 matmuls: these carry counts, not ML

# Test hook: route the CPU backend through the packed device path
# (_impl: device while_loop rounds + in-program lax.cond exactness redo)
# instead of the host-driven rounds, so the TPU program is testable on the
# CPU-only CI mesh.
FORCE_PACKED_PATH = False

from collections import OrderedDict

_SPEC_CACHE: "OrderedDict" = OrderedDict()
_SPEC_CACHE_CAP = 32  # bounds pinned executables (same policy as _SEQ_CACHE)


def make_speculative_scheduler(
    cfg: FilterConfig = FilterConfig(),
    weights=None,
    unsched_taint_key: int = 0,
    zone_key_id: int = 5,
    score_cfg=None,
    percentage_of_nodes_to_score: int = 100,
    hybrid: bool = True,
    donate_cluster: bool = False,
    quality_topk: int = 0,
):
    """Same call contract as make_sequential_scheduler:
    fn(cluster, pods, ports, last_index0, nominated=None, extra_mask=None,
    extra_score=None, aff_state=None) -> (hosts i32[B] (-1 unschedulable),
    new_cluster with committed requested/nonzero columns).  hosts is
    returned as a device array so the caller can overlap its fetch with the
    next batch's dispatch.

    Memoized by configuration (the _SEQ_CACHE policy): every Scheduler
    instance with the same knobs shares ONE jitted program, so e.g. the
    bench's raw-engine loop and its live-path Scheduler compile once.
    FORCE_PACKED_PATH is read per call, so the memo never staleness-locks
    the CPU test hook.

    quality_topk=K > 0 (STATIC, output-only — the placement-quality
    observatory seam, runtime/quality.py): the call returns
    (hosts, new_cluster, ops/select.TopKQuality) instead of the pair.
    Each pod's winner-pinned top-k rows + scores + feasible count are
    captured AT THE ROUND IT WAS ACCEPTED (so they reflect exactly the
    carry state its commit saw); the hybrid exactness redo returns the
    sequential scan's quality instead, so the pytree always describes
    the placements actually committed.  Winners are bit-identical
    flag-on/off (pinned by tests/test_quality.py).

    Buffer donation (accelerator device path only): the PACKED batch
    buffers — device_put fresh every call, dead after the launch — are
    always donated, so their HBM recycles into the while_loop carries and
    outputs.  donate_cluster=True additionally donates the cluster (the
    in-place chained-state pattern): correct only for callers that
    consume the returned new_cluster and never touch the input again
    (bench.py's raw loop); the live Scheduler's resident snapshot cache
    must NOT donate."""
    key = (
        cfg,
        tuple(np.asarray(weights, np.float32)) if weights is not None else None,
        unsched_taint_key,
        zone_key_id,
        score_cfg,
        percentage_of_nodes_to_score,
        hybrid,
        donate_cluster,
        quality_topk,
    )
    hit = _SPEC_CACHE.get(key)
    if hit is not None:
        _SPEC_CACHE.move_to_end(key)
        return hit
    w_all = np.asarray(
        DEFAULT_PRIORITY_WEIGHTS if weights is None else weights, np.float32
    )
    w_ipa = float(w_all[PRIO_INDEX["InterPodAffinityPriority"]])
    # affinity batches move the IPA score from score_batch's static pass
    # into the per-round dynamic evaluation (it must see in-batch commits)
    w_no_ipa = w_all.copy()
    w_no_ipa[PRIO_INDEX["InterPodAffinityPriority"]] = 0.0
    hard_w = float(cfg.hard_pod_affinity_weight)

    def _round(cluster, pods, pod_ports, conflict, escore, nom, aff, c):
        """One propose-and-commit round (shared by the on-device while_loop
        and the host-driven CPU loop).  nom: NominatedState or None;
        aff: BatchAffinityState, LeanBatchAffinity, or None (every entry
        point accepts the lean form and densifies it in _parts /
        densify_batch_affinity)."""
        B = pods.valid.shape[0]
        N = cluster.allocatable.shape[0]
        reqf = pods.req.astype(jnp.float32)
        nzf = pods.nonzero_req.astype(jnp.float32)
        pports = pod_ports.astype(jnp.bool_)
        pports_f = pod_ports.astype(jnp.float32)
        conflict_f = conflict.astype(jnp.float32)
        tril = jnp.tril(jnp.ones((B, B), jnp.float32), k=-1)
        cl = dataclasses.replace(
            cluster, requested=c["req"], nonzero_req=c["nz"]
        )
        if aff is not None:
            topo = cluster.topo_pairs.astype(jnp.float32)     # [N, TP]
            # topology-key -> pair-slot masks (cheap broadcasts; XLA CSEs
            # them across the uses below)
            aff_kp = (
                pods.aff_term_topo_key[:, :, None]
                == cluster.pair_topo_key[None, None]
            )                                                 # [B, PT, TP]
            anti_kp = (
                pods.anti_term_topo_key[:, :, None]
                == cluster.pair_topo_key[None, None]
            )                                                 # [B, AT, TP]
            # bootstrap gating: pod i may self-bootstrap term t only when
            # no EARLIER-in-batch pod that could satisfy t is still pending
            # (batch order = the order the sequential scan would commit);
            # the gate folds into aff_term_self, so the SHARED
            # MatchInterPodAffinity predicate (ops/predicates.py) evaluates
            # the unioned (pre-batch | in-batch) state unchanged
            earlier_alive = tril * c["active"].astype(jnp.float32)[None, :]
            cb = jnp.einsum(
                "jit,ij->it", aff.aff_match.astype(jnp.float32),
                earlier_alive, precision=_X,
            ) <= 0                                            # [B, PT]
            pods_eval = dataclasses.replace(
                pods,
                aff_term_pairs=pods.aff_term_pairs | c["xaff"],
                anti_term_pairs=pods.anti_term_pairs | c["xanti"],
                forbidden_pairs=pods.forbidden_pairs | c["xforb"],
                aff_term_self=pods.aff_term_self & cb,
            )
        else:
            pods_eval = pods
        mask, _ = filter_batch(cl, pods_eval, cfg, unsched_taint_key,
                               need_per=False)
        # spread freshness (VERDICT r2 item 6): counts refresh between
        # repair rounds exactly like resources — base snapshot counts plus
        # the in-batch commits accumulated in the carry, so same-batch
        # service mates repel from round 2 on instead of piling up until
        # the next cycle's snapshot
        lean_spread = pods.spread_counts.shape[-1] != N
        w_use = (w_no_ipa if aff is not None else w_all)
        if lean_spread:
            # lean batches (every pod in <= 1 spread group): the whole
            # SelectorSpread score is a function of the pod's GROUP, so
            # compute it once per group over [G, N] (G ~ tens) and
            # broadcast with a one-hot matmul — 10-20x less work than the
            # per-pod [B, N] evaluation the generic path does.  The carry
            # tracks in-batch commits at group granularity ("spread"
            # [G, N]), which for single-group pods is exactly the
            # pod_spread_match bookkeeping.
            counts_g = cluster.group_counts.T + c["spread"]   # [G, N]
            scores_g = spread_score_from_counts(
                counts_g, cluster, zone_key_id)               # [G, N]
            onehot_g = pod_group_onehot(
                pods, cluster.group_counts.shape[1])          # [B, G]
            has_g = jnp.any(onehot_g > 0, axis=-1)
            sp = jnp.matmul(onehot_g, scores_g, precision=_X)
            # a groupless pod has zero counts everywhere -> score 10
            sp = jnp.where(has_g[:, None], sp, MAX_PRIORITY)
            w_use = np.array(w_use, np.float32)
            w_spread = float(w_use[PRIO_INDEX["SelectorSpreadPriority"]])
            w_use[PRIO_INDEX["SelectorSpreadPriority"]] = 0.0
            pods_r = pods
        else:
            pods_r = dataclasses.replace(
                pods, spread_counts=spread_counts(cl, pods) + c["spread"]
            )
        total, _ = score_batch(
            cl, pods_r, weights=w_use,
            score_cfg=score_cfg, zone_key_id=zone_key_id,
            skip_zero_weight=True, need_per=False,
        )
        if lean_spread:
            total = total + w_spread * sp
        mask = mask & c["active"][:, None] & c["emask"] & pods.valid[:, None]
        if aff is not None:
            # dynamic IPA score (interpod_affinity.go fScore) over
            # (pre-batch | in-batch) raw pair weights, renormalized per pod
            raw = jnp.matmul(
                pods.pref_pair_weights + c["xpref"], topo.T, precision=_X
            )                                                 # [B, N]
            big = jnp.float32(3.4e38)
            mn = jnp.min(
                jnp.where(cluster.valid[None], raw, big), axis=1,
                keepdims=True,
            )
            mx = jnp.max(
                jnp.where(cluster.valid[None], raw, -big), axis=1,
                keepdims=True,
            )
            spr = mx - mn
            ipa = jnp.where(
                spr > 0, jnp.floor(MAX_PRIORITY * (raw - mn) / spr), 0.0
            )
            total = total + w_ipa * jnp.where(cluster.valid[None], ipa, 0.0)
        if percentage_of_nodes_to_score < 100:  # 0 = adaptive
            lim = num_feasible_nodes_device(
                jnp.sum(cl.valid.astype(jnp.int32)),
                percentage_of_nodes_to_score,
            )
            starts = c["li"] + jnp.arange(B, dtype=jnp.int32)
            mask = jax.vmap(limit_feasible, in_axes=(0, None, 0))(
                mask, lim, starts
            )
        if escore is not None:
            total = total + escore
        hosts, feasible = select_hosts_batch(total, mask, c["li"])
        prop = c["active"] & feasible            # proposers this round
        # earlier same-node proposers: an equality comparison masked by
        # the strict lower triangle (batch order = commit order) — B^2
        # elementwise work, NOT a [B,N] incidence matmul, so the commit
        # bookkeeping stays cheap on the CPU fallback too
        same = (
            (hosts[:, None] == hosts[None, :])
            & prop[:, None] & prop[None, :]
        )
        prior = same.astype(jnp.float32) * tril              # [B, B]
        cum_req = jnp.matmul(prior, reqf, precision=_X)      # [B, R]
        node_req = c["req"][hosts]                           # [B, R]
        alloc_h = cluster.allocatable[hosts]
        if nom is not None:
            # podFitsOnNode pass one: nominated pods with priority >= this
            # pod's claim resources on their nominated node (resource fit
            # is monotone, so pass one implies the no-nominated pass two)
            nw = (
                (nom.prio[None, :] >= pods.priority[:, None])
                & (nom.node[None, :] >= 0)
                & (nom.node[None, :] == hosts[:, None])
            ).astype(jnp.float32)                            # [B, K]
            nom_extra = jnp.matmul(nw, nom.req, precision=_X)  # [B, R]
        else:
            nom_extra = jnp.float32(0.0)
        over = (reqf > 0) & (node_req + cum_req + nom_extra + reqf > alloc_h)
        fits = ~jnp.any(over, axis=1)
        # ports: conflict with claims already on the node OR with an
        # earlier same-node proposer's wanted ports
        prior_ports = jnp.matmul(prior, pports_f, precision=_X) > 0
        claimed_h = c["claimed"][hosts]                      # [B, PV]
        blocked = jnp.matmul(
            (claimed_h | prior_ports).astype(jnp.float32),
            conflict_f, precision=_X,
        ) > 0
        pconf = jnp.any(pports & blocked, axis=1)
        accept = prop & fits & ~pconf
        if aff is not None:
            # same-round required-anti ordering: pod b is rejected when an
            # earlier proposer j shares a topology domain with b under one
            # of b's anti terms (j matches the term) or one of j's anti
            # terms (b matches it).  D[o, t, c] = "candidate c's proposed
            # node is in owner o's term-t domain at o's proposed node".
            H = topo[hosts]                                   # [B, TP]
            a_own = anti_kp.astype(jnp.float32) * H[:, None, :]  # [B, AT, TP]
            D = jnp.einsum("otp,cp->otc", a_own, H, precision=_X) > 0
            # am1[b, t, j] = "pod j matches pod b's required anti term t"
            am1 = jnp.transpose(aff.anti_match, (1, 2, 0))    # [B, AT, B]
            v1 = jnp.any(D & am1, axis=1)                     # [b, j]
            v2 = jnp.any(D & aff.anti_own, axis=1)            # [j, b]
            conf_ba = v1 | v2.T                               # [b, j]
            earlier_prop = (tril > 0) & prop[None, :]
            aviol = jnp.any(conf_ba & earlier_prop, axis=1)
            accept = accept & ~aviol
        # ---- hybrid exactness sentinel (VERDICT r4 #3): the engine's only
        # semantic divergence from the one-at-a-time scan is ORDER
        # INVERSION — a later pod committing while an earlier pod is
        # passed over (bounced or still infeasible), where the commit can
        # INTERFERE with what the earlier pod would have gotten
        # one-at-a-time.  Interference = j's accepted node was feasible
        # for i this round (capacity/ports race), or i and j are related
        # through required (anti-)affinity terms in either direction
        # (domain races, including a later mate opening a domain the scan
        # would never have opened for i).  When the flag trips, schedule()
        # discards the speculative result and redoes the batch through
        # the exact sequential scan — so the scheduled/unschedulable
        # split always matches scan semantics.  Orderly multi-round
        # convergence (founder-then-mates bootstrap chains) does NOT trip
        # it: gated mates are infeasible (empty mask row) and unrelated
        # to other groups' founders.
        if aff is not None:
            passed_over = c["active"] & ~accept          # [i]
            later = tril.T > 0                           # [i, j]: j > i
            interf = mask[:, hosts]                      # [i, j] = mask[i, host_j]
            a_any = jnp.any(aff.aff_match, axis=2)       # [x, y]: x sats y's aff
            n_any = jnp.any(aff.anti_match, axis=2)      # [x, y]: x matches y's anti
            rel = a_any | a_any.T | n_any | n_any.T      # either direction
            interf = interf | rel
            inv_new = jnp.any(
                passed_over[:, None] & accept[None, :] & later & interf
            )
        else:
            # plain batches: the inversion term is subsumed by the other
            # two sentinels, so skip its [B, B] work on the hot path.
            # Invariant: a passed-over pod is either infeasible this
            # round (it retires with hosts=-1 -> the unscheduled sentinel
            # fires) or bounced — and in any round with a bounce, the
            # EARLIEST bounced proposer on that node has only accepted
            # pods before it (prior_acc == prior for it), so its bounce
            # is a real_bounce and that sentinel fires.  This subsumption
            # argument does NOT carry to affinity batches (aviol bounces
            # are excluded from real_bounce; domain openings retire
            # nothing), which keep the full inversion term above.
            inv_new = jnp.asarray(False)
        accf = accept[:, None].astype(jnp.float32)
        # the accept pass is conservative (earlier proposers count even
        # if they themselves bounce), which never overcommits but can
        # bounce a pod that would fit the truly-accepted state.  Only
        # ban the node (emask clear) when the bounce ALSO holds against
        # accepted-only prior state — a conservatively-bounced pod keeps
        # the node and retries next round.
        prior_acc = prior * accept[None, :].astype(jnp.float32)
        cum_acc = jnp.matmul(prior_acc, reqf, precision=_X)
        over_acc = (reqf > 0) & (node_req + cum_acc + nom_extra + reqf > alloc_h)
        fits_acc = ~jnp.any(over_acc, axis=1)
        prior_ports_acc = jnp.matmul(prior_acc, pports_f, precision=_X) > 0
        blocked_acc = jnp.matmul(
            (claimed_h | prior_ports_acc).astype(jnp.float32),
            conflict_f, precision=_X,
        ) > 0
        pconf_acc = jnp.any(pports & blocked_acc, axis=1)
        real_bounce = prop & ~accept & (~fits_acc | pconf_acc)
        if aff is not None:
            # an anti-violation against an ACCEPTED peer needs no emask
            # ban: next round's xanti/xforb exclude the whole domain
            aviol_acc = jnp.any(
                conf_ba & (tril > 0) & accept[None, :], axis=1
            )
            real_bounce = real_bounce & ~aviol_acc
        acc_node = accf * (
            hosts[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :]
        ).astype(jnp.float32)                                # [B, N]
        if lean_spread:
            # group-granular commit counts ([G, N] carry)
            spread_next = c["spread"] + jnp.matmul(
                onehot_g.T, acc_node, precision=_X)
        else:
            # the SAME AND-subset match the sequential scan uses
            # (ops/priorities.py pod_spread_match)
            spread_match = pod_spread_match(
                pods, cluster.group_counts.shape[1])         # [B, B] [i, j]
            spread_next = c["spread"] + jnp.matmul(
                spread_match, acc_node, precision=_X)
        # committed state lands via scatter-add on the node axis (a
        # segment-sum; XLA lowers it to a cheap scatter on every
        # backend, where the old one_hot.T matmuls cost B*N*R flops)
        out = {
            "hosts": jnp.where(accept, hosts, c["hosts"]),
            "req": c["req"].at[hosts].add(reqf * accf),
            "nz": c["nz"].at[hosts].add(nzf * accf),
            "spread": spread_next,
            "claimed": c["claimed"].at[hosts].max(
                pports & accept[:, None]
            ),
            # really-bounced proposers never re-pick the node that
            # bounced them (progress: the first active proposer of any
            # contended node is always accepted or really bounced)
            "emask": c["emask"] & ~(
                real_bounce[:, None]
                & (jnp.arange(N, dtype=jnp.int32)[None, :]
                   == hosts[:, None])
            ),
            "li": c["li"] + jnp.int32(B),
            # the three contention signals the hybrid redo triggers on
            # (see schedule()): order inversion with interference, any
            # REAL capacity/port bounce (under pressure, round-1
            # simultaneity alone can change the packing — different
            # tie-break SETS — without any pod being passed over), and
            # any pod left unscheduled (checked host-side on the result)
            "inv": c["inv"] | inv_new | jnp.any(real_bounce),
        }
        if quality_topk:
            # quality top-k (static output-only flag): capture each
            # accepted pod's winner-pinned ranking + feasible count AT
            # ITS COMMIT ROUND, off the exact (mask, total, hosts) the
            # acceptance above used; bounced/pending pods keep -1 until
            # their round, retired-infeasible pods keep -1 forever
            qb = select_topk_batch(
                total, mask, hosts, feasible, min(quality_topk, N)
            )
            upd = accept[:, None]
            out["topn"] = jnp.where(upd, qb.top_nodes, c["topn"])
            out["tops"] = jnp.where(upd, qb.top_scores, c["tops"])
            out["feas"] = jnp.where(accept, qb.feasible, c["feas"])
        if aff is None:
            # retired: accepted, or nothing feasible this round
            out["active"] = c["active"] & feasible & ~accept
        else:
            # deferred retirement: while the round commits anything, an
            # infeasible pod stays active (a mate's landing may open its
            # domain next round).  A commit-free round retires only the
            # FIRST infeasible pod in batch order — exactly the pod the
            # sequential scan would fail next — so a later founder whose
            # bootstrap was gated by that pod gets its round with the
            # blocker finally dead instead of being mass-retired with it.
            any_acc = jnp.any(accept)
            inf = c["active"] & ~feasible
            first_inf = inf & (jnp.cumsum(inf.astype(jnp.int32)) == 1)
            out["active"] = (
                (c["active"] & feasible & ~accept)
                | jnp.where(any_acc, inf, inf & ~first_inf)
            )
            # predicateMetadata.AddPod analog, batched over this round's
            # accepted placements: their topology pairs flow into the
            # pending pods' affinity state for the next round
            accN = accf * H                                   # [B(j), TP]
            am_f = aff.aff_match.astype(jnp.float32)
            nm_f = aff.anti_match.astype(jnp.float32)
            out["xaff"] = c["xaff"] | (
                (jnp.einsum("jit,jp->itp", am_f, accN, precision=_X) > 0)
                & aff_kp
            )
            out["xanti"] = c["xanti"] | (
                (jnp.einsum("jit,jp->itp", nm_f, accN, precision=_X) > 0)
                & anti_kp
            )
            keyed_anti = anti_kp.astype(jnp.float32) * accN[:, None, :]
            out["xforb"] = c["xforb"] | (
                jnp.einsum(
                    "jti,jtp->ip", aff.anti_own.astype(jnp.float32),
                    keyed_anti, precision=_X,
                ) > 0
            )
            keyed_aff = aff_kp.astype(jnp.float32) * accN[:, None, :]
            xpref = c["xpref"] + hard_w * jnp.einsum(
                "jti,jtp->ip", aff.aff_own.astype(jnp.float32), keyed_aff,
                precision=_X,
            )
            # preferred (soft) terms, both directions (scan parity):
            # 1. pending pods' own preferred terms the accepted pods match
            pref_kp = (
                aff.pref_topo_key[:, :, None]
                == cluster.pair_topo_key[None, None]
            )                                                 # [B, PP, TP]
            m1 = jnp.einsum(
                "jit,jp->itp", aff.pref_match.astype(jnp.float32), accN,
                precision=_X,
            )
            xpref = xpref + jnp.sum(
                m1 * aff.pref_weight[:, :, None]
                * pref_kp.astype(jnp.float32),
                axis=1,
            )
            # 2. the accepted pods' preferred terms add +-w per matching
            #    pending pod over the landing domain
            keyed_pref = pref_kp.astype(jnp.float32) * accN[:, None, :]
            xpref = xpref + jnp.einsum(
                "jti,jt,jtp->ip", aff.pref_own.astype(jnp.float32),
                aff.pref_weight, keyed_pref, precision=_X,
            )
            out["xpref"] = xpref
        return out

    def _init_carry(cluster, pods, pod_ports, last_index0, emask0, has_aff):
        B = pods.valid.shape[0]
        N = cluster.allocatable.shape[0]
        # lean batches carry in-batch spread commits per GROUP (see _round)
        lean_spread = pods.spread_counts.shape[-1] != N
        S = cluster.group_counts.shape[1] if lean_spread else B
        c = {
            "hosts": jnp.full((B,), -1, jnp.int32),
            "req": cluster.requested.astype(jnp.float32),
            "nz": cluster.nonzero_req.astype(jnp.float32),
            "spread": jnp.zeros((S, N), jnp.float32),
            "claimed": jnp.zeros((N, pod_ports.shape[1]), jnp.bool_),
            "emask": emask0,
            "active": pods.valid,
            "li": jnp.asarray(last_index0, jnp.int32),
            "inv": jnp.asarray(False),
        }
        if has_aff:
            TP = cluster.topo_pairs.shape[1]
            PT = pods.aff_term_pairs.shape[1]
            AT = pods.anti_term_pairs.shape[1]
            c["xaff"] = jnp.zeros((B, PT, TP), jnp.bool_)
            c["xanti"] = jnp.zeros((B, AT, TP), jnp.bool_)
            c["xforb"] = jnp.zeros((B, TP), jnp.bool_)
            c["xpref"] = jnp.zeros((B, TP), jnp.float32)
        if quality_topk:
            tkq = min(quality_topk, N)
            c["topn"] = jnp.full((B, tkq), -1, jnp.int32)
            c["tops"] = jnp.zeros((B, tkq), jnp.float32)
            c["feas"] = jnp.zeros((B,), jnp.int32)
        return c

    def _parts(tree):
        from kubernetes_tpu.models.batched import (
            LeanBatchAffinity,
            densify_batch_affinity,
        )

        pods = tree["pods"]
        aff = tree.get("aff")
        if isinstance(aff, LeanBatchAffinity):
            # only the factors crossed the link; rebuild the dense
            # cross-match tensors on device (one gather per family).
            # _parts is the single chokepoint every jitted path
            # (_packed, _round_host, _carry_init) funnels through.
            aff = densify_batch_affinity(aff)
        return (
            pods, tree["pp"], tree["cf"], tree.get("emask"),
            tree.get("escore"), tree.get("nom"), aff,
        )

    def _impl(cluster, tree, last_index0):
        pods, pod_ports, conflict, emask0, escore, nom, aff = _parts(tree)
        B = pods.valid.shape[0]
        N = cluster.allocatable.shape[0]
        if emask0 is None:
            emask0 = jnp.ones((B, N), jnp.bool_)
        else:
            emask0 = emask0.astype(jnp.bool_)
        init = _init_carry(
            cluster, pods, pod_ports, last_index0, emask0, aff is not None
        )
        out = lax.while_loop(
            lambda c: jnp.any(c["active"]),
            lambda c: _round(
                cluster, pods, pod_ports, conflict, escore, nom, aff, c
            ),
            init,
        )
        rounds = (out["li"] - jnp.asarray(last_index0, jnp.int32)) // B
        # third contention sentinel, ON DEVICE: a pod left unscheduled
        # means capacity/domain pressure, under which any placement
        # difference can change the split
        inv = out["inv"] | jnp.any(pods.valid & (out["hosts"] < 0))
        if hybrid:
            # device-resident exactness redo: fold the sequential-scan
            # fallback into the SAME program behind lax.cond (XLA executes
            # only the taken branch), so the caller never syncs on the
            # sentinel — the old host-side bool(np.asarray(inv)) check
            # serialized the whole pipeline on device compute + a scalar
            # D2H RTT every batch.  Uncontended batches pay one predicate;
            # contended ones run the exact scan on device.
            from kubernetes_tpu.models.batched import BatchPortState

            # .jitted = the raw traceable fn (schedule_entry's host-side
            # device_put wrapper must not run inside this traced branch)
            seq = _exact_scan().jitted
            ports_state = BatchPortState(pod_ports, conflict)

            def _redo(_):
                souts = seq(
                    cluster, pods, ports_state, last_index0, nom,
                    emask0, escore, aff,
                )
                h2, c2 = souts[0], souts[1]
                base = (
                    h2.astype(jnp.int32),
                    c2.requested.astype(jnp.float32),
                    c2.nonzero_req.astype(jnp.float32),
                )
                if quality_topk:
                    # the redo's quality describes the placements
                    # actually committed (the scan's), same widths by
                    # construction (same N, same static K)
                    q2 = souts[2]
                    base = base + (q2.top_nodes, q2.top_scores, q2.feasible)
                return base

            def _keep(_):
                base = (
                    out["hosts"].astype(jnp.int32),
                    out["req"].astype(jnp.float32),
                    out["nz"].astype(jnp.float32),
                )
                if quality_topk:
                    base = base + (out["topn"], out["tops"], out["feas"])
                return base

            picked = lax.cond(inv, _redo, _keep, None)
            hosts, req, nz = picked[:3]
            qual = TopKQuality(*picked[3:]) if quality_topk else None
            return hosts, req, nz, rounds, inv, qual
        qual = (
            TopKQuality(out["topn"], out["tops"], out["feas"])
            if quality_topk else None
        )
        return out["hosts"], out["req"], out["nz"], rounds, inv, qual

    @lru_cache(maxsize=64)
    def _packed(meta):
        def run(cluster, bufs, last_index0):
            tree = unpack_tree(bufs, meta)
            hosts, req, nz, rounds, inv, qual = _impl(
                cluster, tree, last_index0
            )
            # new_cluster is assembled INSIDE the jit so that under
            # donation the untouched static leaves alias input->output
            # (identity) and req/nz land in the donated buffers — the
            # in-place chained-state update
            new_cluster = dataclasses.replace(
                cluster, requested=req, nonzero_req=nz
            )
            return hosts, new_cluster, rounds, inv, qual

        # the packed batch buffers (argnum 1) are dead after the launch by
        # construction (schedule() re-packs + re-uploads every call);
        # cluster donation is the maker's opt-in for chained-state
        # callers.  XLA:CPU implements no donation (FORCE_PACKED_PATH
        # tests run this path on cpu) — plain jit there avoids per-call
        # donation warnings.
        donate: tuple = ()
        if jax.default_backend() != "cpu":
            donate = (0, 1) if donate_cluster else (1,)
        return jax.jit(run, donate_argnums=donate)

    # ---- CPU path: host-driven rounds.  XLA:CPU executes while_loop bodies
    # without intra-op thread parallelism, so the SAME round as a
    # free-standing jit runs ~8x faster on the multicore host; the handful
    # of tiny host syncs per batch are free without a tunnel.

    @lru_cache(maxsize=64)
    def _materialize(meta):
        """Unpack + densify ONCE per batch: the per-round jits below take
        the materialized parts pytree directly, so the lean affinity
        state's dense reconstruction doesn't repeat every repair round."""

        @jax.jit
        def run(bufs):
            return _parts(unpack_tree(bufs, meta))

        return run

    @jax.jit
    def _round_host(cluster, parts, c):
        pods, pod_ports, conflict, _em, escore, nom, aff = parts
        return _round(
            cluster, pods, pod_ports, conflict, escore, nom, aff, c
        )

    @jax.jit
    def _carry_init(cluster, parts, last_index0):
        pods, pod_ports, _cf, emask0, _es, _nom, aff = parts
        B = pods.valid.shape[0]
        N = cluster.allocatable.shape[0]
        if emask0 is None:
            emask0 = jnp.ones((B, N), jnp.bool_)
        else:
            emask0 = emask0.astype(jnp.bool_)
        return _init_carry(
            cluster, pods, pod_ports, last_index0, emask0, aff is not None
        )

    def _host_rounds(cluster, bufs, meta, last_index0):
        parts = _materialize(meta)(bufs)
        c = _carry_init(cluster, parts, np.int32(last_index0))
        rounds = 0
        while bool(np.asarray(c["active"]).any()):
            c = _round_host(cluster, parts, c)
            rounds += 1
        qual = (
            TopKQuality(c["topn"], c["tops"], c["feas"])
            if quality_topk else None
        )
        return c["hosts"], c["req"], c["nz"], rounds, c["inv"], qual

    def _exact_scan():
        """The memoized sequential scan both redo paths share (in-_impl
        lax.cond on device, host-side redo on CPU) — one construction
        site so the two cannot diverge.  make_sequential_scheduler is
        _SEQ_CACHE-memoized, so calling per redo costs nothing."""
        from kubernetes_tpu.models.batched import make_sequential_scheduler

        return make_sequential_scheduler(
            cfg=cfg, weights=weights,
            unsched_taint_key=unsched_taint_key,
            zone_key_id=zone_key_id, score_cfg=score_cfg,
            percentage_of_nodes_to_score=percentage_of_nodes_to_score,
            quality_topk=quality_topk,
        )

    def schedule(cluster: ClusterTensors, pods: PodBatch, ports,
                 last_index0, nominated=None, extra_mask=None,
                 extra_score=None, aff_state=None):
        on_cpu = jax.default_backend() == "cpu" and not FORCE_PACKED_PATH
        tree = {"pods": pods, "pp": ports.pod_ports, "cf": ports.conflict}
        if extra_mask is not None:
            tree["emask"] = np.asarray(extra_mask, bool)
        if extra_score is not None:
            tree["escore"] = np.asarray(extra_score, np.float32)
        if nominated is not None:
            tree["nom"] = nominated
        if aff_state is not None:
            tree["aff"] = aff_state
        # the optional extras ride the same packed buffers (<=3 RTTs); the
        # tree's key set is part of meta, so each combination jits once
        bufs, meta = pack_tree(tree)
        if not on_cpu:
            # explicit async DMA: host-numpy jit ARGUMENTS cross the
            # remote-attached tunnel on a slow synchronous path (~55MB/s
            # measured vs ~1.4GB/s for device_put), which stalled every
            # affinity batch ~2s on its [B, ., B] cross-match tensors.
            # A mesh-sharded cluster (multi-chip live path) pins the
            # launch to its mesh: the packed buffers replicate there
            # instead of committing to device 0 (which would conflict).
            from kubernetes_tpu.parallel.mesh import (
                replicated_on_cluster_mesh,
            )

            from kubernetes_tpu.codec.transfer import note_transfer_tree

            note_transfer_tree("h2d", "batch_replicate", bufs)
            dst = replicated_on_cluster_mesh(cluster)
            bufs = (
                jax.device_put(bufs, dst)
                if dst is not None else jax.device_put(bufs)
            )
        if on_cpu:
            hosts, req, nz, rounds, inv, qual = _host_rounds(
                cluster, bufs, meta, last_index0
            )
        else:
            hosts, new_cluster, rounds, inv, qual = _packed(meta)(
                cluster, bufs, np.int32(last_index0)
            )
            # the exactness redo already ran ON DEVICE behind lax.cond
            # (_impl), so nothing here syncs: hosts/new_cluster are final
            # and the pipeline stays fully async.  last_redo is the device
            # sentinel scalar — fetching it (bool()/int()) blocks on the
            # batch, so only observability/tests should touch it.
            schedule.last_rounds = rounds
            schedule.last_redo = inv if hybrid else False
            if quality_topk:
                return hosts, new_cluster, qual
            return hosts, new_cluster
        schedule.last_rounds = rounds  # observability: repair rounds used
        schedule.last_redo = False
        if hybrid and not bool(np.asarray(inv)):
            # CPU path (host-driven rounds): the unscheduled-pod sentinel
            # is checked host-side — hosts are already host-resident and
            # syncs are free without a tunnel
            hn = np.asarray(hosts)
            valid = np.asarray(pods.valid, bool)
            inv = bool((hn[valid] < 0).any())
        if hybrid and bool(np.asarray(inv)):
            # order inversion with interference detected: the split could
            # deviate from one-at-a-time semantics, so redo the WHOLE
            # batch through the exact sequential scan (the speculative
            # commits above never touched the caller's cluster).  This
            # costs one scan on the contended batches only — uncontended
            # batches (the common case: round 1 commits everything, or
            # orderly founder->mates chains) keep the parallel fast path.
            # With quality on the scan's own TopKQuality rides along as
            # the third output — same arity either way.
            schedule.last_redo = True
            return _exact_scan()(
                cluster, pods, ports, last_index0, nominated,
                extra_mask, extra_score, aff_state,
            )
        new_cluster = dataclasses.replace(cluster, requested=req, nonzero_req=nz)
        if quality_topk:
            return hosts, new_cluster, qual
        return hosts, new_cluster

    # engine identity tag (see models/batched.py): multi-round placement
    # with repair — NOT sequential-commit ordered; gang scheduling's
    # cross-gang drop guard must never run on this engine
    schedule.engine_kind = "speculative"
    # the raw traceable device path (while_loop rounds + in-program
    # exactness redo) for callers composing INSIDE jit — the megacycle
    # driver (models/megacycle.py) scans it over K chained batches.
    # Signature: _impl(cluster, {"pods","pp","cf",...}, last_index0) ->
    # (hosts, req, nz, rounds, inv, quality-or-None)
    schedule.raw_impl = _impl
    # quality variants return (hosts, new_cluster, TopKQuality); 0 = off
    schedule.quality_topk = quality_topk
    _SPEC_CACHE[key] = schedule
    while len(_SPEC_CACHE) > _SPEC_CACHE_CAP:
        _SPEC_CACHE.popitem(last=False)
    return schedule
